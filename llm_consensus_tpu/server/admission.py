"""Admission control: bounded per-priority queues, shedding, deadlines, drain.

The reference accepts unbounded concurrent work — every HTTP request
spawns a future immediately (``src/main.rs:101,156,182``), so overload
manifests as memory growth and collapse instead of backpressure. This
module is the opposite contract, the one every production serving stack
makes explicit:

- **Bounded queues, one per priority.** When a priority's queue is full
  the request is SHED at the door (:class:`QueueFullError` -> the
  gateway's ``429`` + ``Retry-After``) instead of admitted into an
  ever-deeper backlog. Dispatch drains strictly by priority order.
- **Deadlines.** A request may carry a deadline; if it expires while
  still queued the work is cancelled before it ever touches the backend
  (:class:`DeadlineExpiredError` -> ``504``), and an admitted request's
  backend call runs under ``asyncio.wait_for`` with the remaining
  budget so in-flight work is cancelled at the deadline too.
- **Graceful drain.** :meth:`AdmissionController.drain` stops admitting
  (:class:`DrainingError` -> ``503``) and waits for every
  already-admitted request — queued and in-flight — to reach its
  terminal outcome. The gateway calls it on SIGTERM.

Single-event-loop asyncio; the controller owns a dispatcher task with a
bounded in-flight window (``max_inflight``) so the backend sees at most
a fixed number of concurrent batch calls regardless of queue depth.

Every transition feeds the metrics registry: queue depth gauges,
admitted/shed/expired/completed counters (all labeled by priority), and
queue-wait histograms — the series the overload integration test
cross-checks against observed HTTP outcomes.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from collections.abc import Awaitable, Callable
from dataclasses import dataclass, field

from llm_consensus_tpu.server import metrics as _metrics
from llm_consensus_tpu.utils import tracing as _tracing

log = logging.getLogger(__name__)

__all__ = [
    "AdmissionConfig",
    "AdmissionController",
    "DeadlineExpiredError",
    "DrainingError",
    "QueueFullError",
]


class QueueFullError(Exception):
    """Load shed: the request's priority queue is at its bound."""

    def __init__(self, priority: str, retry_after: float):
        super().__init__(
            f"{priority} queue full; retry after {retry_after:.1f}s"
        )
        self.priority = priority
        self.retry_after = retry_after


class DrainingError(Exception):
    """The controller is draining (SIGTERM): no new admissions."""


class DeadlineExpiredError(Exception):
    """The request's deadline passed before the work completed."""


@dataclass
class AdmissionConfig:
    # Priority order = dispatch order: the first listed priority drains
    # first. Every request names one of these.
    priorities: tuple[str, ...] = ("interactive", "batch")
    # Per-priority queue bound; an int applies to every priority, a dict
    # overrides per name.
    max_queue: int | dict[str, int] = 64
    # Concurrent in-flight executions across all priorities. The backend
    # underneath batches, so a handful of concurrent generate_batch
    # calls keeps the chip full without unbounded task fan-out.
    max_inflight: int = 8
    # Deadline applied when a request does not carry one; None = none.
    default_deadline_s: float | None = None
    # Retry-After hint returned on shed when the queue-wait history is
    # still empty.
    retry_after_s: float = 1.0
    # Hard ceiling on overflow admission (PR 14): a granting
    # overflow_hook stretches a priority's queue bound by at most this
    # factor — preemption absorbs storms, it never REMOVES
    # backpressure (a stale preempt signal + a mega-storm must
    # eventually shed fast 429s instead of queueing requests to
    # deadline death and growing queue memory with offered load).
    # UNIT NORMALIZATION (PR 15): the factor multiplies whatever unit
    # the bound itself uses — requests in classic mode, MODELED BYTES
    # in cost-budget mode — so the hard-cap path can never again mix
    # a bytes-denominated preempt signal with a request-count cap.
    max_overflow_factor: int = 16
    # Cost-budget admission (PR 15): > 0 switches every queue bound
    # from request COUNTS to MODELED BYTES — the same unit the fleet
    # router's load_cost compares and ContinuousBatcher.
    # modeled_request_cost prices (a 32k-context request is not one
    # unit of work). Each submit carries its modeled cost; a request
    # without one is priced at one nominal slot
    # (budget / bound_for(priority)). 0 (default) = classic
    # request-count bounds.
    cost_budget_bytes: float = 0.0

    def bound_for(self, priority: str) -> int:
        if isinstance(self.max_queue, dict):
            return int(self.max_queue.get(priority, 64))
        return int(self.max_queue)


@dataclass
class _Item:
    thunk: Callable[[], Awaitable]
    priority: str
    deadline: float | None  # monotonic seconds, None = no deadline
    enqueued_at: float
    future: asyncio.Future = field(default_factory=asyncio.Future)
    # Request trace captured at submit: the dispatcher's _run task has
    # its own contextvars context (it is NOT a child of the submitter),
    # so the trace must ride the item and be re-installed around the
    # thunk (tracing.use_trace) for downstream spans to attach.
    trace: object | None = None
    # Modeled cost in bytes (PR 15, cost-budget mode): charged to the
    # priority's queue-cost account while queued, released at dispatch
    # or expiry. 0 in classic request-count mode.
    cost: float = 0.0


class AdmissionController:
    """Bounded-queue dispatcher between the gateway and a backend."""

    def __init__(
        self,
        config: AdmissionConfig | None = None,
        registry: _metrics.MetricsRegistry | None = None,
    ):
        self.config = config or AdmissionConfig()
        if not self.config.priorities:
            raise ValueError("need at least one priority")
        reg = registry or _metrics.REGISTRY
        self._queues: dict[str, deque[_Item]] = {
            p: deque() for p in self.config.priorities
        }
        # Modeled bytes queued per priority (PR 15 cost-budget mode):
        # charged at append, released at every popleft site — the
        # bound AND the overflow hard cap read this one account, so
        # the two can never drift units.
        self._queue_cost: dict[str, float] = {
            p: 0.0 for p in self.config.priorities
        }
        self._inflight = 0
        self._draining = False
        # Overload overflow hook (PR 14): consulted at a queue-full
        # moment BEFORE shedding. Returning True admits the request
        # past the bound — the fleet's preempt-to-host-tier path
        # (ReplicaSet.preempt_for_admission) frees backend capacity by
        # demoting resident KV chains instead of 429ing, so an
        # overload storm degrades to restore latency, not lost work.
        # The hook must be cheap and non-blocking (it runs on the
        # event loop inside submit) and is expected to become False
        # once nothing is left to preempt — that, not the queue bound,
        # is then the shed condition. None (default) = classic shed.
        # CHEAPNESS CONTRACT with remote stores (PR 16): the fleet's
        # hook reads the page store's headroom to decide whether
        # demotion can still land pages. A RemotePageStore serves that
        # read from its last piggybacked stats snapshot — NEVER a
        # network round-trip — precisely because this call sits on the
        # event loop at peak overload. A store outage therefore reads
        # as zero headroom (hook returns False) and overload degrades
        # to the classic 429 shed, not a wedged submit path.
        self.overflow_hook: Callable[[], bool] | None = None
        self._work = asyncio.Event()
        self._idle = asyncio.Event()
        self._idle.set()
        self._dispatcher: asyncio.Task | None = None
        self._m_depth = reg.gauge(
            "gateway_queue_depth", "Requests waiting for admission"
        )
        self._m_inflight = reg.gauge(
            "gateway_inflight", "Requests currently executing"
        )
        self._m_admitted = reg.counter(
            "gateway_admitted_total", "Requests accepted into a queue"
        )
        self._m_shed = reg.counter(
            "gateway_shed_total", "Requests shed with 429 (queue full)"
        )
        self._m_expired = reg.counter(
            "gateway_deadline_expired_total",
            "Requests that hit their deadline before completing",
        )
        self._m_completed = reg.counter(
            "gateway_completed_total",
            "Admitted requests that reached a terminal outcome",
        )
        self._m_wait = reg.histogram(
            "gateway_queue_wait_seconds",
            "Time from admission to dispatch",
        )
        self._m_cost = reg.gauge(
            "gateway_queue_cost_bytes",
            "Modeled bytes waiting for admission (cost-budget mode)",
        )

    # -- admission ------------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    def pending(self) -> int:
        """Admitted-but-unfinished request count (queued + in-flight)."""
        return sum(len(q) for q in self._queues.values()) + self._inflight

    async def submit(
        self,
        thunk: Callable[[], Awaitable],
        *,
        priority: str | None = None,
        deadline_s: float | None = None,
        cost: float | None = None,
    ):
        """Admit ``thunk`` and await its terminal outcome.

        Raises :class:`DrainingError` / :class:`QueueFullError` at the
        door, :class:`DeadlineExpiredError` when the deadline passes
        (queued or in-flight), else returns/raises whatever the awaited
        thunk does.

        ``cost`` (PR 15): the request's modeled bytes
        (``ContinuousBatcher.modeled_request_cost`` — the unit
        ``load_cost`` routes on). Read only in cost-budget mode
        (``AdmissionConfig.cost_budget_bytes > 0``), where the queue
        bound, the overflow hard cap, and the shed decision all
        compare in modeled bytes; a costless submit is priced at one
        nominal slot (budget / bound) so legacy callers keep
        approximately the classic depth bound.
        """
        prio = priority or self.config.priorities[0]
        q = self._queues.get(prio)
        if q is None:
            raise ValueError(
                f"unknown priority {prio!r}; have {self.config.priorities}"
            )
        if self._draining:
            raise DrainingError("gateway is draining; not admitting")
        bound = self.config.bound_for(prio)
        budget = self.config.cost_budget_bytes
        factor = self.config.max_overflow_factor
        if budget > 0:
            # Cost-budget mode: bound and hard cap in ONE unit,
            # modeled bytes — a 32k-context request charges what it
            # costs, N small ones fit where one huge one would not.
            # An EMPTY queue always admits (classic mode's invariant):
            # the budget bounds the BACKLOG, never a single request's
            # size — a request whose lone modeled cost exceeds the
            # budget must not be unservable forever on an idle
            # gateway.
            if cost is None or cost <= 0:
                cost = budget / max(1, bound)
            queued = self._queue_cost[prio]
            over = len(q) > 0 and queued + cost > budget
            capped = len(q) > 0 and queued + cost > budget * factor
        else:
            cost = 0.0
            over = len(q) >= bound
            capped = len(q) >= bound * factor
        if over:
            hook = self.overflow_hook
            preempted = False
            if hook is not None and not capped:
                try:
                    preempted = bool(hook())
                except Exception:  # noqa: BLE001 - hook must not 500
                    log.exception("admission overflow hook failed")
            if not preempted:
                self._m_shed.labels(priority=prio).inc()
                raise QueueFullError(prio, self._retry_after_hint())
        if deadline_s is None:
            deadline_s = self.config.default_deadline_s
        now = time.monotonic()
        item = _Item(
            thunk=thunk,
            priority=prio,
            deadline=(now + deadline_s) if deadline_s is not None else None,
            enqueued_at=now,
            trace=_tracing.current_trace(),
            cost=cost,
        )
        q.append(item)
        self._queue_cost[prio] += item.cost
        self._m_admitted.labels(priority=prio).inc()
        self._m_depth.labels(priority=prio).set(len(q))
        self._m_cost.labels(priority=prio).set(self._queue_cost[prio])
        self._idle.clear()
        self._ensure_dispatcher()
        self._work.set()
        if item.deadline is not None:
            # Wake the dispatcher at the deadline so a queued item is
            # cancelled on time, not on the next unrelated admission.
            asyncio.get_running_loop().call_later(
                deadline_s, self._work.set
            )
        return await item.future

    def _retry_after_hint(self) -> float:
        """Shed hint: recent mean queue wait, else the configured floor."""
        h = self._m_wait
        if h.count:
            return max(self.config.retry_after_s, h.sum / h.count)
        return self.config.retry_after_s

    # -- dispatch -------------------------------------------------------

    def _ensure_dispatcher(self) -> None:
        if self._dispatcher is None or self._dispatcher.done():
            self._dispatcher = asyncio.create_task(
                self._dispatch_loop(), name="admission-dispatcher"
            )

    def _next_item(self) -> _Item | None:
        """Pop the next runnable item in strict priority order, resolving
        any already-expired queued items along the way."""
        now = time.monotonic()
        for prio in self.config.priorities:
            q = self._queues[prio]
            while q:
                item = q.popleft()
                self._release_cost(item)
                self._m_depth.labels(priority=prio).set(len(q))
                if item.future.done():
                    # Caller gave up while queued (e.g. an aborted SSE
                    # client cancelled its submit): terminal already —
                    # don't burn backend time on a dead request.
                    self._m_completed.labels(priority=item.priority).inc()
                    self._maybe_idle()
                    continue
                if item.deadline is not None and item.deadline <= now:
                    self._expire(item)
                    continue
                return item
        return None

    def _release_cost(self, item: _Item) -> None:
        """Release a dequeued item's modeled-cost charge (every
        popleft site calls this exactly once — the account mirrors
        queue membership, nothing else)."""
        if item.cost:
            c = self._queue_cost[item.priority] = max(
                0.0, self._queue_cost[item.priority] - item.cost
            )
            self._m_cost.labels(priority=item.priority).set(c)

    def _expire(self, item: _Item) -> None:
        self._m_expired.labels(priority=item.priority).inc()
        self._m_completed.labels(priority=item.priority).inc()
        if not item.future.done():
            item.future.set_exception(
                DeadlineExpiredError(
                    f"deadline expired after "
                    f"{time.monotonic() - item.enqueued_at:.3f}s in queue"
                )
            )
        self._maybe_idle()

    def _expire_due(self) -> None:
        """Resolve every queued item whose deadline has passed. Runs on
        each dispatcher wake-up even when the in-flight window is full —
        a queued 504 must not wait for an unrelated slot to free."""
        now = time.monotonic()
        for prio in self.config.priorities:
            q = self._queues[prio]
            for _ in range(len(q)):
                item = q.popleft()
                if item.deadline is not None and item.deadline <= now:
                    self._release_cost(item)
                    self._expire(item)
                else:
                    q.append(item)
            self._m_depth.labels(priority=prio).set(len(q))

    async def _dispatch_loop(self) -> None:
        while True:
            if self._inflight >= self.config.max_inflight:
                self._expire_due()
                await self._work.wait()
                self._work.clear()
                continue
            item = self._next_item()
            if item is None:
                self._maybe_idle()
                await self._work.wait()
                self._work.clear()
                continue
            wait = time.monotonic() - item.enqueued_at
            self._m_wait.observe(wait)
            if item.trace is not None:
                # The admission wait, recorded at dispatch (start
                # reconstructed in the trace's clock).
                item.trace.add_span(
                    "queued",
                    time.perf_counter() - wait,
                    wait,
                    priority=item.priority,
                )
            self._inflight += 1
            self._m_inflight.set(self._inflight)
            asyncio.create_task(self._run(item))

    async def _run(self, item: _Item) -> None:
        try:
            with _tracing.use_trace(item.trace), _tracing.request_span(
                "execute", priority=item.priority
            ):
                coro = item.thunk()
                if item.deadline is not None:
                    remaining = item.deadline - time.monotonic()
                    result = await asyncio.wait_for(coro, max(remaining, 0.0))
                else:
                    result = await coro
        except (asyncio.TimeoutError, TimeoutError):
            self._m_expired.labels(priority=item.priority).inc()
            if not item.future.done():
                item.future.set_exception(
                    DeadlineExpiredError("deadline expired mid-execution")
                )
        except Exception as e:  # noqa: BLE001 - forwarded to the caller
            if not item.future.done():
                item.future.set_exception(e)
        else:
            if not item.future.done():
                item.future.set_result(result)
        finally:
            self._inflight -= 1
            self._m_inflight.set(self._inflight)
            self._m_completed.labels(priority=item.priority).inc()
            self._maybe_idle()
            self._work.set()

    def _maybe_idle(self) -> None:
        if self.pending() == 0:
            self._idle.set()

    # -- drain ----------------------------------------------------------

    def begin_drain(self) -> None:
        """Stop admitting; already-admitted work keeps running."""
        self._draining = True

    async def drain(self) -> None:
        """Stop admitting and wait until every admitted request (queued
        and in-flight) has reached its terminal outcome."""
        self.begin_drain()
        self._work.set()
        await self._idle.wait()
        if self._dispatcher is not None:
            self._dispatcher.cancel()
            try:
                await self._dispatcher
            except asyncio.CancelledError:
                pass
            self._dispatcher = None
