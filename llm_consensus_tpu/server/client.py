"""Stdlib client for the serving gateway (incl. SSE stream parsing).

A thin, dependency-free wire client: tests drive overload/deadline/drain
scenarios through it, and operators get a one-import Python API mirroring
the curl examples in README "Serving". Synchronous by design — each call
opens one ``http.client`` connection (the gateway closes connections per
response), so N client threads are N concurrent requests, which is
exactly what the overload tests need to be able to count.
"""

from __future__ import annotations

import json
from collections.abc import Iterator
from http.client import HTTPConnection

__all__ = ["GatewayClient", "GatewayHTTPError"]


class GatewayHTTPError(Exception):
    """Non-2xx gateway response, carrying the mapped admission outcome."""

    def __init__(self, status: int, body: str, retry_after: float | None):
        super().__init__(f"HTTP {status}: {body}")
        self.status = status
        self.body = body
        #: Parsed ``Retry-After`` seconds on 429/503 sheds, else None.
        self.retry_after = retry_after


class GatewayClient:
    """Client for one gateway endpoint (host, port)."""

    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self.host = host
        self.port = port
        self.timeout = timeout

    # -- plumbing -------------------------------------------------------

    def _open(
        self,
        method: str,
        path: str,
        payload: dict | None = None,
        headers: dict | None = None,
    ):
        """Open one connection and send the request; return
        ``(conn, resp)`` with the response unread, raising
        :class:`GatewayHTTPError` (and closing the connection) on any
        non-200 — the ONE copy of the error prologue, shared by the
        buffered and streaming paths. The caller owns ``conn.close()``
        on success. ``headers`` adds/overrides request headers (e.g.
        ``{"X-Profile": "1"}`` for the gateway's profiler bridge)."""
        conn = HTTPConnection(self.host, self.port, timeout=self.timeout)
        try:
            body = json.dumps(payload).encode() if payload is not None else None
            hdrs = {"Content-Type": "application/json"} if body else {}
            hdrs.update(headers or {})
            conn.request(method, path, body=body, headers=hdrs)
            resp = conn.getresponse()
            if resp.status != 200:
                data = resp.read()
                ra = resp.getheader("Retry-After")
                raise GatewayHTTPError(
                    resp.status,
                    data.decode(errors="replace"),
                    float(ra) if ra is not None else None,
                )
        except BaseException:
            conn.close()
            raise
        return conn, resp

    def _request(
        self,
        method: str,
        path: str,
        payload: dict | None = None,
        headers: dict | None = None,
    ):
        conn, resp = self._open(method, path, payload, headers)
        try:
            return resp, resp.read()
        finally:
            conn.close()

    def _json(
        self,
        method: str,
        path: str,
        payload: dict | None = None,
        headers: dict | None = None,
    ):
        _, data = self._request(method, path, payload, headers)
        return json.loads(data)

    # -- API ------------------------------------------------------------

    def healthz(self) -> dict:
        return self._json("GET", "/healthz")

    def readyz(self) -> dict:
        """``GET /readyz``; raises GatewayHTTPError(503) when unready."""
        return self._json("GET", "/readyz")

    def traces(self, trace_id: str | None = None) -> dict:
        """``GET /debug/traces`` (summaries) or one trace's span tree."""
        path = "/debug/traces" + (f"?id={trace_id}" if trace_id else "")
        return self._json("GET", path)

    def flight(self, format: str | None = None, limit: int | None = None) -> dict:
        """``GET /debug/flight``: the serving flight recorder's event
        ring; ``format="chrome"`` returns Chrome trace-event JSON
        (save it and open in Perfetto / chrome://tracing)."""
        q = []
        if format:
            q.append(f"format={format}")
        if limit is not None:
            q.append(f"limit={limit}")
        return self._json(
            "GET", "/debug/flight" + ("?" + "&".join(q) if q else "")
        )

    def requests(self, request_id: str | None = None) -> dict:
        """``GET /debug/requests``: per-request serving summaries, or
        one by request id / trace id."""
        path = "/debug/requests" + (
            f"?id={request_id}" if request_id else ""
        )
        return self._json("GET", path)

    def metrics(self) -> str:
        _, data = self._request("GET", "/metrics")
        return data.decode()

    def generate(self, prompt: str, headers: dict | None = None, **params) -> dict:
        """``POST /v1/generate`` -> ``{"text", "num_tokens", "logprob",
        "trace_id"}``.

        Keyword params pass through to the request body
        (max_new_tokens, temperature, top_k, top_p, seed, stop,
        priority, deadline_s, model); ``headers`` adds request headers
        (e.g. ``{"X-Profile": "1"}``).
        """
        return self._json(
            "POST", "/v1/generate", {"prompt": prompt, **params}, headers
        )

    def consensus(
        self, question: str, headers: dict | None = None, **params
    ) -> dict:
        """``POST /v1/consensus`` -> answer/rounds/endorsed/author/
        feedback/trace_id."""
        return self._json(
            "POST", "/v1/consensus", {"question": question, **params}, headers
        )

    def stream_generate(self, prompt: str, **params) -> Iterator[dict]:
        """``POST /v1/generate`` with ``stream=true``: yields each SSE
        event's JSON payload (``{"text": piece}`` chunks, then a final
        ``{"done": true, ...}``). Terminates on ``[DONE]``."""
        conn, resp = self._open(
            "POST", "/v1/generate", {"prompt": prompt, "stream": True, **params}
        )
        try:
            for payload in _iter_sse(resp):
                if payload == "[DONE]":
                    return
                yield json.loads(payload)
        finally:
            conn.close()

    def stream_text(self, prompt: str, **params) -> str:
        """Convenience: concatenate a stream's text pieces."""
        return "".join(
            ev.get("text", "") for ev in self.stream_generate(prompt, **params)
        )


def _iter_sse(resp) -> Iterator[str]:
    """Yield the data payload of each SSE event from a response stream."""
    data_lines: list[str] = []
    while True:
        raw = resp.readline()
        if not raw:  # EOF: connection closed by the server
            if data_lines:
                yield "\n".join(data_lines)
            return
        line = raw.decode().rstrip("\r\n")
        if not line:  # blank line terminates one event
            if data_lines:
                yield "\n".join(data_lines)
                data_lines = []
            continue
        if line.startswith("data:"):
            data_lines.append(line[5:].lstrip())
