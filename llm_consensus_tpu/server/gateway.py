"""Asyncio HTTP/1.1 serving gateway (hand-rolled, stdlib only).

The network-facing layer over the scheduler / continuous-batcher /
coordinator stack. The reference binds actix HTTP handlers straight to
the coordinator with unbounded per-request futures
(``src/main.rs:101,156,182``); this gateway instead routes every request
through :class:`~llm_consensus_tpu.server.admission.AdmissionController`
(bounded queues, shed, deadlines, drain) and exports the metrics
registry at a standard scrape endpoint.

Routes:

- ``POST /v1/generate`` — one completion from the backend. Body:
  ``{"prompt": ..., "max_new_tokens"?, "temperature"?, "top_k"?,
  "top_p"?, "seed"?, "stop"?, "stream"?, "priority"?, "deadline_s"?}``.
  With ``"stream": true`` the response is Server-Sent Events: one
  ``data: {"text": piece}`` event per token chunk, a final
  ``data: {"done": true, ...}`` summary, then ``data: [DONE]``.
- ``POST /v1/consensus`` — drives the FULL panel protocol
  (:class:`~llm_consensus_tpu.consensus.coordinator.Coordinator`) for
  ``{"question": ..., "max_rounds"?, "seed"?, "priority"?,
  "deadline_s"?}`` and returns answer/rounds/endorsed/author/feedback.
- ``GET /metrics`` — Prometheus text exposition of the registry.
  ``?fleet=1`` on a front gateway (PR 20) scrapes every peer's
  ``/metrics`` and merges the families under a ``host=`` label
  (``host="self"`` is this process) — federation sums equal the sums
  of the per-peer scrapes.
- ``GET /healthz`` — LIVENESS: process up, drain state, backend
  heartbeat ages (always 200 while the process can answer).
- ``GET /readyz`` — READINESS: 503 while draining or while the
  backend's serving-loop heartbeat is staler than
  ``GatewayConfig.ready_stall_s`` (wedged loop => pull this replica
  from rotation without killing it).
- ``GET /debug/traces`` — request-trace summaries (newest first);
  ``?id=<trace_id>`` returns one trace's full span tree. Every
  ``/v1/*`` response carries its ``trace_id`` (body + ``X-Trace-Id``).
- ``GET /debug/flight`` — the serving flight recorder (PR 10): the
  bounded ring of typed scheduler events as JSON, or with
  ``?format=chrome`` as Chrome trace-event JSON loadable in Perfetto
  (device track reconstructed from dispatch→fetch windows, host track
  for un-overlapped scheduler work, one track per request).
  ``?fleet=1`` (PR 20) merges every peer's ring onto this process's
  clock (RTT-halving offset estimate from the ``now_pc`` stamp each
  reply carries); with ``format=chrome`` each host gets its own
  ``pid`` pair so one forwarded request reads as one aligned lane
  across processes.
- ``GET /debug/requests`` — per-request serving summaries (TTFT,
  inter-token-gap percentiles, spec tokens accepted per round,
  restored-vs-prefilled header pages); ``?id=<request or trace id>``
  returns one — or every member, for a trace several generations ran
  under (a consensus panel fan-out). The same summary rides each
  ``/v1/generate`` response as ``meta`` when the backend records one.
- ``GET /debug/chains`` — chain-residency probe (PR 16):
  ``?prompt=<text>`` (tokenized by the backend) or ``?ids=1,2,3``
  returns the backend's ``prefix_probe`` — how many leading tokens
  are registry-resident (``registry_tokens``) vs restorable from the
  host tier (``host_tokens``). This is the wire form of the
  PrefixRouter's affinity question, and what a PEER front gateway
  asks before routing.

Cross-host peer tier (PR 16): ``GatewayConfig(peers=(...))`` turns
this gateway into a ROUTING FRONT — ``/v1/*`` requests are not served
locally but forwarded to the peer gateway whose ``/debug/chains``
probe shows the longest resident chain for the prompt (ties and cold
chains go to the first reachable peer: "move the query, not the
cache" across hosts). The probe + forward run in the default executor
(urllib blocks); the peer's response body/status relay with this
front's ``X-Trace-Id`` attached. PR 20 makes that id a PROPAGATED
context: it rides the forwarded *request* too, the peer *adopts* it
(its spans join the front's trace), and the front folds its routing
time into the relayed ``meta["hops"]`` — so one trace id genuinely
follows the request across hosts and the per-hop breakdown covers the
whole path. An unreachable peer is skipped; all peers unreachable
=> 502.

Status mapping: 429 + ``Retry-After`` on shed, 503 + ``Retry-After``
while draining, 504 on deadline expiry, 502 on backend failure, 400 on
malformed requests. Every response closes the connection
(``Connection: close``) — serving concurrency comes from concurrent
connections, which asyncio multiplexes on one loop.

The HTTP layer is deliberately minimal (HTTP/1.1, Content-Length
bodies, no TLS, no keep-alive, no chunked *request* bodies): it is the
in-process front door for tests and single-host serving, and the
protocol surface later scale-out PRs (multi-replica routing,
disaggregated prefill) stand behind.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import math
import re
import threading
import time

from llm_consensus_tpu.backends.base import (
    Backend,
    BackendError,
    GenerationRequest,
    GenerationResult,
    SamplingParams,
)
from llm_consensus_tpu.server import metrics as _metrics
from llm_consensus_tpu.server.admission import (
    AdmissionConfig,
    AdmissionController,
    DeadlineExpiredError,
    DrainingError,
    QueueFullError,
)
from llm_consensus_tpu.utils import tracing as _tracing

log = logging.getLogger(__name__)

__all__ = ["Gateway", "GatewayConfig", "GatewayThread"]

_MAX_HEADER_LINES = 100
_TOKENISH = re.compile(r"\S+\s*|\s+")


class _HTTPError(Exception):
    def __init__(self, status: int, message: str, headers=None):
        super().__init__(message)
        self.status = status
        self.message = message
        self.headers = dict(headers or {})


_REASONS = {
    200: "OK",
    400: "Bad Request",
    404: "Not Found",
    405: "Method Not Allowed",
    408: "Request Timeout",
    413: "Payload Too Large",
    429: "Too Many Requests",
    500: "Internal Server Error",
    502: "Bad Gateway",
    503: "Service Unavailable",
    504: "Gateway Timeout",
}

#: One Prometheus exposition sample line: name, optional {labels}, value.
_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s+(\S+.*)$")


def _metrics_family(name: str, known: dict) -> str:
    """Family a sample line belongs to: histogram series (`_bucket`/
    `_sum`/`_count`) group under their base family when its HELP/TYPE
    header was seen; everything else is its own family."""
    for suf in ("_bucket", "_sum", "_count"):
        if name.endswith(suf) and name[: -len(suf)] in known:
            return name[: -len(suf)]
    return name


def _merge_metrics_text(texts: dict) -> str:
    """Merge per-host Prometheus expositions under a ``host=`` label
    (PR 20 federation view). Values relay verbatim — a summed family in
    the merged view is exactly the sum of the per-host scrapes (the
    lockstep the federation tests assert). HELP/TYPE headers dedupe to
    one copy per family; samples group under their family so strict
    parsers stay happy.
    """
    meta_lines: dict[str, list[str]] = {}
    fam_order: list[str] = []
    fam_samples: dict[str, list[str]] = {}
    for host, text in texts.items():
        for line in text.splitlines():
            line = line.rstrip()
            if not line:
                continue
            if line.startswith("#"):
                parts = line.split(None, 3)
                fam = parts[2] if len(parts) >= 3 else line
                if fam not in meta_lines:
                    meta_lines[fam] = []
                    fam_samples.setdefault(fam, [])
                    fam_order.append(fam)
                if line not in meta_lines[fam]:
                    meta_lines[fam].append(line)
                continue
            m = _SAMPLE_RE.match(line)
            if not m:
                continue
            name, labels, value = m.groups()
            fam = _metrics_family(name, meta_lines)
            if fam not in fam_samples:
                fam_samples[fam] = []
                meta_lines.setdefault(fam, [])
                fam_order.append(fam)
            inner = labels[1:-1] if labels else ""
            merged = f'host="{host}"' + ("," + inner if inner else "")
            fam_samples[fam].append(f"{name}{{{merged}}} {value}")
    out: list[str] = []
    for fam in fam_order:
        out.extend(meta_lines.get(fam, ()))
        out.extend(fam_samples.get(fam, ()))
    return "\n".join(out) + "\n"


class GatewayConfig:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 8080,
        admission: AdmissionConfig | None = None,
        max_body_bytes: int = 1 << 20,
        # Cap on reading one request's head+body. An idle open socket
        # otherwise pins the handler (and with it drain: Server.
        # wait_closed waits on every active connection) forever.
        read_timeout_s: float = 30.0,
        # Default sampling for /v1/generate when the body omits a field.
        sampling: SamplingParams | None = None,
        # Coordinator defaults for /v1/consensus.
        max_rounds: int = 5,
        consensus_seed: int | None = None,
        # Readiness (GET /readyz): 503 when the backend's serving loop
        # heartbeat is older than this (wedged device call, deadlock).
        # Size it above the longest legitimate device program.
        ready_stall_s: float = 10.0,
        # Opt-in JAX device profiling: a request carrying
        # ``X-Profile: 1`` wraps its backend work in
        # ``jax.profiler.trace(profile_dir)`` (one at a time; TensorBoard
        # format, aligned with the request's host spans). None = off.
        profile_dir: str | None = None,
        # Cross-host peer tier (PR 16): base URLs of downstream peer
        # gateways ("http://host:port"). Non-empty => this gateway is a
        # routing FRONT: /v1/* is forwarded to the peer whose
        # /debug/chains probe shows the longest resident chain.
        peers: tuple = (),
        # Budget for one forwarded /v1/* request (generation time
        # included — size like a client timeout, not an RPC timeout).
        peer_timeout_s: float = 120.0,
        # Budget for one /debug/chains residency probe; a peer that
        # cannot answer this quickly is skipped for this request.
        peer_probe_timeout_s: float = 2.0,
        # Fleet observability (PR 20): adopt an incoming X-Trace-Id
        # as this process's trace id (child spans join the front's
        # trace instead of rooting a fresh one), attach the per-hop
        # breakdown to response ``meta["hops"]``, and serve the
        # ``/metrics?fleet=1`` / ``/debug/flight?fleet=1`` federation
        # views. The bench's ``--serve-fleet-obs`` A/B lever.
        fleet_obs: bool = True,
    ):
        self.host = host
        self.port = port
        self.admission = admission or AdmissionConfig()
        self.max_body_bytes = max_body_bytes
        self.read_timeout_s = read_timeout_s
        self.sampling = sampling or SamplingParams()
        self.max_rounds = max_rounds
        self.consensus_seed = consensus_seed
        self.ready_stall_s = ready_stall_s
        self.profile_dir = profile_dir
        self.peers = tuple(p.rstrip("/") for p in peers)
        self.peer_timeout_s = peer_timeout_s
        self.peer_probe_timeout_s = peer_probe_timeout_s
        self.fleet_obs = bool(fleet_obs)


class Gateway:
    """One backend + one panel behind an admission-controlled HTTP front.

    ``panel`` feeds ``POST /v1/consensus``; each request gets a fresh
    :class:`Coordinator` (the coordinator holds per-question state, so
    instances are per-request while panel/backend/config are shared).
    """

    def __init__(
        self,
        backend: Backend,
        panel=None,
        config: GatewayConfig | None = None,
        registry: _metrics.MetricsRegistry | None = None,
    ):
        self.backend = backend
        self.config = config or GatewayConfig()
        self.registry = registry or _metrics.REGISTRY
        if panel is None:
            from llm_consensus_tpu.consensus.personas import default_panel

            panel = default_panel()
        self.panel = panel
        self.admission = AdmissionController(
            self.config.admission, registry=self.registry
        )
        # Preempt-instead-of-shed (PR 14): a backend that can free
        # capacity under overload (the replica fleet demotes resident
        # KV chains to its shared host tier) exposes
        # ``preempt_for_admission``; the admission controller consults
        # it at queue-full moments and admits past the bound while it
        # returns True — 429s resume only when preemption is exhausted.
        hook = getattr(backend, "preempt_for_admission", None)
        if callable(hook):
            self.admission.overflow_hook = hook
        self._server: asyncio.Server | None = None
        self._conn_tasks: set[asyncio.Task] = set()
        self.port: int | None = None  # actual bound port (ephemeral-safe)
        self._started = time.monotonic()
        # One device profile at a time: jax.profiler.start_trace is
        # process-global and errors on nesting.
        self._profile_lock = threading.Lock()
        reg = self.registry
        self._m_requests = reg.counter(
            "gateway_requests_total", "HTTP requests by route and status"
        )
        self._m_ttft = reg.histogram(
            "gateway_ttft_seconds",
            "Time from request arrival to first token byte",
        )
        self._m_latency = reg.histogram(
            "gateway_request_seconds", "Full request latency"
        )
        self._m_tps = reg.histogram(
            "gateway_tokens_per_second",
            "Generated tokens per second of request wall-clock",
            buckets=_metrics.THROUGHPUT_BUCKETS,
        )
        self._m_hops = reg.histogram(
            "gateway_hop_seconds",
            "Per-hop request time attribution (PR 20): front_route, "
            "admission_wait, prefill, handoff, wire_transfer, decode",
        )
        # Best clock-offset estimate per peer host (PR 20):
        # host -> (offset_s, rtt_s); min-RTT wins (NTP-style — the
        # tightest round trip bounds the midpoint error). Fed
        # opportunistically by every /debug/chains routing probe and
        # fleet scrape that sees a peer ``now_pc`` stamp.
        self._peer_offsets: dict[str, tuple[float, float]] = {}

    # -- lifecycle ------------------------------------------------------

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle_conn, self.config.host, self.config.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        log.info(
            "gateway listening on %s:%d (%d panelists)",
            self.config.host,
            self.port,
            len(self.panel),
        )

    async def serve_forever(self) -> None:
        assert self._server is not None, "call start() first"
        async with self._server:
            await self._server.serve_forever()

    async def drain(self) -> None:
        """Graceful shutdown: stop admitting, finish every admitted
        request, then stop accepting connections."""
        log.info("gateway draining (%d pending)", self.admission.pending())
        await self.admission.drain()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        # Server.wait_closed() does not wait for in-flight connection
        # HANDLERS before 3.12 (gh-79033) — wait for them explicitly so
        # an admitted request's response finishes writing before exit.
        # Admitted work is already done and reads time out
        # (read_timeout_s), so this is normally write-flush time only —
        # but a client that stops READING its response can pin a write
        # forever, so the wait carries the same bound.
        if self._conn_tasks:
            await asyncio.wait(
                list(self._conn_tasks), timeout=self.config.read_timeout_s
            )
        log.info("gateway drained")

    async def run_until(self, stop: asyncio.Event) -> None:
        """Serve until ``stop`` is set, then drain. The serve CLI sets
        ``stop`` from SIGTERM/SIGINT handlers."""
        await self.start()
        await stop.wait()
        await self.drain()

    # -- connection handling --------------------------------------------

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        # Tracked so drain() can wait for handlers (see drain()).
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
        try:
            try:
                method, path, headers, body = await asyncio.wait_for(
                    self._read_request(reader), self.config.read_timeout_s
                )
            except _HTTPError as e:
                await self._respond_json(
                    writer, e.status, {"error": e.message}, e.headers
                )
                return
            except (asyncio.TimeoutError, TimeoutError):
                with contextlib.suppress(Exception):
                    await self._respond_json(
                        writer, 408, {"error": "request read timed out"}
                    )
                return
            except (ValueError, asyncio.LimitOverrunError):
                # StreamReader raises ValueError for a request/header
                # line past its 64 KiB limit: a client error, not a
                # handler crash.
                with contextlib.suppress(Exception):
                    await self._respond_json(
                        writer, 400, {"error": "malformed request"}
                    )
                return
            except (asyncio.IncompleteReadError, ConnectionError):
                return
            await self._route(method, path, headers, body, writer)
        except (ConnectionError, asyncio.CancelledError):
            pass
        except Exception:  # noqa: BLE001 - last-resort 500
            log.exception("gateway handler crashed")
            with contextlib.suppress(Exception):
                await self._respond_json(
                    writer, 500, {"error": "internal error"}
                )
        finally:
            if task is not None:
                self._conn_tasks.discard(task)
            with contextlib.suppress(Exception):
                writer.close()
                await writer.wait_closed()

    async def _read_request(self, reader: asyncio.StreamReader):
        line = await reader.readline()
        if not line:
            raise asyncio.IncompleteReadError(b"", None)
        try:
            method, path, _version = line.decode("latin-1").split()
        except ValueError:
            raise _HTTPError(400, "malformed request line") from None
        headers: dict[str, str] = {}
        for _ in range(_MAX_HEADER_LINES):
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            k, sep, v = h.decode("latin-1").partition(":")
            if not sep:
                raise _HTTPError(400, f"malformed header {h!r}")
            headers[k.strip().lower()] = v.strip()
        else:
            raise _HTTPError(400, "too many headers")
        body = b""
        try:
            n = int(headers.get("content-length", "0") or "0")
        except ValueError:
            raise _HTTPError(400, "malformed Content-Length") from None
        if n < 0:
            raise _HTTPError(400, "malformed Content-Length")
        if n > self.config.max_body_bytes:
            raise _HTTPError(413, f"body of {n} bytes exceeds limit")
        if n:
            body = await reader.readexactly(n)
        return method, path, headers, body

    def _health_doc(self) -> dict:
        """Liveness payload: process-level state + the backend's serving
        loop heartbeat (when it exposes one)."""
        doc = {
            "status": "draining" if self.admission.draining else "ok",
            "pending": self.admission.pending(),
            "uptime_s": round(time.monotonic() - self._started, 3),
        }
        health = getattr(self.backend, "health", None)
        if callable(health):
            try:
                doc["backend"] = health()
            except Exception as e:  # noqa: BLE001 - health must not 500
                doc["backend"] = {"error": repr(e)}
        return doc

    def _readiness(self) -> tuple[bool, dict]:
        """Readiness: NOT ready while draining or while the backend's
        serving loop heartbeat is stale (wedged loop => stop routing
        traffic here; liveness stays 200 so the process isn't killed)."""
        doc = self._health_doc()
        if self.admission.draining:
            return False, {**doc, "reason": "draining"}
        hb = doc.get("backend") or {}
        if "error" in hb:
            # Fail CLOSED: a health probe that RAISES means the serving
            # loop's state is unknown — stop routing traffic here.
            return False, {**doc, "reason": f"health probe failed: {hb['error']}"}
        age = hb.get("last_tick_age_s")
        # Replica fleet (PR 14): the backend heartbeat aggregates one
        # entry per batcher replica. The aggregate alive/max-age checks
        # below already flip readiness when ANY replica wedges (alive
        # is ANDed, the age is the stalest loop's); here we NAME the
        # wedged indices so the operator knows which replica to
        # restart — the router has already stopped sending it traffic.
        # Elastic lifecycle (PR 19): a DRAINING replica is deliberately
        # finishing its in-flight work while the router skips it, and a
        # RETIRED replica's loop is deliberately stopped — neither is
        # wedged, and neither may flip readiness. They are surfaced
        # under their own keys so the operator sees the drain progress.
        replicas = hb.get("replicas") or []
        draining = [
            i
            for i, r in enumerate(replicas)
            if r.get("state") == "draining"
        ]
        retired = [
            i
            for i, r in enumerate(replicas)
            if r.get("state") == "retired"
        ]
        wedged = [
            i
            for i, r in enumerate(replicas)
            if r.get("state", "serving") == "serving"
            and (
                not r.get("alive")
                or (
                    r.get("last_tick_age_s") is not None
                    and r["last_tick_age_s"] > self.config.ready_stall_s
                )
            )
        ]
        if draining:
            doc = {**doc, "draining_replicas": draining}
        if retired:
            doc = {**doc, "retired_replicas": retired}
        if wedged:
            doc = {**doc, "wedged_replicas": wedged}
        if hb.get("alive") is False:
            reason = "serving loop dead"
            if wedged:
                reason = f"serving loop dead (replicas {wedged})"
            return False, {**doc, "reason": reason}
        if age is not None and age > self.config.ready_stall_s:
            reason = (
                f"serving loop stalled {age:.1f}s "
                f"(> {self.config.ready_stall_s}s)"
            )
            if wedged:
                reason += f" (replicas {wedged})"
            return False, {**doc, "reason": reason}
        return True, doc

    async def _route(self, method, path, headers, body, writer) -> None:
        path, _, rawq = path.partition("?")
        if path == "/healthz" and method == "GET":
            await self._respond_json(writer, 200, self._health_doc())
            self._count(path, 200)
            return
        if path == "/readyz" and method == "GET":
            ready, doc = self._readiness()
            status = 200 if ready else 503
            await self._respond_json(
                writer,
                status,
                {**doc, "ready": ready},
                None if ready else {"Retry-After": "5"},
            )
            self._count(path, status)
            return
        if path == "/debug/traces" and method == "GET":
            await self._handle_traces(rawq, writer)
            return
        if path == "/debug/flight" and method == "GET":
            await self._handle_flight(rawq, writer)
            return
        if path == "/debug/requests" and method == "GET":
            await self._handle_requests(rawq, writer)
            return
        if path == "/debug/chains" and method == "GET":
            await self._handle_chains(rawq, writer)
            return
        if path == "/metrics" and method == "GET":
            await self._handle_metrics(rawq, writer)
            return
        if path in ("/v1/generate", "/v1/consensus"):
            if method != "POST":
                await self._respond_json(
                    writer, 405, {"error": "POST only"}, {"Allow": "POST"}
                )
                self._count(path, 405)
                return
            try:
                payload = json.loads(body or b"{}")
                if not isinstance(payload, dict):
                    raise ValueError("body must be a JSON object")
            except ValueError as e:
                await self._respond_json(writer, 400, {"error": f"bad JSON: {e}"})
                self._count(path, 400)
                return
            if self.config.peers:
                await self._handle_peer_forward(
                    path, payload, body, writer, headers
                )
                return
            if path == "/v1/generate":
                await self._handle_generate(payload, headers, writer)
            else:
                await self._handle_consensus(payload, headers, writer)
            return
        await self._respond_json(writer, 404, {"error": f"no route {path}"})
        # Arbitrary client paths must not become metric labels (a port
        # scan would grow the family without bound): one shared label.
        self._count("<unmatched>", 404)

    async def _handle_traces(self, rawq: str, writer) -> None:
        """``GET /debug/traces``: newest-first summaries; ``?id=<trace>``
        returns that trace's full span tree; ``?limit=N`` bounds the
        listing."""
        from urllib.parse import parse_qs

        q = parse_qs(rawq)
        store = _tracing.trace_store()
        tid = (q.get("id") or [None])[0]
        if tid:
            trace = store.get(tid)
            if trace is None:
                await self._respond_json(
                    writer, 404, {"error": f"no trace {tid!r}"}
                )
                self._count("/debug/traces", 404)
                return
            await self._respond_json(writer, 200, trace.to_dict())
            self._count("/debug/traces", 200)
            return
        try:
            limit = int((q.get("limit") or ["50"])[0])
        except ValueError:
            limit = 50
        await self._respond_json(
            writer,
            200,
            {
                "enabled": _tracing.enabled(),
                "max_traces": store.max_traces,
                "max_spans_per_trace": store.max_spans,
                "evicted_traces": store.evicted,
                "traces": [t.summary() for t in store.traces(limit)],
            },
        )
        self._count("/debug/traces", 200)

    async def _handle_metrics(self, rawq: str, writer) -> None:
        """``GET /metrics``: Prometheus text exposition. With
        ``?fleet=1`` on a front gateway (PR 20): scrape every peer's
        ``/metrics`` concurrently and merge the families under a
        ``host=`` label (``host="self"`` for this process) — sums over
        the merged view equal the sums of the per-peer scrapes."""
        from urllib.parse import parse_qs

        q = parse_qs(rawq)
        if (
            self.config.fleet_obs
            and (q.get("fleet") or [""])[0] in ("1", "true")
        ):
            texts = {"self": self.registry.render()}
            loop = asyncio.get_running_loop()
            if self.config.peers:
                fetched = await asyncio.gather(
                    *(
                        loop.run_in_executor(
                            None,
                            self._fetch_peer_text,
                            f"{p}/metrics",
                            self.config.peer_probe_timeout_s,
                        )
                        for p in self.config.peers
                    ),
                    return_exceptions=True,
                )
                for peer, got in zip(self.config.peers, fetched):
                    if isinstance(got, str):
                        texts[peer] = got
            await self._respond_raw(
                writer,
                200,
                _merge_metrics_text(texts).encode(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
            self._count("/metrics", 200)
            return
        text = self.registry.render().encode()
        await self._respond_raw(
            writer, 200, text, "text/plain; version=0.0.4; charset=utf-8"
        )
        self._count("/metrics", 200)

    def _fetch_peer_text(self, url: str, timeout: float) -> str:
        """Blocking GET returning a peer's raw text body (executor
        only)."""
        import urllib.request

        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode("utf-8", "replace")

    def _fetch_peer_json(self, url: str, timeout: float):
        """Blocking GET returning ``(doc, t_send_pc, t_recv_pc)`` —
        the perf_counter stamps bracketing the exchange feed the
        RTT-halving clock-offset estimate (executor only)."""
        import urllib.request

        t0 = time.perf_counter()
        with urllib.request.urlopen(url, timeout=timeout) as r:
            doc = json.loads(r.read())
        return doc, t0, time.perf_counter()

    @staticmethod
    def _clock_offset(doc: dict, t_send: float, t_recv: float):
        """Midpoint clock-offset estimate from a reply carrying the
        peer's ``now_pc`` perf_counter stamp: assuming the reply was
        stamped mid-flight, ``t_local ≈ t_peer + offset`` with
        ``offset = (t_send + t_recv)/2 − now_pc``. Returns
        ``(offset_s, rtt_s)`` or ``(None, None)`` when the peer
        predates the stamp."""
        now = doc.get("now_pc")
        if not isinstance(now, (int, float)):
            return None, None
        return (t_send + t_recv) / 2.0 - float(now), t_recv - t_send

    def _note_offset(self, host: str, offset, rtt) -> None:
        if offset is None:
            return
        cur = self._peer_offsets.get(host)
        if cur is None or rtt <= cur[1]:
            self._peer_offsets[host] = (float(offset), float(rtt))

    async def _handle_flight(self, rawq: str, writer) -> None:
        """``GET /debug/flight``: the flight recorder's event ring
        (PR 10). ``?format=chrome`` renders Chrome trace-event JSON
        (open in Perfetto / chrome://tracing); the plain JSON form
        takes ``?limit=N`` (newest N events). Programs still in flight
        appear with their dispatch stamp and zero duration — quiesce
        before comparing the device track against counters."""
        from urllib.parse import parse_qs

        # Deferred: serving.flight rides the serving package (jax);
        # a FakeBackend gateway only pays that import if someone asks.
        from llm_consensus_tpu.serving import flight as _flight

        q = parse_qs(rawq)
        if (
            self.config.fleet_obs
            and (q.get("fleet") or [""])[0] in ("1", "true")
        ):
            await self._handle_flight_fleet(q, writer)
            return
        rec = _flight.flight_recorder()
        events = rec.events()
        raw_limit = (q.get("limit") or [None])[0]
        limit = None
        if raw_limit is not None:
            try:
                limit = int(raw_limit)
            except ValueError:
                limit = None
        if (q.get("format") or [""])[0] == "chrome":
            # ?limit= applies here too (newest N events); the default
            # is the whole ring — a Perfetto export wants everything.
            if limit is not None:
                # limit <= 0 really means "no events" — a bare -0:
                # slice would return the whole ring.
                events = events[-limit:] if limit > 0 else []
            await self._respond_json(writer, 200, _flight.to_chrome(events))
            self._count("/debug/flight", 200)
            return
        if limit is None:
            limit = 512
        await self._respond_json(
            writer,
            200,
            {
                "enabled": _flight.enabled(),
                "capacity": rec.capacity,
                "dropped": rec.dropped,
                "n_events": len(events),
                # Clock-probe stamp (PR 20): a scraping front halves
                # the exchange's RTT around this to place our
                # perf_counter timebase on its own.
                "now_pc": time.perf_counter(),
                "events": [
                    e.to_dict()
                    for e in (events[-limit:] if limit > 0 else [])
                ],
            },
        )
        self._count("/debug/flight", 200)

    async def _handle_flight_fleet(self, q: dict, writer) -> None:
        """``GET /debug/flight?fleet=1`` (PR 20): merged cross-process
        flight timeline. Scrapes every peer's ``/debug/flight``
        concurrently, estimates each peer's clock offset from the
        ``now_pc`` stamp riding the reply (RTT-halving midpoint;
        min-RTT estimate wins across probes), and merges the rings
        onto this process's perf_counter timebase. ``?format=chrome``
        renders one ``pid`` pair per host so a forwarded request reads
        as one aligned lane across processes."""
        from llm_consensus_tpu.serving import flight as _flight

        loop = asyncio.get_running_loop()
        own = _flight.flight_recorder().events()
        by_host: dict = {"self": (own, 0.0)}
        hosts_doc: dict = {"self": {"offset_s": 0.0, "rtt_s": 0.0}}
        unreachable: list[str] = []
        if self.config.peers:
            fetched = await asyncio.gather(
                *(
                    loop.run_in_executor(
                        None,
                        self._fetch_peer_json,
                        f"{p}/debug/flight?limit=100000",
                        self.config.peer_probe_timeout_s,
                    )
                    for p in self.config.peers
                ),
                return_exceptions=True,
            )
            for peer, got in zip(self.config.peers, fetched):
                if isinstance(got, BaseException):
                    unreachable.append(peer)
                    continue
                doc, t0, t1 = got
                off, rtt = self._clock_offset(doc, t0, t1)
                self._note_offset(peer, off, rtt)
                best = self._peer_offsets.get(peer)
                offset = best[0] if best else 0.0
                evs = [
                    _flight.FlightEvent(
                        seq=int(e.get("seq", 0)),
                        kind=str(e.get("kind", "?")),
                        t0=float(e.get("t0", 0.0)),
                        dur=float(e.get("dur_s", 0.0)),
                        trace_id=e.get("trace_id"),
                        meta=e.get("meta") or {},
                    )
                    for e in doc.get("events", ())
                    if isinstance(e, dict)
                ]
                by_host[peer] = (evs, offset)
                hosts_doc[peer] = {
                    "offset_s": round(offset, 6),
                    "rtt_s": round(best[1], 6) if best else None,
                }
        if (q.get("format") or [""])[0] == "chrome":
            await self._respond_json(
                writer, 200, _flight.to_chrome_fleet(by_host)
            )
            self._count("/debug/flight", 200)
            return
        merged = _flight.merge_fleet(by_host)
        try:
            limit = int((q.get("limit") or ["512"])[0])
        except ValueError:
            limit = 512
        await self._respond_json(
            writer,
            200,
            {
                "hosts": hosts_doc,
                "unreachable": unreachable,
                "n_events": len(merged),
                "events": [
                    {**e.to_dict(), "host": e.meta.get("host")}
                    for e in (merged[-limit:] if limit > 0 else [])
                ],
            },
        )
        self._count("/debug/flight", 200)

    async def _handle_requests(self, rawq: str, writer) -> None:
        """``GET /debug/requests``: per-request serving summaries from
        the RequestLog (newest first); ``?id=`` accepts a request id
        OR a trace id."""
        from urllib.parse import parse_qs

        from llm_consensus_tpu.serving import flight as _flight

        q = parse_qs(rawq)
        log_ = _flight.request_log()
        rid = (q.get("id") or [None])[0]
        if rid:
            docs = log_.get_all(rid)
            if not docs:
                await self._respond_json(
                    writer, 404, {"error": f"no request {rid!r}"}
                )
                self._count("/debug/requests", 404)
                return
            # One trace can cover several generations (a consensus
            # panel fan-out): a unique match returns the summary doc
            # itself, a shared trace returns every member.
            await self._respond_json(
                writer,
                200,
                docs[0]
                if len(docs) == 1
                else {"id": rid, "requests": docs},
            )
            self._count("/debug/requests", 200)
            return
        try:
            limit = int((q.get("limit") or ["50"])[0])
        except ValueError:
            limit = 50
        await self._respond_json(
            writer,
            200,
            {
                "retained": len(log_),
                "requests": log_.recent(limit),
            },
        )
        self._count("/debug/requests", 200)

    async def _handle_chains(self, rawq: str, writer) -> None:
        """``GET /debug/chains``: chain-residency probe (PR 16).
        ``?prompt=<text>`` (backend-tokenized) or ``?ids=1,2,3``
        answers the backend's ``prefix_probe`` — registry-resident vs
        host-restorable leading tokens. The probe itself takes the
        batcher lock, so it runs in the executor, never on the loop."""
        from urllib.parse import parse_qs

        probe = getattr(self.backend, "prefix_probe", None)
        if not callable(probe):
            await self._respond_json(
                writer, 404, {"error": "backend has no prefix probe"}
            )
            self._count("/debug/chains", 404)
            return
        q = parse_qs(rawq)
        raw_ids = (q.get("ids") or [None])[0]
        prompt = (q.get("prompt") or [None])[0]
        loop = asyncio.get_running_loop()
        try:
            if raw_ids:
                ids = [int(x) for x in raw_ids.split(",") if x.strip()]
            elif prompt:
                tok = getattr(self.backend, "tokenizer", None)
                if tok is None:
                    await self._respond_json(
                        writer,
                        404,
                        {"error": "backend has no tokenizer; use ?ids="},
                    )
                    self._count("/debug/chains", 404)
                    return
                # HF tokenizers can be slow on long prompts: executor.
                ids = await loop.run_in_executor(None, tok.encode, prompt)
            else:
                raise ValueError("need ?prompt=<text> or ?ids=1,2,3")
        except ValueError as e:
            await self._respond_json(writer, 400, {"error": str(e)})
            self._count("/debug/chains", 400)
            return
        doc = await loop.run_in_executor(None, probe, ids)
        # ``now_pc`` (PR 20): clock-probe stamp piggybacked on the
        # residency probe — the front halves the probe's RTT around it
        # to estimate this host's perf_counter offset for free.
        await self._respond_json(
            writer,
            200,
            {"n_ids": len(ids), "now_pc": time.perf_counter(), **doc},
        )
        self._count("/debug/chains", 200)

    # -- cross-host peer tier (PR 16) -----------------------------------

    def _probe_peer(self, peer: str, prompt: str) -> int:
        """Blocking residency probe of one peer (executor only).
        Returns the longest resident/restorable prefix in tokens, 0
        for a cold (or probe-less) peer, -1 for an unreachable one."""
        import urllib.parse
        import urllib.request

        url = (
            f"{peer}/debug/chains?prompt="
            f"{urllib.parse.quote(prompt, safe='')}"
        )
        try:
            t_send = time.perf_counter()
            with urllib.request.urlopen(
                url, timeout=self.config.peer_probe_timeout_s
            ) as r:
                doc = json.loads(r.read())
            t_recv = time.perf_counter()
            # Clock-offset piggyback (PR 20): every routing probe that
            # reaches a peer refines its offset estimate for free.
            off, rtt = self._clock_offset(doc, t_send, t_recv)
            self._note_offset(peer, off, rtt)
            return max(
                int(doc.get("registry_tokens", 0)),
                int(doc.get("host_tokens", 0)),
            )
        except Exception:  # noqa: BLE001 - any failure => skip peer
            return -1

    def _forward_peer(self, peer: str, path: str, body: bytes, tid):
        """Blocking forward of one /v1/* body to ``peer`` (executor
        only). Returns (status, body, content_type); raises only on
        transport failure (no HTTP response at all)."""
        import urllib.error
        import urllib.request

        headers = {"Content-Type": "application/json"}
        if tid:
            headers["X-Trace-Id"] = tid
        req = urllib.request.Request(
            f"{peer}{path}", data=body, headers=headers, method="POST"
        )
        try:
            with urllib.request.urlopen(
                req, timeout=self.config.peer_timeout_s
            ) as r:
                return (
                    r.status,
                    r.read(),
                    r.headers.get("Content-Type", "application/json"),
                )
        except urllib.error.HTTPError as e:
            # A peer's 4xx/5xx is a RESPONSE to relay, not a transport
            # failure: the peer's shed/drain statuses must reach the
            # client (its Retry-After semantics are the contract).
            return (
                e.code,
                e.read(),
                e.headers.get("Content-Type", "application/json"),
            )

    async def _handle_peer_forward(
        self, path: str, payload: dict, body: bytes, writer, headers=None
    ) -> None:
        """Front-gateway routing (PR 16): probe every peer's
        ``/debug/chains`` for this prompt concurrently, forward the
        request to the one with the longest resident chain (first
        reachable on ties/cold), relay its response. All blocking I/O
        runs in the executor; the loop never waits on a socket.

        Trace propagation (PR 20): ``X-Trace-Id`` rides the forwarded
        REQUEST (not just the relayed response) and the peer adopts it
        — one id genuinely follows the request across hosts, so the
        front's route spans and the peer's serving spans join under
        the same trace in the merged fleet export. A chained front
        adopts an incoming id the same way. The front also injects its
        own ``front_route`` hop (probe + routing decision time) into
        the relayed response's ``meta["hops"]``."""
        prompt = payload.get("prompt") or payload.get("question") or ""
        trace = _tracing.trace_store().start(
            path, route=path, trace_id=self._incoming_tid(headers)
        )
        tid = trace.trace_id if trace is not None else None
        t_start = time.monotonic()
        loop = asyncio.get_running_loop()
        try:
            if isinstance(prompt, str) and prompt:
                scores = await asyncio.gather(
                    *(
                        loop.run_in_executor(None, self._probe_peer, p, prompt)
                        for p in self.config.peers
                    )
                )
            else:
                # No prompt to probe with (bad body: let the peer 400
                # it) — treat every peer as cold-but-reachable.
                scores = [0] * len(self.config.peers)
            ranked = [
                (p, s)
                for p, s in zip(self.config.peers, scores)
                if s >= 0
            ]
            if not ranked and any(s < 0 for s in scores):
                # Every probe failed — the probes may be down while
                # serving still works (older peers): fall back to
                # trying peers in order rather than 502ing outright.
                ranked = [(p, 0) for p in self.config.peers]
            peer = max(ranked, key=lambda ps: ps[1])[0] if ranked else None
            if peer is None:
                await self._respond_json(
                    writer, 502, {"error": "no peers configured"}
                )
                self._count(path, 502)
                return
            t_fwd = time.monotonic()
            if trace is not None:
                # The routing decision's span: probe fan-out + ranking.
                # (Span stamps live in perf_counter space — backdate
                # the start by the measured monotonic duration.)
                route_s = t_fwd - t_start
                trace.add_span(
                    "front_route",
                    time.perf_counter() - route_s,
                    route_s,
                    peer=peer,
                )
            try:
                status, out, ctype = await loop.run_in_executor(
                    None, self._forward_peer, peer, path, body, tid
                )
            except Exception as e:  # noqa: BLE001 - transport failure
                log.warning("peer %s unreachable: %s", peer, e)
                await self._respond_json(
                    writer,
                    502,
                    {"error": f"peer {peer} unreachable", "trace_id": tid},
                )
                self._count(path, 502)
                return
            out = self._inject_front_hop(
                status, out, ctype, t_fwd - t_start
            )
            hdrs = {"X-Peer": peer}
            if tid:
                hdrs["X-Trace-Id"] = tid
            await self._respond_raw(writer, status, out, ctype, hdrs)
            self._count(path, status)
        finally:
            if trace is not None:
                trace.finish()

    def _inject_front_hop(
        self, status: int, out: bytes, ctype: str, front_s: float
    ) -> bytes:
        """Fold this front's routing time into the relayed response's
        ``meta["hops"]`` (PR 20) so the client-visible hop breakdown
        covers the WHOLE path, front included. Only a parseable 200
        JSON body is touched — anything else relays verbatim."""
        if not (
            self.config.fleet_obs
            and status == 200
            and "json" in (ctype or "")
        ):
            return out
        try:
            doc = json.loads(out)
            if not isinstance(doc, dict):
                return out
            meta = doc.get("meta") or {}
            hops = {
                "front_route": round(front_s, 6),
                **(meta.get("hops") or {}),
            }
            doc["meta"] = {**meta, "hops": hops}
            self._m_hops.labels(hop="front_route").observe(front_s)
            return json.dumps(doc).encode()
        except Exception:  # noqa: BLE001 - relay verbatim on any doubt
            return out

    @staticmethod
    def _shed_reason(e: Exception) -> str:
        """Flight-event reason for a shed (PR 19): ``slo`` = deadline-
        aware shed of a would-miss request, ``tenant`` = fair-share cap,
        ``draining`` = SIGTERM drain, else the classic ``queue_full``."""
        if isinstance(e, DrainingError):
            return "draining"
        if getattr(e, "slo_miss", False):
            return "slo"
        if getattr(e, "tenant_over", False):
            return "tenant"
        return "queue_full"

    def _record_shed(self, route: str, trace, reason: str = "queue_full") -> None:
        """Mirror an admission shed into the flight recorder (PR 10):
        the timeline's counterpart of the 429/503 the client saw.

        Records ONLY when the flight module is already loaded: an
        import here would execute the serving package's __init__ (and
        with it jax) synchronously inside the event loop — seconds of
        stall for every in-flight request, at exactly peak overload.
        A gateway whose backend never loaded the serving stack has no
        batcher feeding the ring, so there is no timeline to join.
        """
        import sys as _sys

        mod = _sys.modules.get("llm_consensus_tpu.serving.flight")
        if mod is None:
            return
        try:
            mod.flight_recorder().record(
                "shed",
                time.perf_counter(),
                trace_id=_tracing.trace_id_of(trace),
                route=route,
                reason=reason,
            )
        except Exception:  # noqa: BLE001 - recording must never 500
            log.exception("flight shed record failed")

    # -- routes ---------------------------------------------------------

    @contextlib.contextmanager
    def _maybe_profile(self, headers: dict):
        """``X-Profile: 1`` (with ``GatewayConfig.profile_dir`` set)
        captures a JAX device profile around this request's backend
        work — a TensorBoard trace in ``profile_dir`` aligned with the
        request's host spans (a ``jax_profile`` span marks the window
        on the trace). One capture at a time: concurrent flagged
        requests run unprofiled rather than queueing on the profiler's
        process-global state. SSE streaming requests are not profiled
        (their backend work outlives the handler's await points)."""
        if not (
            self.config.profile_dir
            and headers.get("x-profile", "").strip() == "1"
        ):
            yield False
            return
        if not self._profile_lock.acquire(blocking=False):
            log.warning("X-Profile ignored: a device profile is in flight")
            yield False
            return
        try:
            with _tracing.request_span(
                "jax_profile", logdir=self.config.profile_dir
            ), _tracing.trace_jax_profile(self.config.profile_dir):
                yield True
        finally:
            self._profile_lock.release()

    @staticmethod
    def _trace_id() -> str | None:
        trace = _tracing.current_trace()
        return trace.trace_id if trace is not None else None

    def _incoming_tid(self, headers) -> str | None:
        """The ``X-Trace-Id`` a forwarding front attached (PR 20) —
        adopting it roots this process's spans under the front's trace
        id instead of minting a fresh root. None when fleet
        observability is off or no id arrived; the trace store
        validates the id's shape before adopting."""
        if not self.config.fleet_obs or not headers:
            return None
        return headers.get("x-trace-id")

    def _hop_breakdown(self, trace, meta, dt: float) -> dict | None:
        """Per-hop time attribution for one request (PR 20), sourced
        from the joined trace spans plus the batcher's summary meta:

        - ``admission_wait`` — the admission queue's "queued" span(s);
        - ``prefill`` / ``decode`` — split from the serving summary's
          ``ttft_s`` / ``duration_s`` when the backend records one,
          else the admission "execute" span stands in for ``decode``;
        - ``handoff`` — disagg claim→export→restore spans;
        - ``wire_transfer`` — remote-store ``store_op`` spans.

        A forwarding front prepends ``front_route`` at relay time
        (:meth:`_inject_front_hop`). For a single-generation request
        the hop sum tracks the client-observed latency (the e2e
        tolerance the fleet-obs bench gates); a consensus fan-out's
        spans overlap, so there the breakdown is attribution, not a
        wall-clock identity. Each hop lands in the
        ``gateway_hop_seconds{hop=}`` histogram."""
        if not self.config.fleet_obs or trace is None:
            return None
        sums: dict[str, float] = {}
        for s in trace.spans():
            if s.name == "queued":
                sums["admission_wait"] = (
                    sums.get("admission_wait", 0.0) + s.duration
                )
            elif s.name == "handoff":
                sums["handoff"] = sums.get("handoff", 0.0) + s.duration
            elif s.name == "store_op":
                sums["wire_transfer"] = (
                    sums.get("wire_transfer", 0.0) + s.duration
                )
            elif s.name == "execute":
                sums["execute"] = sums.get("execute", 0.0) + s.duration
        hops: dict[str, float] = {}
        timing = meta if isinstance(meta, dict) else {}
        ttft = timing.get("ttft_s")
        dur = timing.get("duration_s")
        if isinstance(ttft, (int, float)):
            hops["prefill"] = float(ttft)
            if isinstance(dur, (int, float)) and dur >= ttft:
                hops["decode"] = float(dur) - float(ttft)
        elif "execute" in sums:
            # No serving summary (e.g. a FakeBackend): the execute
            # span IS the backend time; call it decode rather than
            # invent a prefill split the backend never measured.
            hops["decode"] = sums["execute"]
        if "handoff" in sums and "wire_transfer" in sums:
            # Store-op spans nest INSIDE the handoff window (the
            # coordinator's claim→export→restore wraps the page
            # put/get): report handoff net of its wire time so the
            # hop sum stays a partition, not a double count.
            sums["handoff"] = max(
                0.0, sums["handoff"] - sums["wire_transfer"]
            )
        for key in ("admission_wait", "handoff", "wire_transfer"):
            if key in sums:
                hops[key] = sums[key]
        if not hops:
            return None
        hops = {k: round(v, 6) for k, v in hops.items()}
        for k, v in hops.items():
            self._m_hops.labels(hop=k).observe(v)
        return hops

    def _sampling_from(self, payload: dict) -> SamplingParams:
        d = self.config.sampling
        stop = payload.get("stop") or ()
        if isinstance(stop, str):
            stop = (stop,)
        return SamplingParams(
            max_new_tokens=int(
                payload.get("max_new_tokens", d.max_new_tokens)
            ),
            temperature=float(payload.get("temperature", d.temperature)),
            top_k=int(payload.get("top_k", d.top_k)),
            top_p=float(payload.get("top_p", d.top_p)),
            seed=int(payload.get("seed", d.seed)),
            stop=tuple(stop),
        )

    def _cost_kw(
        self, adm_kw: dict, prompt: str, max_new_tokens: int, members: int = 1
    ) -> dict:
        """Attach the request's modeled cost (PR 15) when the
        admission controller runs in cost-budget mode and the backend
        can price it (``request_cost`` — the continuous batcher's
        modeled bytes, the same unit the fleet router's load_cost
        compares). ``members``: a consensus panel fans one question
        into N generations, so it costs N times the single prompt.
        Pricing failures fall back to the controller's nominal-slot
        default rather than 500ing the request."""
        if self.admission.config.cost_budget_bytes <= 0:
            return adm_kw
        rc = getattr(self.backend, "request_cost", None)
        if callable(rc):
            try:
                adm_kw["cost"] = float(rc(prompt, max_new_tokens)) * members
            except Exception:  # noqa: BLE001 - pricing must not 500
                log.exception("request_cost failed; using nominal cost")
        return adm_kw

    def _lane_for(self, model: str | None, fallback: str) -> str:
        """Per-model admission lane (PR 18): when the controller was
        configured with a ``model:<name>`` priority lane for this
        request's model tag, default the request there — one member's
        burst queues behind its own bound instead of starving the
        panel's other models. An explicit payload ``priority`` always
        wins (``_admission_kw`` reads it first); unknown models keep
        the route's base lane and fail later with the backend's
        unknown-model error, not a KeyError here."""
        if model:
            lane = f"model:{model}"
            if lane in self.admission.config.priorities:
                return lane
        return fallback

    def _admission_kw(self, payload: dict, default_priority: str) -> dict:
        kw = {"priority": payload.get("priority", default_priority)}
        if payload.get("deadline_s") is not None:
            d = float(payload["deadline_s"])
            # json.loads accepts NaN/Infinity: a non-finite deadline
            # reaches loop.call_later(nan) and corrupts the shared timer
            # heap (NaN compares False both ways) for the whole process.
            if not math.isfinite(d):
                raise ValueError(f"deadline_s must be finite, got {d}")
            kw["deadline_s"] = d
        # SLO class + tenant (PR 19): validated HERE, at the 400
        # boundary, so a typo'd class never reaches admission as a 500.
        if payload.get("slo") is not None:
            s = payload["slo"]
            classes = self.admission.config.slo_classes or {}
            if not isinstance(s, str) or s not in classes:
                raise ValueError(
                    f"unknown slo class {s!r}; have {sorted(classes)}"
                )
            kw["slo"] = s
        if payload.get("tenant") is not None:
            t = payload["tenant"]
            if not isinstance(t, str) or not t:
                raise ValueError("tenant must be a non-empty string")
            kw["tenant"] = t
        return kw

    async def _handle_generate(self, payload: dict, headers, writer) -> None:
        prompt = payload.get("prompt")
        if not isinstance(prompt, str) or not prompt:
            await self._respond_json(
                writer, 400, {"error": "need a non-empty string 'prompt'"}
            )
            self._count("/v1/generate", 400)
            return
        # Field coercion up front: a mistyped body ("max_new_tokens":
        # "abc") is the client's 400, not a handler crash.
        try:
            req = GenerationRequest(
                prompt=prompt,
                params=self._sampling_from(payload),
                model=payload.get("model"),
            )
            adm_kw = self._cost_kw(
                self._admission_kw(
                    payload,
                    self._lane_for(payload.get("model"), "interactive"),
                ),
                prompt,
                req.params.max_new_tokens,
            )
        except (TypeError, ValueError, OverflowError) as e:
            await self._respond_json(
                writer, 400, {"error": f"bad request field: {e}"}
            )
            self._count("/v1/generate", 400)
            return
        # The trace is minted AFTER validation (a 400 never mints one)
        # and discarded again if admission sheds the request — a 429
        # storm must not churn the bounded ring and evict the slow
        # traces being debugged. Everything downstream — admission
        # queue, coordinator rounds, batcher chunks/steps — attaches
        # spans through the contextvars protocol or explicit trace
        # handles (None when tracing is disabled: every site no-ops).
        trace = _tracing.trace_store().start(
            "/v1/generate",
            route="/v1/generate",
            # Adopt a forwarding front's id (PR 20): this process's
            # spans join the front's trace instead of rooting anew.
            trace_id=self._incoming_tid(headers),
        )
        # Route-driven restore prefetch (PR 17): the destination is
        # decided (single-replica backends) or about to be (the fleet
        # prefetches again at route time), and the request is about to
        # sit in the admission queue — free overlap for staging the
        # chain's host-store pages. Non-blocking, advisory, and never
        # allowed to fail the request.
        pf = getattr(self.backend, "prefetch", None)
        if callable(pf):
            try:
                pf(prompt)
            except Exception:  # noqa: BLE001 - advisory path
                log.exception("prefetch hook failed (ignored)")
        t0 = time.monotonic()
        if payload.get("stream"):
            try:
                with _tracing.use_trace(trace):
                    await self._handle_generate_stream(
                        req, adm_kw, writer, t0
                    )
            finally:
                if trace is not None:
                    trace.finish()
            return

        async def thunk():
            # Profiling wraps ONLY the backend call, inside the
            # dispatched thunk: the capture window (and the one-at-a-
            # time profiler slot) must not include the admission-queue
            # wait, where it would mostly record OTHER requests' work.
            with self._maybe_profile(headers):
                return await self.backend.generate(req)

        try:
            with _tracing.use_trace(trace):
                result: GenerationResult = await self.admission.submit(
                    thunk, **adm_kw
                )
        except Exception as e:  # noqa: BLE001 - mapped to HTTP statuses
            status, doc, hdrs = self._error_response(e)
            if isinstance(e, (QueueFullError, DrainingError)):
                self._record_shed(
                    "/v1/generate", trace, self._shed_reason(e)
                )
                if trace is not None:
                    _tracing.trace_store().discard(trace.trace_id)
            await self._respond_json(writer, status, doc, hdrs)
            self._count("/v1/generate", status)
            return
        finally:
            if trace is not None:
                trace.finish()
        dt = time.monotonic() - t0
        self._observe_generation(dt, dt, result.num_tokens)
        tid = trace.trace_id if trace is not None else None
        meta = getattr(result, "meta", None)
        hops = self._hop_breakdown(trace, meta, dt)
        if hops:
            # Fold IN PLACE when the backend handed us its RequestLog
            # summary (same dict object) — /debug/requests must serve
            # the identical doc the response meta carries.
            if isinstance(meta, dict):
                meta["hops"] = hops
            else:
                meta = {"hops": hops}
        await self._respond_json(
            writer,
            200,
            {
                "text": result.text,
                "num_tokens": result.num_tokens,
                "logprob": result.logprob,
                "trace_id": tid,
                # Per-request serving timeline (PR 10) when the backend
                # records one (the continuous batcher's summary — the
                # same doc /debug/requests?id= serves).
                **({"meta": meta} if meta else {}),
            },
            {"X-Trace-Id": tid} if tid else None,
        )
        self._count("/v1/generate", 200)

    async def _handle_generate_stream(
        self, req: GenerationRequest, adm_kw: dict, writer, t0: float
    ) -> None:
        """SSE streaming: events flow as the backend produces pieces.

        Backends that expose token streaming (an async-generator
        ``generate_stream(request)``) stream truly incrementally; any
        other backend falls back to one admission-controlled generate
        whose text is then chunked into token-ish SSE events — the
        stream CONTENT is identical either way (tested).
        """
        q: asyncio.Queue[str] = asyncio.Queue()
        loop = asyncio.get_running_loop()

        def push(piece: str) -> None:
            loop.call_soon_threadsafe(q.put_nowait, piece)

        task = asyncio.create_task(
            self.admission.submit(
                lambda: self._streaming_thunk(req, push), **adm_kw
            )
        )
        first_at: float | None = None
        headers_sent = False

        async def emit(piece: str) -> None:
            nonlocal first_at, headers_sent
            if not headers_sent:
                await self._start_sse(writer)
                headers_sent = True
            if first_at is None:
                first_at = time.monotonic()
                self._m_ttft.observe(first_at - t0)
            await self._sse_event(writer, {"text": piece})

        try:
            while True:
                getter = asyncio.create_task(q.get())
                done, _pending = await asyncio.wait(
                    {getter, task}, return_when=asyncio.FIRST_COMPLETED
                )
                if getter in done:
                    await emit(getter.result())
                    continue
                getter.cancel()
                with contextlib.suppress(asyncio.CancelledError):
                    await getter
                break
            # Terminal: flush any pieces the producer pushed after the
            # last wait round, then the summary.
            while not q.empty():
                await emit(q.get_nowait())
            result: GenerationResult = task.result()
        except ConnectionError:
            # The client went away mid-stream (curl ^C, reset): routine,
            # not a server error — stop awaiting the admission outcome
            # (its bookkeeping retires the dispatched work either way)
            # and count a client abort instead of a 500.
            task.cancel()
            with contextlib.suppress(BaseException):
                await task
            self._count("/v1/generate", 499)  # nginx-style client abort
            return
        except Exception as e:  # noqa: BLE001 - mapped to HTTP statuses
            status, doc, headers = self._error_response(e)
            if isinstance(e, (QueueFullError, DrainingError)):
                # Same discard the buffered paths apply: a shed stream
                # did no work, and a 429 storm must not churn the ring.
                trace = _tracing.current_trace()
                self._record_shed(
                    "/v1/generate", trace, self._shed_reason(e)
                )
                if trace is not None:
                    _tracing.trace_store().discard(trace.trace_id)
            if headers_sent:
                # Mid-stream failure: the status line is gone; surface a
                # terminal error event instead.
                with contextlib.suppress(Exception):
                    await self._sse_event(writer, {"error": doc["error"]})
                    await self._sse_done(writer)
            else:
                await self._respond_json(writer, status, doc, headers)
            self._count("/v1/generate", status)
            return
        dt = time.monotonic() - t0
        if not headers_sent:  # empty completion: still a valid stream
            await self._start_sse(writer)
            headers_sent = True
        if first_at is None:
            self._m_ttft.observe(dt)
        self._observe_generation(None, dt, result.num_tokens)
        meta = getattr(result, "meta", None)
        hops = self._hop_breakdown(_tracing.current_trace(), meta, dt)
        if hops:
            # In place for the same /debug/requests identity as the
            # buffered path.
            if isinstance(meta, dict):
                meta["hops"] = hops
            else:
                meta = {"hops": hops}
        await self._sse_event(
            writer,
            {
                "done": True,
                "num_tokens": result.num_tokens,
                "trace_id": self._trace_id(),
                **({"meta": meta} if meta else {}),
            },
        )
        await self._sse_done(writer)
        self._count("/v1/generate", 200)

    async def _streaming_thunk(self, req: GenerationRequest, push):
        """Produce pieces via ``push`` and return the final result."""
        gs = getattr(self.backend, "generate_stream", None)
        if gs is not None:
            parts: list[str] = []
            n = 0
            async for piece in gs(req):
                parts.append(piece)
                n += 1
                push(piece)
            return GenerationResult(text="".join(parts), num_tokens=n)
        result = await self.backend.generate(req)
        for piece in _TOKENISH.findall(result.text):
            push(piece)
        return result

    async def _handle_consensus(self, payload: dict, headers, writer) -> None:
        from llm_consensus_tpu.consensus.coordinator import (
            Coordinator,
            CoordinatorConfig,
        )

        question = payload.get("question")
        if not isinstance(question, str) or not question:
            await self._respond_json(
                writer, 400, {"error": "need a non-empty string 'question'"}
            )
            self._count("/v1/consensus", 400)
            return
        try:
            # Consensus phase -> model routing (PR 18): an explicit
            # "phase_models" map in the payload wins; otherwise a
            # multi-model backend's canonical routing (propose on the
            # draft donor, judge/refine on the default) applies.
            phase_models = payload.get("phase_models")
            if phase_models is None:
                pm_hook = getattr(self.backend, "modelset", None)
                if pm_hook is not None:
                    phase_models = pm_hook.phase_models()
            elif not (
                isinstance(phase_models, dict)
                and all(
                    isinstance(k, str) and isinstance(v, str)
                    for k, v in phase_models.items()
                )
            ):
                raise ValueError(
                    "phase_models must map phase names to model names"
                )
            cfg = CoordinatorConfig(
                max_rounds=int(
                    payload.get("max_rounds", self.config.max_rounds)
                ),
                seed=payload.get("seed", self.config.consensus_seed),
                sampling=self._sampling_from(payload),
                phase_models=phase_models,
            )
            adm_kw = self._cost_kw(
                self._admission_kw(payload, "batch"),
                question,
                cfg.sampling.max_new_tokens,
                members=max(1, len(self.panel)),
            )
        except (TypeError, ValueError, OverflowError) as e:
            await self._respond_json(
                writer, 400, {"error": f"bad request field: {e}"}
            )
            self._count("/v1/consensus", 400)
            return
        trace = _tracing.trace_store().start(
            "/v1/consensus",
            route="/v1/consensus",
            trace_id=self._incoming_tid(headers),
        )
        t0 = time.monotonic()

        async def thunk():
            # A fresh coordinator per request: the protocol state machine
            # is per-question; panel/backend/config are the shared parts.
            # Profiling wraps only this execution, never the queue wait.
            coord = Coordinator(list(self.panel), self.backend, cfg)
            with self._maybe_profile(headers):
                return await coord.run(question)

        try:
            with _tracing.use_trace(trace):
                result = await self.admission.submit(thunk, **adm_kw)
        except Exception as e:  # noqa: BLE001 - mapped to HTTP statuses
            status, doc, hdrs = self._error_response(e)
            if isinstance(e, (QueueFullError, DrainingError)):
                self._record_shed(
                    "/v1/consensus", trace, self._shed_reason(e)
                )
                if trace is not None:
                    _tracing.trace_store().discard(trace.trace_id)
            await self._respond_json(writer, status, doc, hdrs)
            self._count("/v1/consensus", status)
            return
        finally:
            if trace is not None:
                trace.finish()
        dt = time.monotonic() - t0
        self._m_ttft.observe(dt)
        self._m_latency.observe(dt)
        tid = trace.trace_id if trace is not None else None
        # A panel fan-out's spans overlap, so the hop breakdown here
        # is attribution (where the panel's time went), not a
        # wall-clock partition like the single-generation paths.
        hops = self._hop_breakdown(trace, None, dt)
        await self._respond_json(
            writer,
            200,
            {
                "answer": result.answer,
                "rounds": result.rounds,
                "endorsed": result.endorsed,
                "author": result.author,
                "feedback": {k: v.value for k, v in result.feedback.items()},
                "trace_id": tid,
                **({"meta": {"hops": hops}} if hops else {}),
            },
            {"X-Trace-Id": tid} if tid else None,
        )
        self._count("/v1/consensus", 200)

    # -- plumbing -------------------------------------------------------

    def _observe_generation(
        self, ttft: float | None, dt: float, num_tokens: int
    ) -> None:
        if ttft is not None:
            self._m_ttft.observe(ttft)
        self._m_latency.observe(dt)
        if dt > 0 and num_tokens:
            self._m_tps.observe(num_tokens / dt)

    def _error_response(self, e: Exception):
        if isinstance(e, QueueFullError):
            return (
                429,
                {"error": str(e), "retry_after": e.retry_after},
                {"Retry-After": str(max(1, round(e.retry_after)))},
            )
        if isinstance(e, DrainingError):
            return 503, {"error": str(e)}, {"Retry-After": "5"}
        if isinstance(e, DeadlineExpiredError):
            return 504, {"error": str(e)}, {}
        if isinstance(e, BackendError):
            return 502, {"error": str(e)}, {}
        if isinstance(e, ValueError):
            return 400, {"error": str(e)}, {}
        log.exception("unexpected gateway error", exc_info=e)
        return 500, {"error": f"internal error: {e}"}, {}

    def _count(self, route: str, status: int) -> None:
        self._m_requests.labels(route=route, status=str(status)).inc()

    async def _respond_json(
        self, writer, status: int, doc: dict, headers=None
    ) -> None:
        await self._respond_raw(
            writer,
            status,
            json.dumps(doc).encode(),
            "application/json",
            headers,
        )

    async def _respond_raw(
        self, writer, status: int, body: bytes, ctype: str, headers=None
    ) -> None:
        reason = _REASONS.get(status, "Unknown")
        lines = [
            f"HTTP/1.1 {status} {reason}",
            f"Content-Type: {ctype}",
            f"Content-Length: {len(body)}",
            "Connection: close",
        ]
        for k, v in (headers or {}).items():
            lines.append(f"{k}: {v}")
        writer.write(("\r\n".join(lines) + "\r\n\r\n").encode() + body)
        await writer.drain()

    async def _start_sse(self, writer) -> None:
        writer.write(
            b"HTTP/1.1 200 OK\r\n"
            b"Content-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\n"
            b"Connection: close\r\n\r\n"
        )
        await writer.drain()

    async def _sse_event(self, writer, doc: dict) -> None:
        writer.write(f"data: {json.dumps(doc)}\n\n".encode())
        await writer.drain()

    async def _sse_done(self, writer) -> None:
        writer.write(b"data: [DONE]\n\n")
        await writer.drain()


class GatewayThread:
    """Run a :class:`Gateway` on a dedicated event loop in a daemon
    thread — the embedding/test harness (the pytest suite drives the
    gateway from synchronous code; a REPL process can serve on the side).

    ``start()`` blocks until the port is bound; ``drain()`` triggers the
    graceful SIGTERM path from any thread and joins."""

    def __init__(self, gateway: Gateway):
        self.gateway = gateway
        self._stop: asyncio.Event | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._started = threading.Event()
        self._finished = threading.Event()
        self._error: BaseException | None = None
        self._thread = threading.Thread(
            target=self._run, name="gateway", daemon=True
        )

    @property
    def port(self) -> int:
        assert self.gateway.port is not None
        return self.gateway.port

    def _run(self) -> None:
        async def main():
            self._loop = asyncio.get_running_loop()
            self._stop = asyncio.Event()
            try:
                await self.gateway.start()
            finally:
                self._started.set()
            await self._stop.wait()
            await self.gateway.drain()

        try:
            asyncio.run(main())
        except BaseException as e:  # noqa: BLE001 - surfaced on start/drain
            self._error = e
        finally:
            self._started.set()
            self._finished.set()

    def start(self) -> "GatewayThread":
        self._thread.start()
        self._started.wait(timeout=30)
        if self.gateway.port is None:
            raise RuntimeError(f"gateway failed to start: {self._error!r}")
        return self

    def drain(self, timeout: float = 60) -> None:
        """Graceful shutdown from any thread; joins the loop thread."""
        if self._loop is not None and not self._finished.is_set():
            self._loop.call_soon_threadsafe(
                lambda: self._stop.set() if self._stop else None
            )
        self._finished.wait(timeout=timeout)
        self._thread.join(timeout=timeout)
        if self._error is not None:
            raise RuntimeError("gateway thread failed") from self._error
