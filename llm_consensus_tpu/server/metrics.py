"""Process-wide metrics registry with Prometheus text exposition.

The repo's only observability before this module was the ad-hoc
:class:`llm_consensus_tpu.utils.tracing.Tracer` (in-process spans, pull
by Python API). Serving needs the standard scrape surface instead: a
registry of counters/gauges/histograms that the gateway exports at
``GET /metrics`` in the Prometheus text format (version 0.0.4), so the
same dashboards that watch any other fleet watch this one.

Stdlib only, thread-safe (the scheduler/batcher mutate metrics from
their worker threads while the asyncio gateway renders), and dependency
free so the hot serving modules (:mod:`serving.scheduler`,
:mod:`serving.continuous`, :mod:`consensus.coordinator`) can import it
without pulling in the gateway or jax.

Metric families are get-or-create by name — two schedulers in one
process share one ``scheduler_requests_total`` — and support optional
labels (``family.labels(priority="interactive").inc()``) for the
per-priority admission series.
"""

from __future__ import annotations

import threading
from bisect import bisect_left

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "LATENCY_BUCKETS",
    "THROUGHPUT_BUCKETS",
    "OCCUPANCY_BUCKETS",
    "INSTANCE_FAMILIES",
    "SERVING_SUBMITTED",
    "SERVING_COMPLETED",
    "SERVING_TOKENS",
    "SERVING_STEPS",
    "SERVING_WAITING",
    "SERVING_ACTIVE",
    "SERVING_OCCUPANCY",
    "SCHED_SUBMITTED",
    "SCHED_DEPTH",
    "SCHED_OCCUPANCY",
    "CONSENSUS_QUESTIONS",
    "CONSENSUS_ROUNDS",
    "CONSENSUS_UNANIMOUS",
    "CONSENSUS_FORCED",
    "CONSENSUS_ROUND_SECONDS",
    "GATEWAY_TTFT",
    "DECODE_STEP_SECONDS",
    "SCHED_OVERHEAD_SECONDS",
    "PIPELINE_FLUSHES",
    "DISPATCH_INFLIGHT",
    "DEVICE_PROGRAMS",
    "RAGGED_ROWS",
    "SPEC_DRAFT_TOKENS",
    "SPEC_ACCEPTED_TOKENS",
    "SPEC_ACCEPTANCE",
    "SPEC_VERIFIED_TOKENS",
    "SPEC_XMODEL_ACCEPTED_TOKENS",
    "SPEC_XMODEL_COVERAGE",
    "MODEL_REQUESTS",
    "MODEL_TOKENS",
    "ACCEPTANCE_BUCKETS",
    "TRACE_DROPPED",
    "FLIGHT_DROPPED",
    "TBT_SECONDS",
    "PROGRAM_MBU",
    "PREFIX_PAGES_SHARED",
    "PREFIX_PAGES_COPIED",
    "PREFIX_LOOKUPS",
    "PREFIX_HITS",
    "PREFILL_STALL_SECONDS",
    "SHARED_KV_BYTES_SAVED",
    "DECODE_GROUP_SIZE",
    "KV_OFFLOAD_DEMOTED",
    "KV_OFFLOAD_RESTORED",
    "KV_OFFLOAD_DROPPED",
    "KV_RESTORE_SECONDS",
    "KV_HOST_TIER_BYTES",
    "REPLICA_ROUTED",
    "REPLICA_PROGRAMS",
    "REPLICA_PREFIX_HIT_RATE",
    "REPLICA_PREEMPTIONS",
    "REPLICA_SHARED_STORE_BYTES",
    "REMOTE_STORE_BYTES",
    "REMOTE_STORE_ERRORS",
    "REMOTE_STORE_RTT",
    "ROLE_HANDOFFS",
]

# Seconds: spans ~1 ms .. 2 min, the TTFT / request-latency range of a
# CPU FakeBackend test and a real chip alike.
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)
# Tokens/sec: spans a struggling CPU run .. a healthy chip fleet.
THROUGHPUT_BUCKETS = (
    1.0, 10.0, 50.0, 100.0, 500.0, 1000.0, 5000.0,
    10_000.0, 50_000.0, 100_000.0, 500_000.0,
)
# Batch-occupancy: requests packed per executed program/step.
OCCUPANCY_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)
# Fractions in [0, 1]: speculative-decoding acceptance per verify round.
ACCEPTANCE_BUCKETS = (0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 1.0)


def _fmt(v: float) -> str:
    """Prometheus sample value: integers render bare, floats as repr."""
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label escaping: backslash, double quote,
    AND line feed (``\\n``) — an unescaped newline in a label value ends
    the sample line mid-token and corrupts the whole exposition."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _label_str(labels: tuple[tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        '{}="{}"'.format(k, _escape_label_value(v)) for k, v in labels
    )
    return "{" + inner + "}"


class _Child:
    """One labeled sample set; the lock is shared with the family."""

    def __init__(self, lock: threading.Lock):
        self._lock = lock


class Counter(_Child):
    """Monotonically increasing count."""

    def __init__(self, lock: threading.Lock):
        super().__init__(lock)
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge(_Child):
    """Arbitrary settable value (queue depths, slot occupancy)."""

    def __init__(self, lock: threading.Lock):
        super().__init__(lock)
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._value


class Histogram(_Child):
    """Cumulative-bucket histogram (Prometheus semantics)."""

    def __init__(self, lock: threading.Lock, buckets: tuple[float, ...]):
        super().__init__(lock)
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError(f"buckets must be sorted and non-empty: {buckets}")
        self.buckets = tuple(float(b) for b in buckets)
        # One slot per finite bucket + the +Inf overflow slot.
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        i = bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def cumulative(self) -> list[tuple[float, int]]:
        """[(le, cumulative_count), ...] ending with (inf, total)."""
        out, total = [], 0
        with self._lock:
            counts = list(self._counts)
        for b, c in zip(self.buckets, counts):
            total += c
            out.append((b, total))
        out.append((float("inf"), total + counts[-1]))
        return out


class _Family:
    """A named metric and its labeled children."""

    def __init__(self, name: str, help_: str, kind: str, **kw):
        self.name = name
        self.help = help_
        self.kind = kind  # "counter" | "gauge" | "histogram"
        self._kw = kw
        self._lock = threading.Lock()
        self._children: dict[tuple[tuple[str, str], ...], _Child] = {}

    def _make(self) -> _Child:
        if self.kind == "counter":
            return Counter(self._lock)
        if self.kind == "gauge":
            return Gauge(self._lock)
        return Histogram(self._lock, self._kw["buckets"])

    def labels(self, **labels: str):
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make()
        return child

    # Label-less convenience: the family acts as its own single child.
    def _default(self):
        return self.labels()

    def inc(self, n: float = 1.0) -> None:
        self._default().inc(n)

    def dec(self, n: float = 1.0) -> None:
        self._default().dec(n)

    def set(self, v: float) -> None:
        self._default().set(v)

    def observe(self, v: float) -> None:
        self._default().observe(v)

    @property
    def value(self) -> float:
        return self._default().value

    @property
    def count(self) -> int:
        return self._default().count

    @property
    def sum(self) -> float:
        return self._default().sum

    def cumulative(self):
        return self._default().cumulative()

    def render(self) -> list[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        with self._lock:
            children = list(self._children.items())
        for key, child in sorted(children):
            ls = _label_str(key)
            if isinstance(child, Histogram):
                for le, cum in child.cumulative():
                    le_s = "+Inf" if le == float("inf") else _fmt(le)
                    lines.append(
                        f"{self.name}_bucket"
                        f"{_label_str(key + (('le', le_s),))} {cum}"
                    )
                lines.append(f"{self.name}_sum{ls} {_fmt(child.sum)}")
                lines.append(f"{self.name}_count{ls} {child.count}")
            else:
                lines.append(f"{self.name}{ls} {_fmt(child.value)}")
        return lines


class MetricsRegistry:
    """Get-or-create registry of metric families.

    One process-wide instance (:data:`REGISTRY`) backs the default
    instrumentation; tests that need isolation construct their own and
    pass it to the gateway/admission layers.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _get(self, name: str, help_: str, kind: str, **kw) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(name, help_, kind, **kw)
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}"
                )
            return fam

    def counter(self, name: str, help_: str = "") -> _Family:
        return self._get(name, help_, "counter")

    def gauge(self, name: str, help_: str = "") -> _Family:
        return self._get(name, help_, "gauge")

    def histogram(
        self,
        name: str,
        help_: str = "",
        buckets: tuple[float, ...] = LATENCY_BUCKETS,
    ) -> _Family:
        return self._get(name, help_, "histogram", buckets=tuple(buckets))

    def get(self, name: str) -> _Family | None:
        return self._families.get(name)

    def render(self) -> str:
        """The full exposition — Prometheus text format 0.0.4."""
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        lines: list[str] = []
        for fam in fams:
            lines.extend(fam.render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict[str, float]:
        """Flat {name[{labels}]: value} map of counters/gauges plus
        histogram ``_count``/``_sum`` — the assertion surface for tests."""
        out: dict[str, float] = {}
        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            with fam._lock:
                children = list(fam._children.items())
            for key, child in children:
                ls = _label_str(key)
                if isinstance(child, Histogram):
                    out[f"{fam.name}_count{ls}"] = child.count
                    out[f"{fam.name}_sum{ls}"] = child.sum
                else:
                    out[f"{fam.name}{ls}"] = child.value
        return out


#: The process-wide default registry (scrape target of ``GET /metrics``).
REGISTRY = MetricsRegistry()


# ---------------------------------------------------------------------------
# Canonical serving-gateway families (PR 2: shared-prefix paged serving).
# Defined HERE — not at their instrumentation sites — so the canonical
# scrape surface is enumerable in one place; the continuous batcher
# imports and feeds them, and they ride REGISTRY into ``GET /metrics``.
# ---------------------------------------------------------------------------

#: Pages mapped into an admission's table from the prefix registry
#: instead of being re-prefilled (each one is page_size tokens of
#: prompt FLOPs the chip never re-spends).
PREFIX_PAGES_SHARED = REGISTRY.counter(
    "gateway_prefix_pages_shared",
    "KV pages mapped from the shared-prefix registry at admission",
)
#: Boundary pages copied (copy-on-write) instead of recomputed.
PREFIX_PAGES_COPIED = REGISTRY.counter(
    "gateway_prefix_pages_copied",
    "Partially-shared boundary pages copied at admission (CoW)",
)
#: Prefix-registry hit rate = hits / lookups.
PREFIX_LOOKUPS = REGISTRY.counter(
    "gateway_prefix_lookups_total",
    "Prefix-registry lookups (one per continuous-batcher admission)",
)
PREFIX_HITS = REGISTRY.counter(
    "gateway_prefix_hits_total",
    "Prefix-registry lookups that mapped or copied at least one page",
)
#: How long each prefill work unit kept the decode loop waiting. Under
#: chunked prefill this is bounded by one chunk's compute; the legacy
#: blocking path records the WHOLE prompt prefill here — the stall the
#: chunked scheduler exists to remove.
PREFILL_STALL_SECONDS = REGISTRY.histogram(
    "gateway_prefill_stall_seconds",
    "Decode-loop stall per prefill work unit (chunk or blocking prefill)",
    buckets=LATENCY_BUCKETS,
)
#: KV bytes the group-aware decode kernel did NOT re-read from HBM
#: (PR 3: shared-prefix decode attention). Each decode step reads a
#: group's shared-prefix pages once instead of once per member; this
#: counts the skipped (members - 1) * shared_tokens * bytes-per-token
#: reads — the dedup PR 2's page sharing made possible in memory, now
#: realized in bandwidth. Incremented only when the grouped program
#: actually ran (jnp-path and windowed-config fallbacks save nothing).
SHARED_KV_BYTES_SAVED = REGISTRY.counter(
    "gateway_shared_kv_bytes_saved_total",
    "KV-cache HBM bytes deduped by group-aware decode attention",
)
#: Members in the largest active decode group at the most recent step
#: (0 = no group — the ungrouped program ran). The panel's N-fanout
#: shows up here as N.
DECODE_GROUP_SIZE = REGISTRY.gauge(
    "gateway_decode_group_size",
    "Largest shared-prefix decode group at the last decode step",
)
#: Hierarchical KV cache (PR 4): the host-RAM tier under the prefix
#: registry. Eviction DEMOTES registry-only prefix pages to pinned host
#: buffers instead of dropping them; a later same-prefix admission
#: RESTORES them (async device_put between decode steps) instead of
#: re-prefilling; host-budget overflow DROPS the LRU page (the tier
#: below host RAM is recompute).
KV_OFFLOAD_DEMOTED = REGISTRY.counter(
    "gateway_kv_offload_demoted_pages_total",
    "Prefix-registry pages demoted to the host-RAM KV tier on eviction",
)
KV_OFFLOAD_RESTORED = REGISTRY.counter(
    "gateway_kv_offload_restored_pages_total",
    "Host-tier KV pages restored to the device pool at admission",
)
KV_OFFLOAD_DROPPED = REGISTRY.counter(
    "gateway_kv_offload_dropped_pages_total",
    "Host-tier KV pages dropped (LRU under the byte budget, or oversize)",
)
#: Host→device promotion latency per page, install included — the
#: number that must beat re-prefilling page_size tokens for the tier to
#: pay for itself.
KV_RESTORE_SECONDS = REGISTRY.histogram(
    "gateway_kv_restore_seconds",
    "Per-page host-to-device KV restore latency (device_put + install)",
    buckets=LATENCY_BUCKETS,
)
#: Host-tier occupancy (bytes resident right now, vs the configured
#: ContinuousConfig.host_cache_bytes budget).
KV_HOST_TIER_BYTES = REGISTRY.gauge(
    "gateway_kv_host_tier_bytes",
    "Bytes resident in the host-RAM KV offload tier",
)


# ---------------------------------------------------------------------------
# Serving / scheduler / consensus process-wide families (PR 5: moved
# here from their instrumentation modules so the canonical surface is
# enumerable in ONE file — scripts/check_metrics.py enforces that every
# family those modules feed is declared here and documented in the
# README observability table).
# ---------------------------------------------------------------------------

SERVING_SUBMITTED = REGISTRY.counter(
    "serving_requests_total", "Requests submitted to the continuous batcher"
)
SERVING_COMPLETED = REGISTRY.counter(
    "serving_completed_total", "Requests retired by the continuous batcher"
)
SERVING_TOKENS = REGISTRY.counter(
    "serving_generated_tokens_total", "Tokens generated (incl. EOS)"
)
SERVING_STEPS = REGISTRY.counter(
    "serving_decode_steps_total", "Device decode steps executed"
)
SERVING_WAITING = REGISTRY.gauge(
    "serving_waiting", "Requests waiting for a continuous-batcher slot"
)
SERVING_ACTIVE = REGISTRY.gauge(
    "serving_active_slots", "Continuous-batcher slots currently decoding"
)
SERVING_OCCUPANCY = REGISTRY.histogram(
    "serving_slot_occupancy",
    "Active slots per decode step (batch occupancy)",
    buckets=OCCUPANCY_BUCKETS,
)
SCHED_SUBMITTED = REGISTRY.counter(
    "scheduler_requests_total", "Requests submitted to the batch scheduler"
)
SCHED_DEPTH = REGISTRY.gauge(
    "scheduler_queue_depth", "Requests pending in the batch scheduler"
)
SCHED_OCCUPANCY = REGISTRY.histogram(
    "scheduler_batch_occupancy",
    "Requests packed per executed scheduler batch",
    buckets=OCCUPANCY_BUCKETS,
)
CONSENSUS_QUESTIONS = REGISTRY.counter(
    "consensus_questions_total", "Questions driven through the protocol"
)
CONSENSUS_ROUNDS = REGISTRY.histogram(
    "consensus_rounds",
    "Evaluation rounds to termination (unanimity or the round cap)",
    buckets=(1, 2, 3, 4, 5, 6, 8, 10, 15, 20),
)
CONSENSUS_UNANIMOUS = REGISTRY.counter(
    "consensus_unanimous_total", "Questions ending in genuine unanimity"
)
CONSENSUS_FORCED = REGISTRY.counter(
    "consensus_forced_total", "Questions force-terminated at the round cap"
)


# ---------------------------------------------------------------------------
# Request-scoped tracing (PR 5): histograms derived from the same
# instrumentation points that record trace spans, so ``/metrics``,
# ``stats()``, and ``GET /debug/traces`` stay in lockstep.
# ---------------------------------------------------------------------------

#: Canonical declaration of the gateway's TTFT histogram (instances
#: with isolated registries re-create it per registry; see
#: INSTANCE_FAMILIES below).
GATEWAY_TTFT = REGISTRY.histogram(
    "gateway_ttft_seconds",
    "Time from request arrival to first token byte",
)
#: One observation per decode-step device program: dispatch through the
#: host fetch of the sampled tokens (the true device step latency the
#: per-trace "decode_step" spans record).
DECODE_STEP_SECONDS = REGISTRY.histogram(
    "gateway_decode_step_seconds",
    "Continuous-batcher decode-step device latency (dispatch to fetch)",
)
#: UN-OVERLAPPED host time per decode dispatch — retirement, admission,
#: prefill-chunk scheduling, group rebuilds that no in-flight decode
#: program hid. Under pipelined dispatch (PR 6, pipeline_depth > 1) a
#: dispatch issued while a program is still in flight did its host work
#: in that program's shadow and observes 0; at depth 1 this reduces to
#: the classic host-gap-between-steps. The scheduler overhead the
#: decode roofline never shows; idle waits do not count.
SCHED_OVERHEAD_SECONDS = REGISTRY.histogram(
    "gateway_sched_overhead_seconds",
    "Un-overlapped host time per decode dispatch (scheduling overhead)",
)
#: Pipelined decode dispatch (PR 6): decode programs dispatched but not
#: yet token-fetched (0..pipeline_depth), and the drains forced by
#: operations that need a stable cache underneath them (host-tier page
#: restores, CoW boundary copies, legacy dense prefill). A flush-heavy
#: workload is paying pipeline restarts for its admission pattern.
DISPATCH_INFLIGHT = REGISTRY.gauge(
    "gateway_dispatch_inflight",
    "Decode programs dispatched but not yet fetched",
)
PIPELINE_FLUSHES = REGISTRY.counter(
    "gateway_pipeline_flushes_total",
    "Decode-pipeline drains before stable-cache operations",
)
#: Fused scheduler step (PR 8): device programs the scheduler loop
#: dispatched, labeled ``kind="fused"`` (one program carrying the
#: step's decode rows AND a prefill chunk — the ragged-attention
#: target state), ``kind="decode"`` (decode rows only),
#: ``kind="prefill"`` (a standalone prefill program: a chunk with no
#: decode batch to ride, or the legacy dense path), ``kind="spec"``
#: (PR 9: one speculative draft+verify+accept round), or
#: ``kind="draft"`` (the draft model's mirror of a prefill). Programs
#: per scheduler iteration == 1 is the fusion working; 2 is the
#: pre-ragged "one chunk program + one decode program" serialization.
DEVICE_PROGRAMS = REGISTRY.counter(
    "gateway_device_programs_total",
    "Device programs dispatched by the continuous-batcher scheduler loop",
)
#: Rows sharing one ragged device program: active decode rows plus the
#: fused prefill-chunk lane (fused/decode programs only). The mixed
#: prefill+decode occupancy of the one kernel.
RAGGED_ROWS = REGISTRY.histogram(
    "gateway_ragged_rows_per_program",
    "Rows (decode rows + fused prefill-chunk lanes) per device program",
    buckets=OCCUPANCY_BUCKETS,
)
#: Multi-round on-device decode (PR 12): decode rounds folded into one
#: dispatched program. A plain decode/fused program under
#: ``ContinuousConfig.decode_rounds`` R runs up to R decode rounds —
#: stop scan, sampling, emit-count/length bookkeeping on device, frozen
#: rows masked — before the host fetches; a speculative verify round
#: counts 1 (its emit is already multi-token). Rounds count once per
#: PROGRAM, not per row: ``device_rounds_total`` over the
#: decode-advancing ``gateway_device_programs_total`` is the realized
#: rounds per program (→ R when multi-round engages), and device
#: programs per generated token drops ~R× at R for a fixed batch
#: shape (its absolute value carries the 1/batch-rows factor) — the
#: cross-check the bench A/B leg gates. Histogram: the per-program
#: round count at dispatch (R, or 1 when a row's stop sequences have
#: no bounded device screen and the window collapses to the
#: host-checked cadence).
DECODE_ROUNDS_PER_PROGRAM = REGISTRY.histogram(
    "gateway_decode_rounds_per_program",
    "Decode rounds folded into one dispatched device program",
    buckets=OCCUPANCY_BUCKETS,
)
DEVICE_ROUNDS = REGISTRY.counter(
    "gateway_device_rounds_total",
    "Decode rounds dispatched across all decode-advancing device programs",
)
#: Speculative decoding inside the continuous batcher (PR 9). The
#: draft proposes ``spec_k`` tokens per round — ONE stream per
#: shared-prefix panel group (mates whose committed text still agrees
#: with their donor's reuse its stream), so ``drafted`` counts k per
#: STREAM, not per row; the target verifies all rows' drafts through
#: the ragged k+1-token rows of one device program and the leviathan
#: accept rule emits the accepted prefix + a correction/bonus token.
#: acceptance = accepted / (k * rows) per round; verified_tokens is the
#: last spec program's total emitted tokens (tokens-per-device-program
#: > 1 is speculation beating the one-token-per-program roofline).
SPEC_DRAFT_TOKENS = REGISTRY.counter(
    "gateway_spec_draft_tokens_total",
    "Draft tokens proposed by speculative decoding (k per stream/round)",
)
SPEC_ACCEPTED_TOKENS = REGISTRY.counter(
    "gateway_spec_accepted_tokens_total",
    "Draft tokens the target's verify rounds accepted",
)
SPEC_ACCEPTANCE = REGISTRY.histogram(
    "gateway_spec_acceptance",
    "Per-round draft acceptance fraction (accepted / (spec_k * rows))",
    buckets=ACCEPTANCE_BUCKETS,
)
SPEC_VERIFIED_TOKENS = REGISTRY.gauge(
    "gateway_spec_verified_tokens",
    "Tokens emitted by the most recent speculative verify program",
)
#: Cross-model speculation (PR 18): draft tokens accepted when the
#: draft rode a vocab-alignment remap (serving/vocab_align.py) — a
#: DIFFERENT tokenizer than the target's. Counted at the same fetch
#: site as gateway_spec_accepted_tokens_total (the cross-model counts
#: are a subset); the coverage gauge is the construction-time
#: exact-match fraction the pairing engaged with, labeled by the
#: target ``model`` so a heterogeneous ModelSet's pairings read apart.
SPEC_XMODEL_ACCEPTED_TOKENS = REGISTRY.counter(
    "gateway_spec_cross_model_accepted_tokens_total",
    "Draft tokens accepted through a cross-model vocab remap",
)
SPEC_XMODEL_COVERAGE = REGISTRY.gauge(
    "gateway_spec_cross_model_coverage",
    "Exact-match vocab coverage of the engaged cross-model draft pairing",
)
#: Multi-model serving plane (PR 18, serving/modelset.py): one gateway
#: fronting N independent engines. Labeled ``model=<member name>`` —
#: the shared metrics plane's per-model split (requests dispatched to
#: each member and the tokens it generated), mirrored into
#: ``ModelSet.stats()`` for the bench.
MODEL_REQUESTS = REGISTRY.counter(
    "gateway_model_requests_total",
    "Requests dispatched to each ModelSet member (label: model)",
)
MODEL_TOKENS = REGISTRY.counter(
    "gateway_model_tokens_total",
    "Tokens generated by each ModelSet member (label: model)",
)
#: Consensus protocol phase latency, labeled
#: ``phase="propose"|"evaluate"|"refine"`` — one observation per phase
#: execution (an evaluation round and its refinement observe
#: separately). Mirrors the per-trace "consensus_round" spans.
CONSENSUS_ROUND_SECONDS = REGISTRY.histogram(
    "consensus_round_seconds",
    "Consensus phase latency by phase (propose/evaluate/refine)",
)
#: Ring-buffer pressure in the tracing layer, labeled
#: ``kind="span"`` (a span evicted/refused by a full Tracer ring or a
#: full per-trace span budget) or ``kind="trace"`` (a whole trace
#: evicted from the bounded TraceStore). Fed via the tracing drop hook
#: wired below — the lockstep contract between the two surfaces.
TRACE_DROPPED = REGISTRY.counter(
    "gateway_trace_dropped_total",
    "Spans/traces dropped by the bounded tracing ring buffers",
)


# ---------------------------------------------------------------------------
# Serving flight recorder + roofline attribution (PR 10).
# ---------------------------------------------------------------------------

#: Events evicted from the flight recorder's bounded ring
#: (:mod:`llm_consensus_tpu.serving.flight`) — the recorder keeps the
#: newest ``capacity`` scheduler events and counts what it forgot, so a
#: truncated ``GET /debug/flight`` export is detectable, never silent.
FLIGHT_DROPPED = REGISTRY.counter(
    "gateway_flight_dropped_total",
    "Flight-recorder events evicted from the bounded ring",
)
#: Time between consecutive generated tokens as the HOST observes them
#: (one observation per generated token past a request's first; tokens
#: that land in the same program fetch — steps_per_sync > 1 chunks,
#: accepted speculative runs — observe 0 for all but the first, which
#: is exactly the bursty arrival a streaming client sees). The
#: per-request p50/p99 summary rides ``/debug/requests`` and the
#: response meta; TTFT for the first token stays in
#: ``gateway_ttft_seconds`` (gateway side) + the batcher's stats()
#: ``ttft_seconds_*`` mirror (submit-to-first-token).
TBT_SECONDS = REGISTRY.histogram(
    "gateway_tbt_seconds",
    "Inter-token gap per generated token (time-between-tokens)",
)
#: Model-bandwidth-utilization per device-program kind, labeled
#: ``kind="fused"|"decode"|"spec"|"prefill"``: the static cost model's
#: HBM bytes for the most recent fetched program of that kind (weight
#: bytes + KV page bytes actually touched, group-shared reads counted
#: once — :func:`llm_consensus_tpu.models.transformer.program_hbm_cost`)
#: divided by its measured wall time and by the configured peak
#: bandwidth (``ContinuousConfig.hbm_gbps``; 0 disables the gauge —
#: stats() still exposes the modeled-bytes / measured-seconds sums per
#: kind so MBU can be derived offline). ~1.0 means the program kind is
#: at the weights+KV roofline; meaningful on the chip only (a CPU
#: "MBU" against an HBM peak is a smoke-test plumbing check).
PROGRAM_MBU = REGISTRY.gauge(
    "gateway_program_mbu",
    "Model-bandwidth-utilization of the last device program, by kind",
)


# ---------------------------------------------------------------------------
# Mesh-native serving (PR 13).
# ---------------------------------------------------------------------------

#: The continuous batcher's serving mesh topology, labeled
#: ``axis="data"`` (slot/page-pool shards — each data shard owns a
#: contiguous slot block and its page range) and ``axis="model"``
#: (tensor-parallel shards — kv heads and the MLP hidden split). 1 on
#: both axes = a single-chip batcher. Purely descriptive: every
#: serving feature (fused ragged dispatch, grouped prefix attention,
#: multi-round decode, speculative decoding, the host KV tier) engages
#: at any value since PR 13 — the README Serving engage matrix is the
#: authoritative table. Mirrored in the batcher's stats() as
#: ``mesh_data_shards`` / ``mesh_model_shards`` (lockstep tested).
MESH_SHARDS = REGISTRY.gauge(
    "gateway_mesh_shards",
    "Serving mesh shard count by axis (1 = unsharded)",
)


# ---------------------------------------------------------------------------
# Prefix-affinity replica fleet (PR 14): N continuous-batcher replicas
# behind one gateway (serving/fleet.py), routed by prefix affinity with
# preempt-to-host-tier instead of 429s. All labeled ``replica="<idx>"``
# except the shared-store gauge (the store is fleet-scoped, one per
# ReplicaSet).
# ---------------------------------------------------------------------------

#: One increment per routed request, labeled ``replica`` and ``reason``
#: (``"prefix"`` — the replica held the longest resident chain;
#: ``"load"`` — no affinity anywhere, least modeled-cost replica won;
#: ``"rebalance"`` — the affinity owner was congested, the chain was
#: exported through the shared store and the request re-homed;
#: ``"random"`` — the bench's control policy). affinity/total is the
#: routed prefix-affinity rate the --serve-replicas bench leg gates.
REPLICA_ROUTED = REGISTRY.counter(
    "gateway_replica_routed_total",
    "Requests routed to each fleet replica, by routing reason",
)
#: Device programs each replica's scheduler loop has dispatched (the
#: sum of its gateway_device_programs_total contributions — that
#: family is process-global, so the per-replica split lives here).
#: Refreshed at route/preempt time and on every fleet stats() pull.
REPLICA_PROGRAMS = REGISTRY.gauge(
    "gateway_replica_programs",
    "Device programs dispatched by each fleet replica",
)
#: Each replica's prefix-registry hit rate (hits / lookups over
#: committed admissions). Affinity routing drives this toward the
#: panel's share rate on the chain-owning replica; random routing
#: dilutes it fleet-wide. Refresh cadence as gateway_replica_programs.
REPLICA_PREFIX_HIT_RATE = REGISTRY.gauge(
    "gateway_replica_prefix_hit_rate",
    "Per-replica prefix-registry hit rate (hits / lookups)",
)
#: Router-requested preemptions per replica: overload moments where
#: resident chains were demoted to the shared host tier (freeing
#: device pages) so the storm could be admitted instead of shed.
REPLICA_PREEMPTIONS = REGISTRY.counter(
    "gateway_replica_preemptions_total",
    "Router-requested preempt-to-host-tier events per fleet replica",
)
#: Bytes resident in the FLEET-SCOPED host page store (one per
#: ReplicaSet; any replica can restore any chain). The per-batcher
#: gateway_kv_host_tier_bytes gauge tracks the same store when shared.
REPLICA_SHARED_STORE_BYTES = REGISTRY.gauge(
    "gateway_replica_shared_store_bytes",
    "Bytes resident in the fleet-shared host page store",
)


# ---------------------------------------------------------------------------
# Roofline-adaptive runtime control (PR 15, serving/control.py): the
# PR-10 cost model closed into a feedback loop. Labeled
# ``knob="spec_k"|"rounds"|"chunk"|"depth"``. Process-global like
# gateway_device_programs_total: a replica FLEET's controllers all
# write the same families (last writer wins on the gauge) — the
# per-replica split lives in the fleet stats() ``per_replica`` list,
# whose batcher stats carry each controller's ``autotune_*`` mirrors,
# exactly the PR-14 convention for the per-replica program counts.
# ---------------------------------------------------------------------------

#: One increment per knob decision that CHANGED the knob's value
#: (steady-state re-decisions are silent, like spec_flip flight
#: events): spec_k shrink/regrow/disengage (value 0 = speculation
#: disengaged until a probe re-accepts), an adaptive-R window cap, a
#: chunk-width flip, a pipeline-depth probe/commit/revert. Mirrored in
#: the batcher's stats() as ``autotune_decisions_<knob>`` (lockstep
#: tested); each change is also an ``autotune`` flight event.
AUTOTUNE_DECISIONS = REGISTRY.counter(
    "gateway_autotune_decisions_total",
    "Adaptive-controller knob decisions that changed a knob value",
)
#: The last decided effective value per knob (spec_k's 0 =
#: disengaged). Pinned knobs (ControlConfig.tune_* = False) never set
#: their label. stats() mirror: ``autotune_<knob>`` (-1 = no decision
#: yet).
AUTOTUNE_VALUE = REGISTRY.gauge(
    "gateway_autotune_value",
    "Last effective knob value decided by the adaptive controller",
)


# ---------------------------------------------------------------------------
# Disaggregated prefill/decode serving (PR 16, serving/remote_store.py +
# serving/disagg.py): the fleet-scoped host page store becomes a
# length-prefixed TCP/UDS transport so the router/store seam spans
# processes and hosts, and replicas specialize into prefill/decode
# ROLES that hand finished chains through it. Like the autotune
# families above, these are process-global, last-writer-wins across a
# roled fleet — the per-ROLE split lives in the fleet stats()
# ``per_replica`` list (each entry names its replica's ``role``), the
# same PR-14/15 convention for per-replica program counts and autotune
# mirrors.
# ---------------------------------------------------------------------------

#: Bytes resident in the AUTHORITATIVE store behind a RemotePageStore
#: client, as of the client's last successful exchange (every response
#: frame piggybacks the server store's counters, so reading this never
#: costs a network round trip — the admission overflow hook reads
#: headroom on the event loop).
REMOTE_STORE_BYTES = REGISTRY.gauge(
    "gateway_remote_store_bytes",
    "Bytes resident in the remote host page store (last-exchange view)",
)
#: Remote page-store operations that failed (connect refused, peer
#: disconnect mid-frame, client timeout against a slow peer). Every
#: failure degrades to a local MISS — get None / touch False / put
#: dropped — so the worker loop recomputes instead of wedging; a
#: climbing rate with a flat restored-pages rate is a dead peer.
REMOTE_STORE_ERRORS = REGISTRY.counter(
    "gateway_remote_store_errors_total",
    "Remote page-store operations that failed and degraded to a miss",
)
#: Wall-clock round-trip per successful remote store exchange (request
#: frame out to response frame parsed). Page payloads ride put/get, so
#: compare against gateway_kv_restore_seconds to see what the wire adds
#: to a restore.
REMOTE_STORE_RTT = REGISTRY.histogram(
    "gateway_remote_store_rtt_seconds",
    "Round-trip latency per successful remote page-store exchange",
    buckets=LATENCY_BUCKETS,
)
#: Chains handed from a prefill-role replica to a decode-role replica
#: through the (shared or remote) page store: the prefill replica ran
#: admission + chunked prefill, exported the finished chain via the
#: PR-14 export path, and a decode replica's admission restored it —
#: zero header pages re-prefilled on the decode side.
ROLE_HANDOFFS = REGISTRY.counter(
    "gateway_role_handoffs_total",
    "Prefill-to-decode chain handoffs through the fleet page store",
)


# ---------------------------------------------------------------------------
# Zero-copy pipelined KV movement plane (PR 17, serving/remote_store.py
# wire v2 + streamed handoff + route-driven restore prefetch). Process-
# global across a fleet like the PR-16 families above; per-replica
# prefetch splits live in each batcher's stats() mirrors.
# ---------------------------------------------------------------------------

#: Plane PAYLOAD bytes moved over the page-store wire by this process's
#: RemotePageStore clients, labeled ``dir="tx"|"rx"`` (tx = puts out,
#: rx = get/get_run planes in). Framing/header overhead is excluded so
#: the rate divides cleanly into pages/s; divide by
#: gateway_remote_store_rtt_seconds_sum for effective wire throughput.
TRANSFER_BYTES = REGISTRY.counter(
    "gateway_transfer_bytes_total",
    "KV plane payload bytes moved over the page-store wire by direction",
)
#: Route-driven restore prefetch outcomes, labeled ``event=``:
#: ``fetched`` pages pulled store->host ahead of admission; ``hit``
#: pages admission consumed from the prefetch cache (each one is a
#: store round trip shaved off the restore flush); ``expired`` pages
#: evicted from the bounded cache before any admission claimed them
#: (wasted transfer — a high expired:fetched ratio means the router is
#: prefetching chains that never arrive, or the cache cap is too
#: small). Admission falls through expired entries to the store and
#: then to recompute — never corrupt, only slower.
KV_PREFETCH = REGISTRY.counter(
    "gateway_kv_prefetch_total",
    "Route-driven KV restore prefetch page outcomes",
)
#: Wall-clock from a decode replica claiming a cold chain to the chain
#: fully exported and restorable (the prefill->decode handoff the
#: HandoffCoordinator runs). The streamed path overlaps export with
#: prefill compute, so this should hug the prefill time itself; the
#: PR-16 synchronous path pays prefill + whole-chain export serially.
HANDOFF_SECONDS = REGISTRY.histogram(
    "gateway_handoff_seconds",
    "Prefill-to-decode chain handoff wall-clock (claim to exported)",
    buckets=LATENCY_BUCKETS,
)


# ---------------------------------------------------------------------------
# Fleet control plane (PR 19, serving/fleet_control.py). The controller
# is fleet-scoped — one per ReplicaSet — so its families are process-
# global like the PR-16/17 plane families above. Per-request SLO/tenant
# admission families live on the gateway's per-instance registry and are
# manifested in INSTANCE_FAMILIES below.
# ---------------------------------------------------------------------------

#: Replica lifecycle census, labeled ``state="serving"|"draining"|
#: "retired"``. Refreshed by ReplicaSet on every state transition; a
#: nonzero ``draining`` means an elastic retire is mid-drain (the router
#: skips that replica for new work while its in-flight requests finish).
FLEET_REPLICAS = REGISTRY.gauge(
    "gateway_fleet_replicas",
    "Batcher replicas per lifecycle state",
)
#: Elastic lifecycle transitions, labeled ``action="spawn"|"drain"|
#: "retire"``. A retire is always preceded by a drain (router stops new
#: work, in-flight finishes, chains demote to the shared HostPageStore)
#: so ``retire`` without a matching ``drain`` indicates a bug.
FLEET_SCALE = REGISTRY.counter(
    "gateway_fleet_scale_total",
    "Elastic replica lifecycle transitions by action",
)
#: Router load-steering weight per replica, labeled ``replica=``. The
#: fleet controller multiplies each replica's modeled queue cost by this
#: weight inside PrefixRouter's least-cost comparisons, so weight > 1
#: repels new work and weight < 1 attracts it. 1.0 = neutral (the
#: static PR-14 behavior).
ROUTER_WEIGHT = REGISTRY.gauge(
    "gateway_router_weight",
    "PrefixRouter load-steering weight per replica",
)
#: Fleet-controller decisions that CHANGED a setpoint, labeled
#: ``decision="router_weights"|"group_cap"|"restore_cap"|"spawn"|
#: "retire"``. Mirrors the PR-15 autotune convention: gauges refresh
#: every tick, this counter moves only on change, and each change also
#: lands a ``fleet`` flight-recorder event for replay.
FLEET_DECISIONS = REGISTRY.counter(
    "gateway_fleet_decisions_total",
    "Fleet-controller setpoint changes by decision",
)


# ---------------------------------------------------------------------------
# Canonical manifest of families created on PER-INSTANCE registries
# (gateway/admission accept an isolated MetricsRegistry for test
# isolation, so their families cannot be module-level objects here).
# scripts/check_metrics.py treats these names as declared; add a row
# here AND to the README observability table when instrumenting a new
# one.
# ---------------------------------------------------------------------------

INSTANCE_FAMILIES: dict[str, str] = {
    "gateway_requests_total": "counter",
    "gateway_request_seconds": "histogram",
    "gateway_tokens_per_second": "histogram",
    "gateway_queue_depth": "gauge",
    "gateway_inflight": "gauge",
    "gateway_admitted_total": "counter",
    "gateway_shed_total": "counter",
    "gateway_deadline_expired_total": "counter",
    "gateway_completed_total": "counter",
    "gateway_queue_wait_seconds": "histogram",
    "gateway_queue_cost_bytes": "gauge",
    "gateway_slo_miss_total": "counter",
    "gateway_slo_shed_total": "counter",
    "gateway_slo_headroom_seconds": "histogram",
    "gateway_tenant_cost_bytes": "counter",
    "gateway_tenant_shed_total": "counter",
    # PR 20 fleet observability: per-hop request attribution sourced
    # from the joined trace spans (labeled ``hop="front_route"|
    # "admission_wait"|"prefill"|"handoff"|"wire_transfer"|"decode"``),
    # and the admission controller's decayed per-``class`` SLO miss
    # fraction the FleetController reads through burn_rates().
    "gateway_hop_seconds": "histogram",
    "gateway_slo_burn_rate": "gauge",
}


# Mirror tracing-layer drops into the registry (lockstep: the hook runs
# at the drop site, inside the tracing module's accounting).
from llm_consensus_tpu.utils import tracing as _tracing  # noqa: E402

_tracing.set_drop_hook(lambda kind, n: TRACE_DROPPED.labels(kind=kind).inc(n))
