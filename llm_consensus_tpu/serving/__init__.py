"""Serving: request scheduler batching concurrent callers onto the device.

The reference serves exactly one question at a time from its REPL
(``src/main.rs:428-471``) and fans out each panel step as independent
HTTP futures. Here concurrent producers (REPL sessions, eval harness,
panel fan-outs) enqueue requests; two granularities are offered:

- :class:`BatchScheduler` — request-level batching (a batch runs to
  completion); simplest, best for uniform fan-outs.
- :class:`ContinuousBatcher` — token-level continuous batching over a
  paged KV cache; requests join and leave the running decode batch at
  step granularity (the throughput-serving mode).
- :class:`ReplicaSet` — N continuous batchers behind one prefix-
  affinity router with a fleet-shared host page store (PR 14): the
  scale-out layer (``serve --replicas K``).
- :class:`ModelSet` — N independent ENGINES (distinct models, configs,
  meshes) behind one gateway (PR 18), with cross-model speculative
  decoding through a vocab-alignment remap (``serve --models ...``).
"""

from llm_consensus_tpu.serving.continuous import (
    ContinuousBackend,
    ContinuousBatcher,
    ContinuousConfig,
    ServeResult,
)
from llm_consensus_tpu.serving.fleet import (
    FleetBackend,
    FleetConfig,
    PrefixRouter,
    ReplicaSet,
)
from llm_consensus_tpu.serving.modelset import (
    ModelSet,
    ModelSetBackend,
    ModelSpec,
)
from llm_consensus_tpu.serving.offload import HostPageStore
from llm_consensus_tpu.serving.vocab_align import VocabMap, align_vocabs
from llm_consensus_tpu.serving.scheduler import (
    BatchScheduler,
    SchedulerConfig,
    ServingBackend,
)

__all__ = [
    "BatchScheduler",
    "ContinuousBackend",
    "ContinuousBatcher",
    "ContinuousConfig",
    "FleetBackend",
    "FleetConfig",
    "HostPageStore",
    "ModelSet",
    "ModelSetBackend",
    "ModelSpec",
    "PrefixRouter",
    "ReplicaSet",
    "SchedulerConfig",
    "ServeResult",
    "ServingBackend",
    "VocabMap",
    "align_vocabs",
]
