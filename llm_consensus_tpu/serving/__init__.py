"""Serving: request scheduler batching concurrent callers onto the device.

The reference serves exactly one question at a time from its REPL
(``src/main.rs:428-471``) and fans out each panel step as independent
HTTP futures. Here concurrent producers (REPL sessions, eval harness,
panel fan-outs) enqueue requests; a scheduler thread drains the queue
into shape-bucketed batches and runs ONE device program per batch —
device-batching replaces request concurrency (SURVEY.md §7).
"""

from llm_consensus_tpu.serving.scheduler import (
    BatchScheduler,
    SchedulerConfig,
    ServingBackend,
)

__all__ = ["BatchScheduler", "SchedulerConfig", "ServingBackend"]
