"""Continuous batching: token-level request interleaving on one chip.

The :class:`llm_consensus_tpu.serving.scheduler.BatchScheduler` batches
whole requests (a batch runs to completion before the next starts); this
module admits and retires requests at *decode-step* granularity, vLLM
style, re-founded on XLA's compile-once constraint:

- One jitted, donated decode-step program over a fixed ``max_slots``-wide
  paged cache (:mod:`llm_consensus_tpu.models.paged_cache`): shapes never
  change, so the hot loop never recompiles. Admission/retirement mutate
  page tables and lengths — data, not shapes.
- **Chunked prefill interleaved with decode** (PR 2): prompts prefill in
  fixed-size chunks scheduled as work units BETWEEN decode steps
  (compile-once per (chunk, prompt-bucket) pair, paged K/V scatter per
  chunk — :func:`llm_consensus_tpu.models.transformer.prefill_chunk_paged`),
  so running slots keep decoding while new prompts fill. A mid-prefill
  sequence's device table row stays NULL (the decode program never sees
  it); the chunk program writes through an explicit host-side table.
  ``prefill_chunk=0`` restores the legacy blocking per-admission dense
  prefill (the parity baseline).
- **Copy-on-write shared prefixes**: admission hashes the prompt's
  page-aligned prefix into a per-shard
  :class:`~llm_consensus_tpu.models.paged_cache.PrefixRegistry`; full
  pages of an already-resident prefix are refcount-mapped into the new
  sequence's table instead of re-prefilled (the consensus panel's N
  personas over one question prefill the common header ONCE), and a
  partially-matching boundary page is copied
  (:func:`~llm_consensus_tpu.models.paged_cache.copy_page`), never
  shared — decode writes land only in private pages. Registration
  happens at admission, gated by per-page readiness flags, so a burst
  of same-prefix requests dedups against the first request's in-flight
  prefill instead of racing it.
- **Host-RAM offload tier** (PR 4, :mod:`llm_consensus_tpu.serving.
  offload`): with ``host_cache_bytes > 0``, prefix-registry eviction
  DEMOTES ready pages to a byte-budgeted host LRU store instead of
  dropping them, and admission falls through registry-miss → host-hit,
  restoring pages via ``device_put`` + install scheduled between
  decode steps exactly like prefill chunks. Restored pages re-register
  under the same per-page readiness gates, so a same-prefix burst
  dedups against an in-flight restore like an in-flight prefill — and
  a restored prefix is byte-identical to a re-prefilled one (tested).
- A host thread drives: admit waiting requests into free slots, run at
  most one restore or prefill chunk, run one decode step for all
  slots, sample,
  retire EOS/length-capped slots, resolve futures. Inactive slots decode
  into the reserved NULL page and their outputs are discarded (the cost
  of a dead slot is one row of an already-batched matmul — negligible
  next to recompilation or bubbles).
- **Pipelined decode dispatch** (PR 6, ``pipeline_depth``, default 2):
  the host loop is a software pipeline, not a dispatch→sync→bookkeep
  lockstep — program *n+1* is enqueued before program *n*'s tokens are
  fetched, fed from *n*'s device-resident token output, so all host
  work (stop scans, retirement, group bookkeeping, chunked-prefill
  admission, host-tier restores) happens while the device is already
  running the next program. Retirement lags by the in-flight depth
  (overshoot tokens are discarded on fetch and pre-budgeted into page
  reservations); restores, CoW boundary copies, and dense prefill
  drain the pipeline first (``gateway_pipeline_flushes_total``).
  Depth 1 is the serialized parity baseline; outputs are
  byte-identical at every depth (tested).

- **Mesh-native hot path** (PR 13): pass ``mesh=`` and the WHOLE stack
  shards — pool pages and slot blocks over ``data`` (one host
  allocator + prefix registry per data shard, so every row's table is
  shard-local), kv heads over ``model``, params via ``shard_params``,
  the draft pool with the target's — and every feature above plus
  fused dispatch, grouped prefix attention, multi-round decode, spec
  decode, and the host tier ENGAGES, serving byte-identical text to
  the single-chip batcher (tests/test_mesh_serving.py parity grid;
  README Serving engage matrix). The Pallas ragged kernel runs under
  shard_map with per-shard page-id rebasing; configs it can't shard
  (``transformer.ragged_mesh_shardable``) take the GSPMD-sharded XLA
  reference instead — the one remaining kernel-level fallback.

Pages for the whole request (prompt + max_new_tokens) are reserved at
admission; requests wait while the pool is exhausted (no mid-flight
growth/preemption in v1 — simpler, and cannot deadlock; prefix-registry
pages held by nobody else are evicted on demand first).

The reference processes requests strictly one-question-at-a-time with
unbounded per-call HTTP concurrency (``src/main.rs:101,156,182``); this
is the TPU-native throughput-serving counterpart.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from llm_consensus_tpu.backends import base as _backend_base
from llm_consensus_tpu.engine.engine import _next_bucket
from llm_consensus_tpu.engine.sampler import (
    SamplerConfig,
    sample_token_per_request,
    stop_scan_hit,
)
from llm_consensus_tpu.engine.tokenizer import ByteTokenizer, Tokenizer
from llm_consensus_tpu.utils.stops import (
    VisibleIdFilter,
    derived_stop_screen,
    earliest_stop_cut,
    stop_tail_window,
)
from llm_consensus_tpu.models.cache import KVCache
from llm_consensus_tpu.models.configs import ModelConfig
from llm_consensus_tpu.models.paged_cache import (
    NULL_PAGE,
    GroupTracker,
    PagedKVCache,
    PagePool,
    PrefixRegistry,
    assign_pages,
    copy_page,
    install_page,
    install_pages,
    install_seq,
    release_seq,
    write_prefill_kv,
)
from llm_consensus_tpu.engine.accept import verify_tokens
from llm_consensus_tpu.serving import flight as _flight
from llm_consensus_tpu.serving.offload import HostPageStore
from llm_consensus_tpu.models.transformer import (
    decode_step_paged,
    fused_step_paged,
    kv_plane_token_bytes,
    model_param_bytes,
    prefill,
    prefill_chunk_paged,
    program_hbm_cost,
    unembed_one,
    verify_step_paged,
)
from llm_consensus_tpu.models.transformer import (
    ragged_mesh_shardable as _ragged_mesh_shardable,
)
from llm_consensus_tpu.server.metrics import (
    PREFILL_STALL_SECONDS as _M_PREFILL_STALL,
)
from llm_consensus_tpu.server.metrics import (
    PREFIX_HITS as _M_PREFIX_HITS,
)
from llm_consensus_tpu.server.metrics import (
    PREFIX_LOOKUPS as _M_PREFIX_LOOKUPS,
)
from llm_consensus_tpu.server.metrics import (
    PREFIX_PAGES_COPIED as _M_PREFIX_COPIED,
)
from llm_consensus_tpu.server.metrics import (
    PREFIX_PAGES_SHARED as _M_PREFIX_SHARED,
)
from llm_consensus_tpu.server.metrics import (
    DECODE_GROUP_SIZE as _M_GROUP_SIZE,
)
from llm_consensus_tpu.server.metrics import (
    SHARED_KV_BYTES_SAVED as _M_KV_SAVED,
)
from llm_consensus_tpu.server.metrics import (
    KV_OFFLOAD_DEMOTED as _M_OFF_DEMOTED,
)
from llm_consensus_tpu.server.metrics import (
    KV_OFFLOAD_DROPPED as _M_OFF_DROPPED,
)
from llm_consensus_tpu.server.metrics import (
    KV_OFFLOAD_RESTORED as _M_OFF_RESTORED,
)
from llm_consensus_tpu.server.metrics import (
    KV_HOST_TIER_BYTES as _M_OFF_HOST_BYTES,
)
from llm_consensus_tpu.server.metrics import (
    KV_RESTORE_SECONDS as _M_RESTORE_SECONDS,
)
from llm_consensus_tpu.server.metrics import (
    DECODE_STEP_SECONDS as _M_STEP_SECONDS,
)
from llm_consensus_tpu.server.metrics import (
    SCHED_OVERHEAD_SECONDS as _M_SCHED_OVERHEAD,
)
from llm_consensus_tpu.server.metrics import (
    PIPELINE_FLUSHES as _M_PIPELINE_FLUSHES,
)
from llm_consensus_tpu.server.metrics import (
    DISPATCH_INFLIGHT as _M_DISPATCH_INFLIGHT,
)
from llm_consensus_tpu.server.metrics import (
    DEVICE_PROGRAMS as _M_DEVICE_PROGRAMS,
)
from llm_consensus_tpu.server.metrics import (
    RAGGED_ROWS as _M_RAGGED_ROWS,
)
from llm_consensus_tpu.server.metrics import (
    DECODE_ROUNDS_PER_PROGRAM as _M_DECODE_ROUNDS,
)
from llm_consensus_tpu.server.metrics import (
    DEVICE_ROUNDS as _M_DEVICE_ROUNDS,
)
from llm_consensus_tpu.server.metrics import (
    SPEC_DRAFT_TOKENS as _M_SPEC_DRAFTED,
)
from llm_consensus_tpu.server.metrics import (
    SPEC_ACCEPTED_TOKENS as _M_SPEC_ACCEPTED,
)
from llm_consensus_tpu.server.metrics import (
    SPEC_ACCEPTANCE as _M_SPEC_ACCEPTANCE,
)
from llm_consensus_tpu.server.metrics import (
    SPEC_VERIFIED_TOKENS as _M_SPEC_VERIFIED,
)
from llm_consensus_tpu.server.metrics import (
    SPEC_XMODEL_ACCEPTED_TOKENS as _M_SPEC_XMODEL,
)
from llm_consensus_tpu.server.metrics import (
    SERVING_ACTIVE as _M_ACTIVE,
)
from llm_consensus_tpu.server.metrics import (
    SERVING_COMPLETED as _M_COMPLETED,
)
from llm_consensus_tpu.server.metrics import (
    SERVING_OCCUPANCY as _M_OCCUPANCY,
)
from llm_consensus_tpu.server.metrics import (
    SERVING_STEPS as _M_STEPS,
)
from llm_consensus_tpu.server.metrics import (
    SERVING_SUBMITTED as _M_SUBMITTED,
)
from llm_consensus_tpu.server.metrics import (
    SERVING_TOKENS as _M_TOKENS,
)
from llm_consensus_tpu.server.metrics import (
    SERVING_WAITING as _M_WAITING,
)
from llm_consensus_tpu.server.metrics import (
    TBT_SECONDS as _M_TBT,
)
from llm_consensus_tpu.server.metrics import (
    PROGRAM_MBU as _M_PROGRAM_MBU,
)
from llm_consensus_tpu.server.metrics import (
    MESH_SHARDS as _M_MESH_SHARDS,
)
from llm_consensus_tpu.server.metrics import (
    KV_PREFETCH as _M_PREFETCH,
)
from llm_consensus_tpu.utils import tracing as _tracing

log = logging.getLogger(__name__)

# Process-wide request-id stream: ids key the (process-global)
# RequestLog, so two batchers in one process must not collide.
_RID = itertools.count(1)

# Width of the per-row device stop screen (PR 12): a request's derived
# candidate-id set rides the multi-round program as one -1-padded
# [max_slots, _SCREEN_W] data row. STATIC — widening it per request
# would make screen size a compiled shape. Requests whose screen
# doesn't fit bound the window to 1 round instead (derived_stop_screen
# returns None past the cap).
_SCREEN_W = 8

# Bound on the per-batcher derived-screen memo (stop tuples are
# client-supplied; see _screen_cache).
_SCREEN_CACHE_MAX = 512

# Cap on prefix_probe's host-tier extension walk (PR 14): each probed
# page hashes a fresh chain-prefix tuple (O(chain) per lookup — the
# store key is the full flat chain), so an unbounded walk is quadratic
# in prompt length on the per-request routing hot path. Host tokens
# only break ties between replicas' registry matches, and the signal
# saturates after a few pages; past the cap the router still routes
# correctly, it just stops counting deeper host residency.
_PROBE_HOST_PAGES = 8


def _weights_fingerprint(params) -> tuple:
    """A cheap, deterministic identity for a parameter tree: leaf
    count plus a hash over the first 4 elements of EVERY leaf (one
    concatenated device fetch at construction — a single leaf would
    not do: norm scales initialize to ones and embeddings can tie
    across checkpoints, so the sample must span the tree). Two
    batchers loaded from the same checkpoint (or sharing one tree,
    shard_params included — resharding moves bytes, not values)
    fingerprint equal; different weights differ with overwhelming
    probability. The host-tier store scope includes this (PR 14): a
    KV page's bytes are a function of the weights that wrote it, so
    replicas serving different checkpoints of one config must never
    cross-restore through a shared store."""
    import hashlib

    import jax.numpy as _jnp

    leaves = jax.tree_util.tree_leaves(params)
    sample = np.asarray(
        _jnp.concatenate(
            [
                _jnp.ravel(leaf)[:4].astype(_jnp.float32)
                for leaf in leaves
            ]
        )
    ).tobytes()
    return (len(leaves), hashlib.sha1(sample).hexdigest())


@dataclass
class ContinuousConfig:
    max_slots: int = 8
    page_size: int = 64
    n_pages: int = 512  # pool size (excl. semantics: page 0 is reserved)
    pages_per_seq: int = 32  # table width = max seq len / page_size
    max_new_tokens: int = 256
    seq_buckets: tuple[int, ...] = (64, 128, 256, 512)
    sampler: SamplerConfig | None = None
    poll_interval_s: float = 0.001
    # Over-long prompts: left-truncate to the largest bucket (keeping the
    # question tail) with a warning, or reject when False.
    truncate_prompts: bool = True
    # Decode steps per device program (one host dispatch+fetch per
    # chunk). The host-driven loop pays a host<->device round trip per
    # sync — on a remote/tunneled chip that RTT dominates the ~ms decode
    # step itself (round 5 measured ~113 ms/step at chunk 1 on the
    # tunnel, i.e. >97% RTT; `bench.py --serve-chunk 16` opts in).
    # Retirement/admission happen at chunk boundaries, so a finished
    # row overshoots up to chunk-1 tokens (discarded on host; page
    # reservations carry the slack — raising this on a config whose
    # pages_per_seq was sized exactly may need one more page per
    # sequence) and a waiting request can be admitted up to chunk-1
    # steps late. Pure throughput/latency knob: outputs are
    # chunk-size-invariant (per-token PRNG streams are (seed, index) —
    # tested). Default 1 = per-token retirement/admission, the right
    # latency behavior on a locally-attached chip.
    steps_per_sync: int = 1
    # Prefill-chunk width (tokens). > 0: prompts prefill in chunks of
    # min(prefill_chunk, prompt's seq bucket) scheduled BETWEEN decode
    # steps — decode stalls per admission are bounded by one chunk's
    # compute instead of the whole prompt. 0: legacy blocking dense
    # prefill at admission (parity baseline; disables prefix sharing).
    prefill_chunk: int = 64
    # Map page-aligned shared prompt prefixes out of the PrefixRegistry
    # instead of re-prefilling them. Requires prefill_chunk > 0 (the
    # chunk program is what can START a prefill mid-prompt).
    share_prefix: bool = True
    # Group-aware decode attention (PR 3): sequences whose tables share
    # a prefix page run read it ONCE per step through the grouped
    # Pallas kernel instead of once per member. Engages only when
    # share_prefix is on, the model runs the Pallas paged kernel
    # (cfg.use_pallas, single device, no sliding window), and a >= 2
    # member group exists this step — otherwise the plain row kernel
    # runs, outputs identical. Off = always the plain kernel (the
    # bench's A/B baseline).
    prefix_attention: bool = True
    # Host-RAM offload tier under the prefix registry (PR 4): byte
    # budget for demoted KV pages. > 0: registry eviction DEMOTES
    # ready prefix pages to host buffers instead of dropping them, and
    # admission falls through registry-miss -> host-hit, restoring
    # pages via device_put interleaved with decode steps. 0 (default):
    # eviction destroys, exactly the PR 2/3 behavior. Requires
    # share_prefix + prefill_chunk > 0 (the restore path re-registers
    # pages under the registry's readiness gates).
    host_cache_bytes: int = 0
    # Decode programs in flight at once (PR 6): the host loop enqueues
    # program n+1 BEFORE fetching program n's tokens, feeding the next
    # dispatch from the device-resident token output of the previous
    # one (the cache already flows through donate_argnums), so the one
    # true host sync of the loop lands while the next program is
    # already running — stop scans, retirement, group bookkeeping,
    # chunked-prefill admission, and host-tier restores all happen in
    # that overlap window. Purely a host-loop restructuring: it
    # engages on every backend, meshes included. Retirement lags
    # dispatch by the in-flight depth (a finished row keeps decoding
    # through the already-enqueued programs; the extra tokens are
    # discarded on fetch and pre-budgeted into the page reservation —
    # up to pipeline_depth * steps_per_sync - 1 overshoot tokens per
    # sequence). Operations that want a stable cache + settled
    # bookkeeping (host-tier restores, CoW boundary copies, dense
    # prefill) DRAIN the pipeline first, counted in
    # gateway_pipeline_flushes_total. 1 = the serialized
    # dispatch->sync->bookkeep loop (the parity baseline); outputs are
    # byte-identical at every depth (tested).
    pipeline_depth: int = 2
    # Fused scheduler step (PR 8): when a prefill chunk is ready AND
    # rows are decoding, dispatch ONE device program carrying both —
    # the chunk rides the decode dispatch as one more row of the
    # ragged attention kernel, its QKV/MLP matmuls batch with the
    # decode rows', and its host bookkeeping (readiness flips,
    # activation, first-token sampling) moves into the pipeline's
    # fetch path, so chunked prefill stops serializing against decode
    # and stops forcing a per-chunk device sync. Engages with
    # prefill_chunk > 0 on BOTH kernel paths (the non-Pallas side runs
    # the same ragged semantics via the XLA reference) and on every
    # topology — meshes included since PR 13. False = the
    # PR 6/7 behavior: one standalone chunk program between decode
    # steps (the bench's A/B baseline; outputs byte-identical either
    # way). Read per loop iteration — flipping it between bursts needs
    # no new batcher.
    ragged_attention: bool = True
    # Speculative decoding inside the batcher (PR 9): draft tokens
    # proposed per scheduler round. With spec_k > 0 AND a draft model
    # passed to the batcher (``ContinuousBatcher(draft=(cfg, params))``,
    # ``serve --draft-model/--spec-k``), each round dispatches ONE
    # device program that (a) runs spec_k + 1 greedy draft steps on the
    # draft's mirror of the page pool — one shared draft stream per
    # shared-prefix group: a panel mate whose committed text still
    # agrees with its group donor's reuses the donor's committed
    # suffix + fresh drafts instead of drafting itself — (b) verifies
    # all rows' drafts through the target's k+1-token ragged verify
    # rows (shared embed/QKV/WO/MLP GEMMs over the widened token axis,
    # speculative K/V scattered into the pool), and (c) applies the
    # leviathan accept/rollback rule ON DEVICE, emitting the accepted
    # prefix + correction/bonus token per row. Rollback is pure count
    # bookkeeping — ``length`` rewinds; rejected K/V sits past every
    # later read in private pages and is overwritten, exactly like
    # mid-chunk retirement overshoot. Greedy output is byte-identical
    # to spec-off for ANY draft; sampled rows use the exact one-hot
    # residual correction (engine/accept.py). spec_k feeds the
    # page-overshoot budget of every admission, so it must not be
    # flipped live — ``spec_decode`` below is the A/B lever. Engages
    # with steps_per_sync == 1 (the verify round IS the multi-token
    # step), meshes included since PR 13: the draft pool shards with
    # the target's (pages over data, heads over model where they
    # divide) and the draft/verify/accept program runs under GSPMD
    # like the plain step.
    spec_k: int = 0
    # Live on/off lever for speculation, read per loop iteration (the
    # bench flips THIS between bursts on one batcher; a flip drains the
    # dispatch pipeline so plain and spec programs never share a
    # window). No effect without spec_k > 0 + a draft model.
    spec_decode: bool = True
    # Multi-round on-device decode (PR 12, ``serve --decode-rounds``):
    # decode rounds per dispatched device program. R > 1 folds up to R
    # decode rounds into ONE program (lax.scan over the shared decode
    # body) with stop checking, sampling, and per-row emit-count /
    # cache-length bookkeeping fully on device: a row that samples EOS,
    # a screened stop-candidate token, or its max-tokens budget inside
    # the window FREEZES (K/V writes redirected to the NULL page, PRNG
    # folds stop, length stops advancing) while its neighbors keep
    # decoding — the host fetches once per R rounds and retires /
    # regroups from the lagged mirror, exactly the PR-9 spec-verify
    # pattern. Text is byte-identical to R = 1: EOS and max-tokens are
    # exact on device; stop SEQUENCES freeze conservatively via the
    # derived byte screen (utils.stops.derived_stop_screen) and the
    # host's byte-level check at fetch stays authoritative (a false
    # positive resumes next window; a miss is trimmed on fetch) — and
    # a request whose stops admit no bounded screen collapses the
    # window to 1 round while it decodes. Engages with
    # steps_per_sync == 1, meshes included since PR 13 (the legacy
    # multi-step chunk has no masking and stays the tunnel-RTT knob);
    # while speculation is engaged the
    # verify round IS the multi-token step, so spec windows keep one
    # verify round per dispatch and multi-round applies to the plain
    # windows — the two compose by decoupling fetch cadence from the
    # verify round, and flips drain the pipeline like every mode
    # change. Sizes the page-overshoot budget of every admission like
    # spec_k does, so treat live flips as between-bursts events (the
    # bench's A/B lever). 1 (default) = today's one-round dispatch.
    decode_rounds: int = 1
    # Roofline attribution (PR 10): the device's peak HBM bandwidth in
    # GB/s (1e9 bytes/s — e.g. ~819 for a v5e, ~1640 for a v5p core).
    # > 0: every fetched device program sets
    # gateway_program_mbu{kind} = modeled HBM bytes (weights + KV pages
    # actually touched, per models.transformer.program_hbm_cost) /
    # measured wall time / peak — ~1.0 means that program kind is at
    # the weights+KV roofline, and the gap IS the remaining tok/s.
    # 0 (default): no gauge; the modeled-bytes and measured-seconds
    # sums still accumulate per kind in stats() (mbu_* keys) so the
    # ratio can be derived offline against any peak. CPU values are a
    # plumbing smoke only — MBU is meaningful on the chip.
    hbm_gbps: float = 0.0


@dataclass
class ServeResult:
    """What a :meth:`ContinuousBatcher.submit` future resolves to."""

    text: str
    num_tokens: int  # generated tokens incl. EOS
    # Per-request serving timeline (PR 10): the same summary dict the
    # RequestLog retains for /debug/requests — TTFT, inter-token-gap
    # percentiles, spec tokens accepted per round, restored-vs-prefilled
    # header pages. Rides the gateway response as "meta". Excluded from
    # equality: two identical generations NEVER share wall-clock stamps,
    # and result comparison means "same text/tokens" everywhere
    # (parity tests compare whole ServeResults).
    timing: dict | None = field(default=None, compare=False)


@dataclass
class _Request:
    prompt_ids: np.ndarray
    max_new_tokens: int
    temperature: float
    seed: int
    future: Future
    # Per-request sampler settings ride as decode-step DATA (arrays),
    # never as compiled constants — a request with new settings joining
    # the batch must not recompile the hot loop.
    top_k: int = 0
    top_p: float = 1.0
    # Stop sequences (engine contract): text trims at the earliest
    # occurrence; the host loop sees every sampled token, so multi-token
    # stops end decoding immediately (no overshoot to EOS/length).
    stop: tuple[str, ...] = ()
    # Tail-window width for the per-token stop check, precomputed once
    # at submit (stop strings are immutable for the request's life —
    # re-encoding them per sampled token would put tokenizer calls on
    # the thread pacing device steps).
    stop_window: int = 0
    # Device stop screen for multi-round decode (PR 12), derived once
    # at submit (memoized per stop tuple): () = no stops (never screen-
    # freezes), a tuple of <= _SCREEN_W candidate ids, or None = stops
    # with no bounded screen — this row bounds any multi-round window
    # it rides to 1 round (host-checked cadence).
    stop_screen: tuple[int, ...] | None = ()
    # Request-scoped trace captured from the submitter's context: the
    # worker thread attaches prefill-chunk/decode-step/restore spans to
    # it explicitly (contextvars do not cross the thread boundary).
    trace: object | None = None
    # Flight-recorder identity + timeline origin (PR 10): rid keys the
    # RequestLog summary; t_submit (perf_counter) anchors TTFT and the
    # request's Chrome-export track.
    rid: str = ""
    t_submit: float = 0.0


@dataclass
class _Slot:
    request: _Request
    pages: list[int]  # every table page this sequence holds one ref on
    generated: list[int]
    prompt_len: int
    # "prefill" until the last chunk lands (device table row stays NULL
    # and the decode loop ignores the row), then "decode".
    phase: str = "decode"
    # -- chunked-prefill state (phase == "prefill") --------------------
    table: np.ndarray | None = None  # host-side table (device sees NULL)
    next_pos: int = 0  # absolute position of the next chunk's first token
    chunk: int = 0  # this request's chunk width
    padded_ids: np.ndarray | None = None  # prompt ids padded to chunk grid
    s_bucket: int = 0  # prompt's seq bucket (program-family key)
    # Registry nodes whose page CONTENT this sequence reads (shared
    # prefix pages written by another in-flight prefill): chunks wait
    # until every dep is ready.
    deps: list = field(default_factory=list)
    # Nodes THIS sequence registered, with the prompt position whose
    # write completes them: [(node, end_pos)].
    reg_nodes: list = field(default_factory=list)
    # Tokens the TARGET committed through plain decode programs that
    # the draft mirror never saw (spec_decode flipped off mid-decode
    # with a draft configured). The next spec engagement replays them
    # through the draft before dispatching (:meth:`_spec_catch_up`) —
    # without the replay the draft would write this row's next K/V at
    # stale positions and its proposals would silently stop accepting.
    draft_lag: int = 0
    # -- per-request token timeline (PR 10) -----------------------------
    # First-token stamp (perf_counter; TTFT = t_first - t_submit), the
    # previous token-arrival stamp, and the observed inter-token gaps
    # (one per token past the first; tokens landing in the same program
    # fetch record 0 past the first — the bursty arrival a streaming
    # client sees). Retirement folds these into the RequestLog summary.
    t_first: float | None = None
    t_last_tok: float = 0.0
    gaps: list = field(default_factory=list)
    # Speculative per-request tallies: verify rounds this row rode and
    # draft tokens those rounds accepted for it.
    spec_rounds: int = 0
    spec_accepted_toks: int = 0
    # Header provenance: full prefix pages mapped from the registry at
    # admission vs restored from the host tier (each page is page_size
    # prompt tokens this request never re-prefilled).
    pages_shared_n: int = 0
    pages_restored_n: int = 0


@dataclass
class _InflightChunk:
    """A prefill chunk riding an in-flight FUSED program (PR 8).

    The chunk's device work (K/V writes, ragged attention, final-chunk
    first-token logits) is already ordered on the stream; what waits
    for the fetch is the HOST bookkeeping — chunk accounting, the
    final chunk's activation + ``install_seq``. ``slot`` is the
    identity guard, exactly like ``_Inflight.rows``.
    """

    idx: int  # slot index
    slot: _Slot
    done: bool  # this program wrote the chunk covering the prompt end
    logits: object  # device [V] last-real-position logits (done only)
    pos: int  # chunk start position (trace span meta)
    width: int  # chunk width


@dataclass
class _Inflight:
    """One dispatched, not-yet-fetched decode program (PR 6).

    ``rows`` snapshots the (slot index, slot object) pairs that were
    decoding at dispatch time: the fetch credits tokens ONLY to rows
    whose slot object is still in place, so a slot retired — or retired
    and re-admitted to a new request — while this program was in flight
    never receives a stale program's output.
    """

    tokens: object  # device [slots, k] sampled tokens (the fetch target)
    next_input: object  # device [slots] final token (next dispatch's input)
    t0: float  # host dispatch stamp (perf_counter)
    k: int  # decode steps folded into this program
    rows: list  # [(slot_idx, _Slot)] decoding at dispatch
    chunk: _InflightChunk | None = None  # fused prefill chunk (PR 8)
    # -- speculative round (PR 9) --------------------------------------
    # ``tokens`` is then the [slots, spec_k + 1] emit buffer; only
    # ``emit_cnt`` leading tokens per row are real. ``counts_out`` is
    # the device-resident post-round PRNG index row the NEXT spec
    # dispatch consumes (counts become data-dependent under
    # accept/rollback, so the host mirror syncs at fetch, not at
    # dispatch).
    spec: bool = False
    spec_k: int = 0
    emit_cnt: object = None  # device [slots] emitted-token counts
    counts_out: object = None  # device [slots] post-round PRNG counts
    # -- multi-round decode (PR 12) --------------------------------------
    # > 0: this program ran through the multi-round machinery (that
    # many masked decode rounds — possibly 1 when a stop-bound
    # collapsed the window); its per-row yield is data-dependent like
    # a spec round's (``emit_cnt`` leading tokens real, ``counts_out``
    # device-resident, host count/draft-lag mirrors sync at fetch).
    # 0: a legacy program whose host mirrors advanced at dispatch.
    rounds: int = 0
    # Whether this window's length was the rounds controller's CHOICE
    # (PR 15) rather than forced by a near-stop cap or an
    # unscreenable-stop collapse — only chosen windows feed the
    # per-arm measured-rate EWMAs (a forced tail window would
    # attribute its frozen rows' starvation to an arm that never
    # chose it).
    rounds_clean: bool = False
    # -- flight recorder + roofline attribution (PR 10) -----------------
    # The "program" flight event recorded at dispatch: the fetch fills
    # its (t0, dur) window in place once the true device window is
    # known. ``cost`` is the static HBM/FLOPs model for this program
    # (program_hbm_cost output), accumulated per kind at fetch time
    # against the measured duration.
    flight: object = None
    cost: dict | None = None


class ContinuousBatcher:
    """Token-level continuous batching over one model's weights."""

    def __init__(
        self,
        cfg: ModelConfig,
        params: dict,
        tokenizer: Tokenizer | None = None,
        config: ContinuousConfig | None = None,
        mesh=None,
        draft: tuple[ModelConfig, dict] | None = None,
        draft_map=None,
        host_store: HostPageStore | None = None,
        host_store_scope: tuple | None = None,
        controller=None,
    ):
        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer or ByteTokenizer()
        self.config = config or ContinuousConfig()
        c = self.config
        # Roofline-adaptive runtime control (PR 15,
        # serving/control.py): an AdaptiveController closing the PR-10
        # cost model into a feedback loop — effective spec_k per
        # dispatch from measured per-group acceptance, adaptive-R
        # window caps, chunk/depth steering from un-overlapped
        # overhead and modeled MBU, restore pacing for the fleet's
        # preempt hook. None (default) = every knob stays its static
        # config value (the pre-PR-15 behavior, and the bench's
        # fixed-grid baseline). Bound below once the modeled terms
        # exist.
        self.controller = controller
        # Speculative draft model (PR 9): the draft decodes against its
        # OWN pool mirroring the target's page geometry — same page
        # ids, same host-side tables/allocator, so prefix sharing, CoW
        # copies, and host-tier restores cover both pools with one set
        # of bookkeeping. Draft prefill rides every prompt (chunked or
        # dense) whenever the draft exists, so flipping ``spec_decode``
        # mid-serve never leaves a prompt without draft context.
        self._draft_cfg: ModelConfig | None = None
        self._draft_params: dict | None = None
        self.draft_cache = None
        # Cross-model vocab remap (PR 18, serving/vocab_align.py):
        # ``draft_map`` carries the exact-match d2t/t2d tables when the
        # draft speaks a DIFFERENT tokenizer. All carried token state —
        # committed streams, spec_fill, the verify drafts — stays in
        # TARGET vocab; t2d applies only at the draft model's input
        # boundary (its decode scan and prefill mirrors), d2t only at
        # its argmax output. An identity map (or None with equal
        # vocabs) keeps the PR-9 single-tokenizer fast path: no gather
        # in any trace.
        self._vocab_map = draft_map
        self._t2d = None
        self._d2t = None
        if draft is not None:
            dcfg, dparams = draft
            if c.spec_k <= 0:
                raise ValueError(
                    "a draft model needs spec_k > 0 (spec_k sizes the "
                    "page-overshoot budget and the verify program)"
                )
            if draft_map is not None and not draft_map.identity:
                if len(draft_map.d2t) != dcfg.vocab_size or len(
                    draft_map.t2d
                ) != cfg.vocab_size:
                    raise ValueError(
                        f"draft_map shape mismatch: d2t[{len(draft_map.d2t)}]"
                        f" vs draft vocab {dcfg.vocab_size}, t2d"
                        f"[{len(draft_map.t2d)}] vs target vocab "
                        f"{cfg.vocab_size}"
                    )
                # Tiny int32 tables captured as jit constants — one
                # device copy, every spec/prefill trace closes over it.
                self._t2d = jnp.asarray(draft_map.t2d, jnp.int32)
                self._d2t = jnp.asarray(draft_map.d2t, jnp.int32)
            elif dcfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {dcfg.vocab_size} != target vocab "
                    f"{cfg.vocab_size} — cross-model speculation needs a "
                    "vocab alignment map (serving.vocab_align."
                    "align_vocabs) or one shared tokenizer"
                )
            if c.steps_per_sync > 1:
                # Not an error: spec_decode is a live lever and the
                # draft pool/prefills are still maintained — but a
                # config that can never verify pays the full draft
                # cost (HBM planes + one mirror program per chunk)
                # for zero speedup, silently. This is the ONE
                # remaining no-engage condition: since PR 13 the
                # draft pool shards with the target's and speculation
                # engages on meshes too.
                log.warning(
                    "speculative decoding engages only with "
                    "steps_per_sync == 1 (got %d): the draft will "
                    "prefill but no verify round will ever "
                    "dispatch",
                    c.steps_per_sync,
                )
            self._draft_cfg = dcfg
            self._draft_params = dparams
        # ``mesh``: run the serving hot loop sharded — slots (the decode
        # batch axis) and the page pool's page axis over ``data``, kv
        # heads over ``model``, params via ``shard_params`` (tp over
        # ``model``, replicated over ``data``). Slot->page affinity
        # below keeps each slot's pages on its own data shard so page
        # reads/writes stay shard-local on real hardware.
        self.mesh = mesh
        self._dp = 1
        self._mp = 1
        self._row_sharding = None
        if mesh is not None:
            from llm_consensus_tpu.parallel.partitioning import shard_params

            dp = int(mesh.shape.get("data", 1))
            if c.max_slots % dp or c.n_pages % dp:
                raise ValueError(
                    f"max_slots ({c.max_slots}) and n_pages ({c.n_pages}) "
                    f"must be multiples of the mesh data axis ({dp})"
                )
            self._dp = dp
            self._mp = int(mesh.shape.get("model", 1))
            self.params = shard_params(self.params, mesh)
            if self._draft_params is not None:
                # The draft shards exactly like the target (PR 13): tp
                # over ``model``, replicated over ``data`` — the spec
                # program's draft scan and verify rows run on the same
                # mesh as the plain decode step.
                self._draft_params = shard_params(self._draft_params, mesh)
            self._row_sharding = self._named(("data",))
            if cfg.use_pallas and not _ragged_mesh_shardable(
                cfg, mesh, c.max_slots, c.n_pages
            ):
                # Every serving feature still ENGAGES — this is purely
                # the kernel-vs-reference choice inside the one
                # attention seam (models.transformer._attn_paged).
                log.warning(
                    "Pallas ragged kernel cannot shard over this mesh "
                    "(n_kv_heads=%d %% model=%d, or slots/pages %% "
                    "data=%d, indivisible): paged attention runs the "
                    "XLA reference under GSPMD instead — outputs "
                    "identical, kernel bandwidth shaping lost",
                    cfg.n_kv_heads,
                    self._mp,
                    self._dp,
                )
        _M_MESH_SHARDS.labels(axis="data").set(self._dp)
        _M_MESH_SHARDS.labels(axis="model").set(self._mp)
        if c.decode_rounds > 1 and c.steps_per_sync > 1:
            # Not an error (the batcher serves correctly either way),
            # but the config still pays decode_rounds into every
            # admission's page-overshoot budget (_round_tokens reads
            # the CONFIG so live flips stay budgeted) while _rounds
            # never engages — capacity spent for zero benefit needs a
            # signal, exactly like the spec warning above. (Since
            # PR 13 meshes engage multi-round decode like single
            # chips; steps_per_sync > 1 is the one remaining
            # no-engage condition.)
            log.warning(
                "decode_rounds=%d never engages with steps_per_sync=%d"
                ": no multi-round program will dispatch, but the "
                "page-overshoot budget still reserves for R rounds",
                c.decode_rounds,
                c.steps_per_sync,
            )
        self.cache = PagedKVCache.create(
            cfg, c.n_pages, c.page_size, c.max_slots, c.pages_per_seq
        )
        if mesh is not None:
            self.cache = jax.device_put(
                self.cache, self._pool_sharding_for(cfg)
            )
        if self._draft_cfg is not None:
            # The draft pool: same n_pages/page_size/table geometry as
            # the target's, its own [L_d, n, page, Hkv_d, D_d] planes.
            # page_table/length are maintained in LOCKSTEP with the
            # target cache at every install/release/assign site, so one
            # host allocator serves both pools. On a mesh it takes the
            # same placement as the target's (pages over ``data``,
            # heads over ``model`` where they divide).
            self.draft_cache = PagedKVCache.create(
                self._draft_cfg,
                c.n_pages,
                c.page_size,
                c.max_slots,
                c.pages_per_seq,
            )
            if mesh is not None:
                self.draft_cache = jax.device_put(
                    self.draft_cache,
                    self._pool_sharding_for(self._draft_cfg),
                )
        # Host-side refcounted page allocator; page 0 is the NULL page.
        # On a mesh, one pool (and one prefix registry) per data shard:
        # slot s (slots shard in contiguous blocks) draws only from its
        # own shard's page range, so a sequence's table always points at
        # shard-local pages — and prefix sharing only ever maps pages
        # within one shard.
        pages_per_shard = c.n_pages // self._dp
        self._shard_of_slot = [
            s * self._dp // c.max_slots for s in range(c.max_slots)
        ]
        self._pools = [
            PagePool(
                p
                for p in range(j * pages_per_shard, (j + 1) * pages_per_shard)
                if p != NULL_PAGE
            )
            for j in range(self._dp)
        ]
        self._registries = [
            PrefixRegistry(pool, c.page_size) for pool in self._pools
        ]
        # Host-RAM offload tier (PR 4; mesh-native since PR 13).
        # Engages only on the chunked shared-prefix path (restores
        # re-register under the registry's readiness gates). On a mesh
        # the demote ``device_get`` assembles the page's sharded plane
        # slices into one host buffer and the restore ``install_page``
        # scatters it back through the pool's NamedSharding — the
        # round trip is bit-identical either way (tested); per-shard
        # streaming of the slices is a chip-transport optimization the
        # correctness contract doesn't depend on.
        self._offload: HostPageStore | None = None
        # Store-key scope (PR 14): with a FLEET-SHARED store, every key
        # must carry the identity of the function that wrote the page —
        # config dims, page size, pool dtype, the weights fingerprint,
        # and the draft's equivalents (draft planes travel in the same
        # entries) — so heterogeneous replicas can never cross-restore.
        # A private (per-batcher) store pays the same prefix for free.
        self._store_scope: tuple = ()
        # Chain-scope doc for /debug/chains (PR 18): which model's
        # weights wrote the chains this batcher counts. Lazy — the
        # weights fingerprint walks every param leaf, a cost the first
        # debug probe pays once, not construction.
        self._probe_scope: dict | None = None
        if (
            c.host_cache_bytes > 0
            and c.share_prefix
            and c.prefill_chunk > 0
        ):
            self._offload = (
                host_store
                if host_store is not None
                else HostPageStore(c.host_cache_bytes)
            )
            if host_store_scope is not None:
                # A sibling replica already computed the scope over the
                # SAME cfg/params/store (ReplicaSet passes replica 0's
                # down) — the weights fingerprint walks every param
                # leaf, and K identical walks at fleet construction
                # would be pure redundant startup latency.
                self._store_scope = host_store_scope
            elif host_store is None:
                # PRIVATE store: nobody else can ever write or read
                # it, so keys only need internal consistency — the
                # empty scope keeps the pre-fleet behavior without
                # paying the per-leaf fingerprint walk at every
                # single-batcher `serve --host-cache-mb` start.
                self._store_scope = ()
            else:
                scope = (
                    cfg.name,
                    cfg.n_layers,
                    cfg.n_kv_heads,
                    cfg.head_dim,
                    c.page_size,
                    str(self.cache.k.dtype),
                    _weights_fingerprint(self.params),
                )
                if self._draft_cfg is not None:
                    scope += (
                        self._draft_cfg.name,
                        self._draft_cfg.n_layers,
                        self._draft_cfg.n_kv_heads,
                        self._draft_cfg.head_dim,
                        _weights_fingerprint(self._draft_params),
                    )
                    if self._vocab_map is not None:
                        # The draft planes a restore installs were
                        # written through THIS remap; a different map
                        # means different draft inputs for the same
                        # target chain.
                        scope += self._vocab_map.scope_key()
                self._store_scope = scope
            for reg in self._registries:
                reg.on_evict = self._demote_nodes
        elif host_store is not None:
            raise ValueError(
                "a shared host_store needs the offload tier engaged: "
                "host_cache_bytes > 0, share_prefix, prefill_chunk > 0"
            )
        # Fleet hooks (PR 14): router-requested preemption (demote
        # reclaimable registry chains to the host tier NOW, freeing
        # pool pages for the overload storm instead of shedding 429s)
        # and chain exports (spill a resident chain's ready pages to
        # the shared store WITHOUT evicting, so another replica can
        # restore it — the rebalance transport). Both are REQUESTS
        # enqueued from router/gateway threads and executed by the
        # worker loop: the demote path's device_get must never race
        # the worker's dispatch-time buffer donation.
        self._preempt_req = 0
        self._preempted_pages = 0
        # Fleet-steered group-formation cap (PR 19): the fleet
        # controller resizes GroupTracker.max_groups from fleet-level
        # sharing pressure. The tracker is worker-owned state, so the
        # resize is an enqueued REQUEST applied at the top of the
        # worker loop, exactly like preempts. None = no change pending.
        self._group_cap_req: int | None = None
        # Export queue entries are mutable [ids, done, stream_until,
        # spilled_pages]: a STREAMED export (PR 17) re-arms itself
        # after each spill until the chain's usable pages are all out
        # or the deadline passes, so transport overlaps the prefill
        # still computing the later pages.
        self._exports: deque = deque()
        self._exported_pages = 0
        # Route-driven restore prefetch (PR 17): a bounded host-side
        # cache of chain pages pulled from the (remote) store AHEAD of
        # admission, filled by a side thread so the store round trip
        # never rides the worker loop or the admission lock. Admission
        # consumes it in front of the store probe, shrinking a restore
        # flush to a local install. Lock order: self._lock before
        # _prefetch_lock, everywhere.
        self._prefetched: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._prefetch_lock = threading.Lock()
        self._prefetch_q: deque = deque()
        self._prefetch_have = threading.Event()
        self._prefetch_thread: threading.Thread | None = None
        # Entries, not bytes: a chain is at most pages_per_seq pages,
        # so this holds a few routed-but-not-yet-admitted chains.
        self._prefetch_cap = max(16, 4 * c.pages_per_seq)
        self._prefetch_fetched = 0
        self._prefetch_hits = 0
        self._prefetch_expired = 0
        # Pending page restores: (registry node, host planes). Filled at
        # admission, drained one page per loop iteration between decode
        # steps (the same bounded-stall discipline as prefill chunks);
        # the node's readiness gate holds dependent prefills until the
        # install lands.
        self._restores: deque = deque()
        self._offload_restored = 0
        # Group-aware decode attention: derive per-step groups from
        # shared prefix page runs. The ragged kernel handles groups,
        # sliding windows, and mixed rows in one program, and since
        # PR 13 meshes too (shard_map with groups riding their
        # members' data shard), so the only remaining engage
        # conditions are use_pallas plus the feature knobs — the PR 3
        # sliding-window fallback and the mesh fallback are both gone
        # (README Serving engage matrix). Grouping is per data shard
        # by construction: pages share only within one shard's
        # registry, so a group's members always land on one shard. On
        # a mesh the KERNEL must actually be shardable: the XLA
        # reference fallback ignores groups, so building them would
        # only accrue shared-KV "savings" that never happen (and pay
        # the per-iteration tracker work) — telemetry must not claim
        # reads the program still performs.
        self._group_decode = (
            c.prefix_attention
            and c.share_prefix
            and c.prefill_chunk > 0
            and cfg.use_pallas
            and (
                mesh is None
                or _ragged_mesh_shardable(
                    cfg, mesh, c.max_slots, c.n_pages
                )
            )
        )
        self._groups = GroupTracker(c.max_slots, c.page_size)
        # KV bytes one token costs per read across all layers (k + v,
        # pool dtype) — the unit of gateway_shared_kv_bytes_saved_total
        # AND the cost model's KV term (one formula, transformer.py).
        self._kv_token_bytes = kv_plane_token_bytes(cfg, self.cache.k.dtype)
        self._kv_bytes_saved = 0
        # Roofline attribution (PR 10): the static per-program cost
        # model's weight term is the parameter tree as it actually sits
        # in HBM (post-shard on a mesh — leaf sizes are global either
        # way), measured once; per-kind accumulators mirror the
        # gateway_program_mbu gauge into stats().
        self._weight_bytes, self._weight_params = model_param_bytes(
            self.params
        )
        self._draft_weight_bytes = self._draft_weight_params = 0
        self._draft_kv_token_bytes = 0
        if self._draft_cfg is not None:
            self._draft_weight_bytes, self._draft_weight_params = (
                model_param_bytes(self._draft_params)
            )
            self._draft_kv_token_bytes = kv_plane_token_bytes(
                self._draft_cfg, self.draft_cache.k.dtype
            )
        if self.controller is not None:
            # Static modeled terms the controller's roofline clauses
            # read: the weight tree as it sits in HBM, the KV
            # byte-per-token unit (cost-dict KV splits), the
            # configured peak, and the host tier's budget (restore-
            # pacing debt cap).
            self.controller.bind(
                hbm_gbps=c.hbm_gbps,
                weight_bytes=self._weight_bytes,
                kv_token_bytes=self._kv_token_bytes,
                host_budget_bytes=(
                    c.host_cache_bytes if self._offload is not None else 0
                ),
            )
        self._mbu = {
            kind: {
                "hbm_bytes": 0,
                "flops": 0,
                "kv_read_tokens": 0,
                "kv_write_tokens": 0,
                "seconds": 0.0,
                "programs": 0,
            }
            for kind in ("fused", "decode", "prefill", "spec")
        }
        # Per-request token timeline (PR 10): stats() mirrors of the
        # gateway_ttft-equivalent (submit -> first token, batcher side)
        # and gateway_tbt_seconds observations — one site, two surfaces.
        self._ttft_sum = 0.0
        self._ttft_count = 0
        self._tbt_sum = 0.0
        self._tbt_count = 0
        # Flight-recorder change detectors: the last spec engage state
        # (flip events record transitions, not steady state) and each
        # row's last draft-stream donor (stream events record donor
        # changes/divergences, not every round's plan).
        self._spec_flip_prev: bool | None = None
        self._stream_src_prev: dict[int, int] = {}
        self._slots: list[_Slot | None] = [None] * c.max_slots
        self._waiting: deque[_Request] = deque()
        self._last_tokens = np.zeros((c.max_slots,), np.int32)
        # Pipelined decode dispatch (PR 6): programs dispatched but not
        # yet fetched (oldest first; bounded by pipeline_depth), and the
        # rows whose next input token must come from the HOST mirror
        # instead of the previous program's device output (rows
        # (re)activated since the last dispatch — their first token was
        # sampled from prefill logits, not decoded in flight).
        self._inflight: deque[_Inflight] = deque()
        self._tok_dirty = np.zeros((c.max_slots,), bool)
        self._pipeline_flushes = 0
        # Fused scheduler step (PR 8): device programs by kind plus the
        # ragged-row occupancy — the same observations behind
        # gateway_device_programs_total / gateway_ragged_rows_per_program
        # — and the count of loop iterations that ran any program (the
        # denominator of "device programs per scheduler iteration").
        self._programs = {
            "fused": 0, "decode": 0, "prefill": 0, "spec": 0, "draft": 0,
        }
        self._ragged_rows_sum = 0
        self._ragged_rows_count = 0
        self._work_iterations = 0
        # Multi-round decode (PR 12): total decode rounds dispatched
        # and the per-program round-count observations — the same
        # numbers behind gateway_device_rounds_total /
        # gateway_decode_rounds_per_program (lockstep tested).
        self._device_rounds = 0
        self._decode_rounds_sum = 0
        self._decode_rounds_count = 0
        # perf_counter stamp of the previous fetch's completion: deeper
        # than depth 1 a program starts on device when its predecessor
        # finishes, not at its own dispatch — the step histogram uses
        # max(dispatch, previous fetch) as the start approximation.
        self._last_fetch_end: float | None = None
        # CoW boundary copy staged by _admit_chunked under the lock,
        # dispatched by _admit's post-lock epilogue (the copy wants a
        # pipeline flush first, and the flush's fetch bookkeeping takes
        # the same lock).
        self._pending_copy: tuple[int, int] | None = None
        # Per-slot PRNG state: requests own their stream (seed, token
        # index), so sampling is reproducible regardless of batch-mates.
        self._seeds = np.zeros((c.max_slots,), np.int32)
        self._counts = np.zeros((c.max_slots,), np.int32)
        # Per-slot sampler settings (data, not compiled constants).
        dflt = c.sampler or SamplerConfig()
        self._topks = np.full((c.max_slots,), dflt.top_k, np.int32)
        self._topps = np.full((c.max_slots,), dflt.top_p, np.float32)
        self._completed = 0
        self._generated_tokens = 0
        self._decode_steps = 0
        self._prefill_chunks = 0
        # Span-derived step telemetry (PR 5): the SAME observations feed
        # the Prometheus histograms and these accumulators, so stats()
        # and /metrics cannot drift. _last_step_end is the perf_counter
        # stamp of the previous decode step's host fetch; None = the
        # loop idled since (idle waits are not scheduling overhead).
        self._decode_step_sum = 0.0
        self._decode_step_count = 0
        self._sched_overhead_sum = 0.0
        self._sched_overhead_count = 0
        self._last_step_end: float | None = None
        # Liveness heartbeat: stamped at the top of every host-loop
        # iteration (the idle loop ticks at >= 10 Hz), and after each
        # decode step. The gateway's readiness probe compares the tick
        # age against its stall threshold.
        self._hb_tick = time.monotonic()
        self._hb_step: float | None = None
        self._vis_filter = VisibleIdFilter(
            self.tokenizer, skip_ids=(self.tokenizer.eos_id,)
        )
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._work = threading.Event()
        # params ride as a jit argument (not a closure constant) so the
        # weights aren't baked into the executable.
        self._jit_decode = jax.jit(
            self._decode_sample, donate_argnums=(1,), static_argnums=(8,)
        )
        # Multi-round decode program (PR 12): rounds is static (the
        # scan length; two cached traces per variant — R, and the
        # stop-bound 1), filters_active as in _jit_decode.
        self._jit_rounds = jax.jit(
            self._rounds_sample, donate_argnums=(2,), static_argnums=(0, 9)
        )
        # Derived stop screens memoized per stop tuple: the derivation
        # scans the vocabulary once, and submit() runs on caller
        # threads that must not repay it per request. BOUNDED
        # (evict-oldest past _SCREEN_CACHE_MAX) like every other
        # long-lived store here — stop tuples are client-supplied, so
        # an unbounded memo is a slow leak under per-request-unique
        # stops; a cycling adversary re-pays only the capped
        # (max_vocab_scan decodes) derivation on its own thread.
        self._screen_cache: dict[tuple, tuple[int, ...] | None] = {}
        self._jit_prefill = {}
        self._jit_chunk = {}  # (chunk, s_bucket) -> compiled chunk prefill
        self._jit_fused = {}  # (chunk, s_bucket) -> compiled fused step
        self._jit_copy_page = jax.jit(copy_page, donate_argnums=(0,))
        self._jit_install_page = jax.jit(install_page, donate_argnums=(0,))
        # Batched restore install (PR 17): one scatter per restore
        # BATCH — jit caches one trace per batch size actually seen
        # (1 and the controller's restore_batch, in practice).
        self._jit_install_pages = jax.jit(
            install_pages, donate_argnums=(0,)
        )
        self._jit_unembed = jax.jit(partial(unembed_one, self.cfg))
        # Speculative state (PR 9). _spec_cfg pins the MoE dispatch of
        # the k+1-token verify rows to the plain decode step's choice,
        # exactly as engine/speculative.py pins its verify chunk.
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._spec_xmodel_accepted = 0
        self._spec_shared_rows = 0
        self._spec_acc_sum = 0.0
        self._spec_acc_count = 0
        self._spec_verified_last = 0
        if self._draft_cfg is not None:
            self._spec_cfg = cfg.moe_pin_for(
                c.max_slots, c.max_slots * (c.spec_k + 1)
            )
            self._jit_spec = jax.jit(
                self._spec_sample,
                static_argnums=(0, 11, 12),
                donate_argnums=(3, 4),
            )
            self._jit_chunk_d = {}  # (chunk, s_bucket) -> draft chunk
            self._jit_prefill_d = {}  # s_bucket -> draft dense prefill
            # Draft-pool copy/install ride _jit_copy_page /
            # _jit_install_page: jit caches per input shape, so the
            # draft planes just add a second cached trace.
        # Round-robin pointer over prefilling slots (fairness when
        # several prompts fill concurrently).
        self._prefill_rr = 0
        self._dense_pending = -1
        self._thread = threading.Thread(
            target=self._run, name="continuous-batcher", daemon=True
        )
        self._thread.start()

    def _named(self, spec) -> "object":
        """NamedSharding over this batcher's mesh for an axis tuple."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        return NamedSharding(self.mesh, P(*spec))

    def _pool_sharding_for(self, cfg: ModelConfig) -> PagedKVCache:
        """Placement of one paged pool on the mesh (PR 13): pages over
        ``data`` (each data shard holds exactly the page range its
        slots allocate from — the host allocator's affinity), kv heads
        over ``model`` when they divide (a draft whose Hkv < mp
        replicates its heads — tiny planes, correctness first), page
        tables and lengths row-sharded over ``data``."""
        head = "model" if cfg.n_kv_heads % self._mp == 0 else None
        plane = self._named((None, "data", None, head, None))
        return PagedKVCache(
            k=plane,
            v=plane,
            page_table=self._named(("data", None)),
            length=self._named(("data",)),
        )

    @property
    def _sync_chunk(self) -> int:
        """Decode steps per dispatched device program (>= 1) — THE one
        definition the decode program, the page-overshoot budget, and
        the fetch accounting all share (three sites drifting
        independently is how the overshoot budget breaks)."""
        return max(1, self.config.steps_per_sync)

    @property
    def _depth(self) -> int:
        """Decode programs allowed in flight (>= 1). Read per loop
        iteration, so a depth change between bursts takes effect
        without restarting the batcher (the bench's A/B lever). With
        an adaptive controller the effective depth steers within
        [1, pipeline_depth] from the un-overlapped overhead signal
        (PR 15) — outputs are depth-invariant by the PR-6 contract,
        so steering can never change text."""
        d = max(1, self.config.pipeline_depth)
        if self.controller is not None:
            d = max(1, min(d, self.controller.depth_for(d)))
        return d

    @property
    def _spec_ok(self) -> bool:
        """Whether decode rounds run the speculative draft/verify
        program (PR 9). Read per loop iteration — ``spec_decode`` is
        the bench's A/B lever. Needs steps_per_sync == 1: the verify
        round IS the multi-token step, and folding further decode
        steps into the same program would need a second data-dependent
        scan (not worth the trace)."""
        return (
            self._draft_cfg is not None
            and self.config.spec_k > 0
            and self.config.spec_decode
            and self._sync_chunk == 1
        )

    @property
    def _rounds(self) -> int:
        """Decode rounds folded into one PLAIN (non-spec) dispatch
        (PR 12) — ``decode_rounds`` when engaged, else 1. Engages with
        steps_per_sync == 1 (the legacy multi-step chunk is unmasked),
        meshes included since PR 13: a frozen row's NULL-page write is
        one more row of the same sharded scatter every live row rides,
        and the stop screen / budgets / emit counts are per-row data
        sharded over ``data`` like every other row array. Read per
        loop iteration (the bench's A/B lever); while > 1 every
        non-spec dispatch runs the multi-round machinery — even a
        stop-bound 1-round window — so a pipeline window never mixes
        host- and device-advanced PRNG counts."""
        c = self.config
        if c.decode_rounds <= 1 or self._sync_chunk != 1:
            return 1
        return c.decode_rounds

    @property
    def _round_tokens(self) -> int:
        """Worst-case tokens ONE dispatched program advances a row by —
        the page-overshoot unit. Plain decode: the steps_per_sync
        chunk, or the decode_rounds window (PR 12) — counted from the
        CONFIG regardless of live engagement, exactly like spec_k, so
        in-flight admissions stay budgeted across a flip. With a draft
        configured: spec_k + 1 verify tokens."""
        rt = max(self._sync_chunk, self.config.decode_rounds)
        if self._draft_cfg is not None:
            rt = max(rt, self.config.spec_k + 1)
        return rt

    # -- device programs ------------------------------------------------

    def _decode_sample(
        self,
        params,
        cache,
        tokens,
        seeds,
        counts,
        temps,
        topks,
        topps,
        filters_active,
        groups=None,
    ):
        """``steps_per_sync`` decode+sample steps as ONE device program.

        Returns ``([slots, k] tokens, [slots, k] logprobs, cache,
        [slots] final token)`` — the final-token row is what a pipelined
        dispatch feeds the NEXT program without a host round trip.
        Each step folds ``(seed, count+j)`` into the per-slot PRNG —
        the same stream a chunk-of-1 loop would draw, so results are
        chunk-size-invariant (tested).

        ``groups`` (DecodeGroupArrays or None): per-step decode-group
        metadata — shared prefix pages read once per group through the
        grouped kernel. None compiles/runs the plain program (the two
        variants are separate cached traces; membership CHANGES within
        a variant are pure data and never recompile).
        """
        k = self._sync_chunk
        body = self._decode_body(
            params, seeds, temps, topks, topps, filters_active, groups
        )
        (cache, tok_end, _), (toks, logps) = jax.lax.scan(
            body, (cache, tokens, counts), None, length=k
        )
        return toks.T, logps.T, cache, tok_end

    def _decode_body(
        self,
        params,
        seeds,
        temps,
        topks,
        topps,
        filters_active,
        groups,
        stop=None,
    ):
        """One decode+sample step as a scan body — shared by the plain,
        the fused, AND the multi-round program so the paths cannot
        drift.

        ``stop`` (PR 12): None = the classic body (every row live,
        carry ``(cache, tok, cnt)``). A ``(budgets, screen)`` pair =
        the early-exit-masked body — carry grows to ``(cache, tok,
        cnt, alive, emitted)``; a live row decodes exactly the classic
        step (same K/V write, same (seed, count) PRNG fold, same
        sampler), then :func:`stop_scan_hit` freezes it on EOS, a
        screened stop candidate, or its emit budget. A frozen row
        stops writing K/V (decode_step_paged's write_mask), stops
        folding its PRNG (count invariance vs R = 1), holds its last
        token (the emit buffer past ``emitted`` is that stale token —
        the host reads only the real prefix), and stays frozen for the
        window's remainder (freezing is monotone, so the real tokens
        are always a prefix)."""

        def body(carry, _):
            if stop is None:
                cache, tok, cnt = carry
                alive = None
            else:
                cache, tok, cnt, alive, emitted = carry
            logits, cache = decode_step_paged(
                self.cfg, params, tok[:, None], cache, groups=groups,
                write_mask=alive, mesh=self.mesh,
            )
            keys = jax.vmap(
                lambda s, c: jax.random.fold_in(jax.random.PRNGKey(s), c)
            )(seeds, cnt)
            # filters_active is STATIC (two cached programs): the
            # all-defaults workload — every active request with
            # top_k=0, top_p=1.0 — never pays the filters' full-vocab
            # sort.
            next_tok, logp = sample_token_per_request(
                logits, keys, temps, topks, topps,
                filters_active=filters_active,
            )
            if stop is None:
                return (cache, next_tok, cnt + 1), (next_tok, logp)
            budgets, screen = stop
            next_tok = jnp.where(alive, next_tok, tok)
            adv = alive.astype(cnt.dtype)
            cnt = cnt + adv
            emitted = emitted + adv
            hit = stop_scan_hit(
                next_tok, self.tokenizer.eos_id, screen, emitted, budgets
            )
            alive = alive & ~hit
            return (cache, next_tok, cnt, alive, emitted), (next_tok, logp)

        return body

    def _rounds_sample(
        self,
        rounds,
        params,
        cache,
        tokens,
        seeds,
        counts,
        temps,
        topks,
        topps,
        filters_active,
        budgets,
        screen,
        groups=None,
    ):
        """Up to ``rounds`` decode rounds as ONE device program (PR 12)
        — the multi-round counterpart of :meth:`_decode_sample`, built
        on the same scan body with the early-exit mask threaded
        through the carry.

        counts: [B] device-resident per-row PRNG indices (the yield is
        data-dependent once rows can freeze mid-window, so counts
        thread program-to-program like the spec path's — the host
        mirror syncs at fetch); budgets: [B] max tokens each row may
        emit this window (its remaining max-new-tokens at dispatch);
        screen: [B, _SCREEN_W] -1-padded candidate stop ids. Every row
        enters alive, so each dispatched row emits >= 1 token — the
        invariant ``next_in`` (the final carry token, held through
        frozen rounds) relies on.

        Returns ``(emit [B, R], logps [B, R], cache, next_in [B],
        counts_out [B], emit_cnt [B])`` — only each row's leading
        ``emit_cnt`` tokens are real, the spec program's contract.
        """
        alive0 = jnp.ones(tokens.shape, dtype=bool)
        emitted0 = jnp.zeros_like(counts)
        body = self._decode_body(
            params, seeds, temps, topks, topps, filters_active, groups,
            stop=(budgets, screen),
        )
        (cache, tok_end, cnt_out, _, emitted), (toks, logps) = jax.lax.scan(
            body, (cache, tokens, counts, alive0, emitted0), None,
            length=rounds,
        )
        return toks.T, logps.T, cache, tok_end, cnt_out, emitted

    def _fused_sample(
        self,
        cfg_chunk,
        params,
        cache,
        tokens,
        seeds,
        counts,
        temps,
        topks,
        topps,
        filters_active,
        groups,
        chunk_tokens,
        chunk_table,
        chunk_start,
        chunk_last,
        chunk_done,
        stop_rounds=0,
        budgets=None,
        screen=None,
    ):
        """The fused scheduler step: ``steps_per_sync`` decode+sample
        steps AND one prefill chunk as ONE device program (PR 8).

        ``stop_rounds`` (STATIC, PR 12): > 0 makes this the MULTI-ROUND
        fused step — the chunk rides round 1 exactly as before (every
        row enters alive, so the first step needs no mask), then
        ``stop_rounds - 1`` early-exit-masked rounds follow via the
        shared stop body, and the returns grow by ``(emit_cnt,
        counts_out)`` with only each row's leading ``emit_cnt`` emit
        tokens real — the chunk keeps riding the decode dispatch under
        ``decode_rounds`` without a pipeline flush per admission.
        0 = the PR-8 behavior and return shape, byte-for-byte.

        The chunk rides the FIRST decode step's layer pass
        (:func:`~llm_consensus_tpu.models.transformer.fused_step_paged`
        — shared token axis, one K/V scatter, the ragged attention
        kernel); the remaining k-1 steps run the same scan body as
        :meth:`_decode_sample`. Returns the plain program's outputs
        plus ``chunk_logits`` [V] — the unembedded hidden state of the
        prompt position ``chunk_last`` (the host samples the request's
        first token from it at fetch, exactly as the standalone path
        does after its final chunk). ``chunk_done`` is STATIC (the
        host knows finality at dispatch): non-final chunks skip the
        full-vocab unembed entirely and return ``None`` — one extra
        cached trace per (chunk, bucket), no wasted [D]x[D,V] matvec
        per intermediate chunk.
        """
        k = self._sync_chunk
        logits, hidden, cache = fused_step_paged(
            self.cfg,
            params,
            tokens[:, None],
            cache,
            chunk_tokens,
            chunk_table,
            chunk_start,
            groups=groups,
            cfg_chunk=cfg_chunk,
            mesh=self.mesh,
        )
        keys = jax.vmap(
            lambda s, c: jax.random.fold_in(jax.random.PRNGKey(s), c)
        )(seeds, counts)
        tok1, logp1 = sample_token_per_request(
            logits, keys, temps, topks, topps, filters_active=filters_active
        )
        chunk_logits = None
        if chunk_done:
            c = chunk_tokens.shape[1]
            h_last = hidden[
                0, jnp.clip(chunk_last - chunk_start, 0, c - 1)
            ]
            chunk_logits = unembed_one(self.cfg, params, h_last)
        if stop_rounds:
            # Multi-round tail (PR 12): round 1 was the fused step
            # above (all rows alive by the dispatch invariant); apply
            # its freeze decision, then scan the masked body for the
            # window's remainder. Same (seed, count + j) folds as
            # _rounds_sample — the chunk lane never perturbs a decode
            # row's PRNG stream.
            emitted = jnp.ones_like(counts)
            alive = ~stop_scan_hit(
                tok1, self.tokenizer.eos_id, screen, emitted, budgets
            )
            if stop_rounds > 1:
                body = self._decode_body(
                    params, seeds, temps, topks, topps, filters_active,
                    groups, stop=(budgets, screen),
                )
                (cache, tok_end, cnt_out, _, emitted), (toks, logps) = (
                    jax.lax.scan(
                        body,
                        (cache, tok1, counts + 1, alive, emitted),
                        None,
                        length=stop_rounds - 1,
                    )
                )
                toks = jnp.concatenate([tok1[:, None], toks.T], axis=1)
                logps = jnp.concatenate([logp1[:, None], logps.T], axis=1)
                return (
                    toks, logps, cache, tok_end, chunk_logits, emitted,
                    cnt_out,
                )
            return (
                tok1[:, None], logp1[:, None], cache, tok1, chunk_logits,
                emitted, counts + 1,
            )
        if k > 1:
            body = self._decode_body(
                params, seeds, temps, topks, topps, filters_active, groups
            )
            (cache, tok_end, _), (toks, logps) = jax.lax.scan(
                body, (cache, tok1, counts + 1), None, length=k - 1
            )
            toks = jnp.concatenate([tok1[:, None], toks.T], axis=1)
            logps = jnp.concatenate([logp1[:, None], logps.T], axis=1)
            return toks, logps, cache, tok_end, chunk_logits
        return tok1[:, None], logp1[:, None], cache, tok1, chunk_logits

    def _spec_sample(
        self,
        spec_k,
        params,
        dparams,
        cache,
        dcache,
        tokens,
        seeds,
        counts,
        temps,
        topks,
        topps,
        filters_active,
        all_greedy,
        groups,
        draft_src,
        spec_fill,
        spec_off,
    ):
        """One speculative round — draft, verify, accept — as ONE
        device program (PR 9).

        tokens: [B] each row's newest committed token (its K/V not yet
        written — the same invariant as the plain decode step's input);
        counts: [B] device-resident per-row PRNG indices (data-
        dependent under accept/rollback, so they thread program-to-
        program like the cache instead of advancing on the host at
        dispatch). Shared draft streams: ``draft_src`` [B] is each
        row's stream donor (its own index = independent); a mate at
        ``spec_off[i]`` tokens behind its donor takes its first
        ``spec_off`` proposals from ``spec_fill`` [B, K] (the donor's
        already-committed suffix — host-known, certain-accept while
        the mate keeps agreeing) and the rest from the donor's fresh
        proposals, and its draft-cache writes consume exactly that
        stream, so its draft context stays consistent with what gets
        verified.

        The draft runs spec_k + 1 greedy steps (the +1 writes the last
        proposal's K/V — on full acceptance the bonus token's next
        round needs it; its own proposal is discarded, exactly like
        ``speculative_generate``'s extra step). The target verifies
        through :func:`verify_step_paged`'s ragged rows; the accept
        rule is :func:`llm_consensus_tpu.engine.accept.verify_tokens`
        — greedy rows byte-identical to plain decode, sampled rows the
        exact one-hot residual rule. Both caches' ``length`` rewinds
        to ``old + emit_cnt`` (count bookkeeping is the WHOLE
        rollback: decode rows write only private pages, so a rejected
        tail never touches registered/shared pages and simply gets
        overwritten).

        Returns (emit [B, K+1], emit_cnt [B], cache, dcache, next_in
        [B], counts_out [B]).
        """
        k = spec_k
        b = tokens.shape[0]
        dcfg = self._draft_cfg
        # Cross-model remap (PR 18): carried state (tokens, hist,
        # spec_fill, drafts) is TARGET vocab; the draft model's inputs
        # gather through t2d and its argmax lifts through d2t. Both
        # tables are trace constants; the identity case compiles with
        # no gather at all (self._t2d is None).
        t2d, d2t = self._t2d, self._d2t

        def dbody(carry, j):
            dc, tok, hist = carry
            din = tok if t2d is None else t2d[tok]
            lg, dc = decode_step_paged(
                dcfg, dparams, din[:, None], dc, mesh=self.mesh
            )
            prop = jnp.argmax(lg, axis=-1).astype(jnp.int32)  # [B]
            if d2t is not None:
                prop = d2t[prop]
            hist = hist.at[:, j].set(prop)
            # Next input = each row's stream token j: donor committed
            # fill while j < spec_off, else the donor's proposal
            # j - spec_off (already in hist — a mate only ever lags).
            from_donor = jnp.take_along_axis(
                hist[draft_src], jnp.clip(j - spec_off, 0, k)[:, None], axis=1
            )[:, 0]
            nxt = jnp.where(
                j < spec_off,
                spec_fill[:, jnp.minimum(j, k - 1)],
                from_donor,
            )
            return (dc, nxt, hist), None

        hist0 = jnp.zeros((b, k + 1), jnp.int32)
        (dcache, _, hist), _ = jax.lax.scan(
            dbody, (dcache, tokens, hist0), jnp.arange(k + 1)
        )
        j_idx = jnp.arange(k)[None, :]
        from_donor = jnp.take_along_axis(
            hist[draft_src],
            jnp.clip(j_idx - spec_off[:, None], 0, k),
            axis=1,
        )
        drafts = jnp.where(
            j_idx < spec_off[:, None], spec_fill, from_donor
        )  # [B, K] each row's verified proposals == its draft-fed stream

        vtok = jnp.concatenate([tokens[:, None], drafts], axis=1)
        logits, cache = verify_step_paged(
            self._spec_cfg, params, vtok, cache, groups=groups,
            mesh=self.mesh,
        )  # [B, K+1, V] fp32

        def row_keys(s, c):
            base = jax.random.PRNGKey(s)
            # Key j = the (seed, output-index) fold the plain sampler
            # burns for generated token counts + j: per-request streams
            # stay (seed, index)-addressed regardless of speculation.
            return jax.vmap(lambda j: jax.random.fold_in(base, c + j))(
                jnp.arange(k + 1)
            )

        keys = jax.vmap(row_keys)(seeds, counts)
        # all_greedy STATIC: the per-position PRNG folds above become
        # dead code on the greedy trace and jit erases them with the
        # leviathan machinery.
        emit, emit_cnt = verify_tokens(
            logits, drafts, temps, topks, topps, keys,
            filters_active=filters_active, all_greedy=all_greedy,
        )
        new_len = cache.length + emit_cnt
        cache = PagedKVCache(
            k=cache.k, v=cache.v, page_table=cache.page_table, length=new_len
        )
        # Draft-length invariant: committed - 1 == the target's length,
        # for every row alike (the draft's next round re-consumes the
        # newest committed token at that position).
        dcache = PagedKVCache(
            k=dcache.k,
            v=dcache.v,
            page_table=dcache.page_table,
            length=new_len,
        )
        next_in = jnp.take_along_axis(
            emit, (emit_cnt - 1)[:, None], axis=1
        )[:, 0]
        return emit, emit_cnt, cache, dcache, next_in, counts + emit_cnt

    def _spec_stream_plan(self, rows_now, k: int | None = None):
        """Host-side shared-draft-stream planning for one round.
        ``k``: this dispatch's EFFECTIVE spec window (PR 15's
        controller may shrink it below config.spec_k; the fill matrix
        and offsets size to what the program will actually verify).

        Per shared-prefix bucket (GroupTracker first-page buckets — the
        panel over one header), the member with the LONGEST committed
        text is the donor; every mate whose generated tokens are a
        prefix of the donor's rides the donor's stream (src -> donor,
        fill = the donor's committed suffix, off = how far behind).
        A mate that has diverged — different token anywhere — simply
        stays its own stream; the comparison re-runs per round, so
        divergence needs no sticky state and a retired donor just
        stops being chosen. Returns (src [S], fill [S, K], off [S],
        streams, shared_rows).

        Pipeline staleness rule: with a spec program still in flight
        (depth >= 2), ``generated`` lags the device by that program's
        data-dependent emissions, so a donor-suffix FILL (off > 0)
        built from the mirror would verify at shifted device positions
        and mostly reject — worse than the mate drafting for itself.
        The lagging-mate catch-up therefore only plans over an empty
        pipeline window (depth 1, or right after a flush). The off ==
        0 path stays allowed in flight: equal mirrors + one shared
        greedy stream emit identically on device, so live equality is
        preserved (a sampled mate can diverge invisibly for one round
        and re-drafts alone the moment the mirror syncs — rejects for
        a round, never wrong output).
        """
        c = self.config
        if k is None:
            k = c.spec_k
        n = c.max_slots
        src = np.arange(n, dtype=np.int32)
        off = np.zeros((n,), np.int32)
        fill = np.zeros((n, k), np.int32)
        decoding = {i for i, _ in rows_now}
        mirror_authoritative = not self._inflight
        shared = 0
        if c.share_prefix:
            for bucket in self._groups.stream_buckets():
                members = [i for i in bucket if i in decoding]
                if len(members) < 2:
                    continue
                donor = max(
                    members,
                    key=lambda i: (len(self._slots[i].generated), -i),
                )
                dgen = self._slots[donor].generated
                for i in members:
                    if i == donor:
                        continue
                    gen = self._slots[i].generated
                    m = len(gen)
                    if gen != dgen[:m]:
                        continue  # diverged from the donor's stream
                    delta = len(dgen) - m
                    if delta > 0 and not mirror_authoritative:
                        continue  # stale fill — see staleness rule
                    src[i] = donor
                    off[i] = min(delta, k)
                    tail = dgen[m : m + k]
                    if tail:
                        fill[i, : len(tail)] = tail
                    shared += 1
        streams = len({int(src[i]) for i in decoding})
        return src, fill, off, streams, shared

    def _prefill_fn(self, s_bucket: int):
        """Jitted per-bucket: prefill one prompt densely, scatter to pages.

        The legacy (``prefill_chunk=0``) admission path — and the parity
        baseline the chunked path is tested against.
        """
        if s_bucket not in self._jit_prefill:

            def f(params, cache, tokens, length, seq_id):
                dense = KVCache.create(self.cfg, 1, s_bucket)
                logits, dense = prefill(
                    self.cfg, params, tokens, length[None], dense
                )
                cache = write_prefill_kv(
                    cache, seq_id, dense.k[:, 0], dense.v[:, 0], length
                )
                return logits[0], cache

            self._jit_prefill[s_bucket] = jax.jit(f, donate_argnums=(1,))
        return self._jit_prefill[s_bucket]

    def _chunk_fn(self, chunk: int, s_bucket: int):
        """Jitted per (chunk, prompt-bucket): one paged prefill chunk.

        Compile-once per chunk bucket: chunk widths come from
        ``min(config.prefill_chunk, s_bucket)``, so the program family
        is bounded by the seq-bucket list exactly like dense prefill.
        The bucket also pins the MoE dispatch path to the choice a
        one-shot [1, s_bucket] prefill would trace — a chunk below the
        dense-fallback threshold must not diverge from the dense
        admission path it is parity-tested against.
        """
        key = (chunk, s_bucket)
        if key not in self._jit_chunk:
            cfg = self.cfg.moe_pin_for(s_bucket, chunk)
            self._jit_chunk[key] = jax.jit(
                partial(prefill_chunk_paged, cfg, mesh=self.mesh),
                donate_argnums=(4,),
            )
        return self._jit_chunk[key]

    def _chunk_fn_d(self, chunk: int, s_bucket: int):
        """Jitted per (chunk, prompt-bucket): the DRAFT model's paged
        prefill chunk — same tokens/table/start as the target's chunk,
        its own pool. Runs whenever a draft is configured (even with
        spec_decode flipped off) so every admitted prompt has draft
        context by the time speculation engages."""
        key = (chunk, s_bucket)
        if key not in self._jit_chunk_d:
            dcfg = self._draft_cfg.moe_pin_for(s_bucket, chunk)
            t2d = self._t2d

            def f(params, tokens, table, pos, dcache):
                # Cross-model remap (PR 18): the chunk arrives in
                # TARGET ids (the one prompt tokenization both pools
                # share); the draft model reads its t2d image. The
                # identity case traces with no gather.
                if t2d is not None:
                    tokens = t2d[tokens]
                return prefill_chunk_paged(
                    dcfg, params, tokens, table, pos, dcache,
                    mesh=self.mesh,
                )

            self._jit_chunk_d[key] = jax.jit(f, donate_argnums=(4,))
        return self._jit_chunk_d[key]

    def _prefill_fn_d(self, s_bucket: int):
        """Jitted per-bucket DRAFT dense prefill (the legacy
        ``prefill_chunk=0`` admission path's mirror)."""
        if s_bucket not in self._jit_prefill_d:
            dcfg = self._draft_cfg
            t2d = self._t2d

            def f(params, cache, tokens, length, seq_id):
                if t2d is not None:
                    # Cross-model remap (PR 18): target-id prompt, t2d
                    # image into the draft (see _chunk_fn_d).
                    tokens = t2d[tokens]
                dense = KVCache.create(dcfg, 1, s_bucket)
                _, dense = prefill(dcfg, params, tokens, length[None], dense)
                cache = write_prefill_kv(
                    cache, seq_id, dense.k[:, 0], dense.v[:, 0], length
                )
                return cache

            self._jit_prefill_d[s_bucket] = jax.jit(f, donate_argnums=(1,))
        return self._jit_prefill_d[s_bucket]

    def _draft_prefill_chunk(self, slot: _Slot, chunk_ids, pos: int) -> None:
        """Run the draft's mirror of one prefill chunk (stream-ordered
        behind whatever program carries the target's chunk)."""
        self._count_program("draft")
        _, self.draft_cache = self._chunk_fn_d(slot.chunk, slot.s_bucket)(
            self._draft_params,
            jnp.asarray(chunk_ids[None]),
            jnp.asarray(slot.table),
            jnp.int32(pos),
            self.draft_cache,
        )

    def _spec_catch_up(self) -> None:
        """Replay plain-decoded tokens through the draft before a spec
        dispatch, for every row that decoded while ``spec_decode`` was
        flipped off.

        Plain decode programs advance only the target cache; the draft
        mirror's length and K/V for the window's tokens go stale
        (tracked per row in ``_Slot.draft_lag``). Without the replay
        the next spec round's draft scan would write this row's K/V at
        the stale positions — wrong RoPE, wrong span — and the row's
        proposals would silently stop accepting for the rest of its
        life. Greedy text stays correct either way (verify is exact);
        what this protects is the speedup the flip is supposed to
        resume.

        The replay runs the draft's own chunk program over the missing
        committed positions ``[tlen - lag, tlen)`` — all >= prompt_len,
        so every write lands in the row's PRIVATE decode pages, never a
        refcount-shared prefix page — in ``slot.chunk``-wide windows
        (the admission traces, already compiled) plus width-1 steps for
        the tail, then re-installs the row's draft length. A flip is a
        between-bursts event; rows alive across one are the edge case.
        """
        lagging = [
            (i, s)
            for i, s in enumerate(self._slots)
            if s is not None and s.phase == "decode" and s.draft_lag > 0
        ]
        if not lagging:
            return
        # Host mirror (generated tokens) must be current: drain any
        # window the lag accumulated under.
        if self._inflight:
            self._flush_pipeline()
            lagging = [
                (i, s)
                for i, s in lagging
                if self._slots[i] is s and s.phase == "decode"
            ]
        for idx, slot in lagging:
            _flight.flight_recorder().record(
                "spec_catch_up",
                time.perf_counter(),
                trace_id=_tracing.trace_id_of(slot.request.trace),
                slot=idx,
                lag=slot.draft_lag,
            )
            # Newest committed token's K/V is pending in BOTH caches
            # (the round input), so the draft must cover [dlen, tlen).
            tlen = slot.prompt_len + len(slot.generated) - 1
            dlen = tlen - slot.draft_lag
            if slot.table is not None:
                table = slot.table
            else:
                # Dense-admission rows: the table is the page list in
                # positional order (mirrors _dense_prefill_pending).
                table = np.full(
                    (self.config.pages_per_seq,), NULL_PAGE, np.int32
                )
                table[: len(slot.pages)] = slot.pages
            table_dev = jnp.asarray(table)
            gen = np.asarray(slot.generated, np.int32)
            cur = dlen
            while cur < tlen:
                width = slot.chunk if slot.chunk and tlen - cur >= slot.chunk else 1
                toks = gen[cur - slot.prompt_len : cur - slot.prompt_len + width]
                self._count_program("draft")
                _, self.draft_cache = self._chunk_fn_d(width, slot.s_bucket)(
                    self._draft_params,
                    jnp.asarray(toks[None]),
                    table_dev,
                    jnp.int32(cur),
                    self.draft_cache,
                )
                cur += width
            # install_seq is idempotent on the (unchanged) table row;
            # what this fixes is the row's draft length.
            self.draft_cache = install_seq(
                self.draft_cache, jnp.int32(idx), table_dev, jnp.int32(tlen)
            )
            slot.draft_lag = 0

    def _fused_fn(self, chunk: int, s_bucket: int):
        """Jitted per (chunk, prompt-bucket): the fused scheduler step
        (:meth:`_fused_sample`). The bucket pins the chunk side's MoE
        dispatch path exactly as :meth:`_chunk_fn` does — the fused
        program must stay output-identical to the split programs it
        replaces (the A/B contract)."""
        key = (chunk, s_bucket)
        if key not in self._jit_fused:
            cfg_chunk = self.cfg.moe_pin_for(s_bucket, chunk)
            self._jit_fused[key] = jax.jit(
                partial(self._fused_sample, cfg_chunk),
                donate_argnums=(1,),
                static_argnums=(8, 14, 15),
            )
        return self._jit_fused[key]

    @property
    def _fused_ok(self) -> bool:
        """Whether a ready chunk may ride the decode dispatch this
        iteration (PR 8; mesh-native since PR 13). On a mesh the fused
        program's concat [B + C] token axis is laid out by GSPMD from
        the operands' shardings — decode rows over ``data``, the chunk
        lane riding replicated with its K/V scatter landing on the
        owner shard's page range — and the attention read goes through
        the same one kernel seam as the plain step, so ONE device
        program per scheduler iteration holds on every topology. Read
        per iteration: the bench flips ``config.ragged_attention``
        between bursts on one batcher."""
        return (
            self.config.ragged_attention
            and self.config.prefill_chunk > 0
        )

    # -- public API -----------------------------------------------------

    def submit(
        self,
        prompt: str,
        *,
        max_new_tokens: int | None = None,
        temperature: float = 0.0,
        seed: int = 0,
        top_k: int | None = None,
        top_p: float | None = None,
        stop: list[str] | tuple[str, ...] | None = None,
        prompt_ids=None,
    ) -> Future:
        """Enqueue a request; Future resolves to a :class:`ServeResult`.

        ``top_k``/``top_p``: ``None`` inherits the batcher's
        config-level sampler; any EXPLICIT value is authoritative —
        including 0 / 1.0, which mean *disabled* exactly as in
        ``SamplingParams`` (so a protocol request with default params
        samples unfiltered on this backend just like on LocalBackend,
        and "no top_k" is expressible on a batcher configured with
        one). ``stop`` follows the engine's stop-sequence contract —
        text trimmed at the earliest stop (stop removed), and the row
        retires as soon as the stop appears (every token is
        host-checked, so multi-token stops end decoding immediately).
        ``prompt_ids``: the prompt's already-encoded token ids — the
        fleet router tokenizes once for routing and passes them
        through (PR 14), so the common panel header is not encoded
        twice per request. Must be THIS tokenizer's encoding of
        ``prompt``; the same largest-bucket truncation applies."""
        if self._stop.is_set():
            raise RuntimeError("batcher stopped")
        c = self.config
        if max_new_tokens is None:
            max_new_tokens = c.max_new_tokens
        if max_new_tokens <= 0:
            raise ValueError(f"max_new_tokens must be > 0, got {max_new_tokens}")
        full_ids = (
            prompt_ids
            if prompt_ids is not None
            else self.tokenizer.encode(prompt)
        )
        cap = c.seq_buckets[-1]
        if len(full_ids) > cap:
            if not c.truncate_prompts:
                raise ValueError(
                    f"prompt is {len(full_ids)} tokens but the largest "
                    f"sequence bucket is {cap} (set truncate_prompts=True "
                    "to left-truncate instead)"
                )
            log.warning(
                "prompt of %d tokens left-truncated to %d (largest bucket)",
                len(full_ids),
                cap,
            )
        ids = np.asarray(full_ids[-cap:], np.int32)
        dflt = c.sampler or SamplerConfig()
        stop = tuple(stop or ())
        window = stop_tail_window(self.tokenizer, stop)
        # Multi-round decode (PR 12): the device stop screen, derived
        # once per distinct stop tuple (the derivation scans the
        # vocabulary; this thread must not repay it per request).
        if stop in self._screen_cache:
            screen = self._screen_cache[stop]
        else:
            screen = derived_stop_screen(
                self.tokenizer, stop, max_ids=_SCREEN_W
            )
            with self._lock:
                while len(self._screen_cache) >= _SCREEN_CACHE_MAX:
                    self._screen_cache.pop(
                        next(iter(self._screen_cache))
                    )
                self._screen_cache[stop] = screen
        req = _Request(
            prompt_ids=ids,
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            seed=seed,
            future=Future(),
            top_k=dflt.top_k if top_k is None else top_k,
            top_p=dflt.top_p if top_p is None else top_p,
            stop=stop,
            stop_window=window,
            stop_screen=screen,
            trace=_tracing.current_trace(),
            rid=f"req-{next(_RID)}",
            t_submit=time.perf_counter(),
        )
        with self._lock:
            self._waiting.append(req)
            _M_WAITING.set(len(self._waiting))
        _M_SUBMITTED.inc()
        self._work.set()
        return req.future

    def heartbeat(self) -> dict:
        """Host-loop liveness: seconds since the last loop tick and the
        last decode step. The loop ticks at >= 10 Hz even when idle, so
        a large ``last_tick_age_s`` means the worker is wedged (stuck
        device call, deadlock) — the gateway's ``/readyz`` probe flips
        to 503 past its stall threshold."""
        now = time.monotonic()
        alive = self._thread.is_alive() and not self._stop.is_set()
        return {
            "alive": alive,
            # Lifecycle state (PR 19): a standalone batcher is simply
            # serving or stopped; a fleet overlays "draining"/"retired"
            # on its replicas during elastic scale-down so /readyz can
            # tell a deliberate drain from a wedged loop.
            "state": "serving" if alive else "stopped",
            "last_tick_age_s": now - self._hb_tick,
            "last_step_age_s": (
                now - self._hb_step if self._hb_step is not None else None
            ),
        }

    # -- fleet surface (PR 14) ------------------------------------------
    # Everything the replica router/gateway threads call on a batcher:
    # read-only probes under the admission lock, plus preempt/export
    # REQUESTS the worker loop executes (the demote path's device_get
    # must never race the worker's dispatch-time buffer donation).

    def prefix_probe(self, ids) -> dict:
        """How much of this prompt's page-aligned prefix chain is
        already resident here: ``registry_tokens`` (device pages — the
        affinity signal; restore-free) and ``host_tokens`` (the host
        tier's extension past the registry match — restorable at
        device_put latency; capped at ``_PROBE_HOST_PAGES`` pages —
        it only breaks ties). Read-only: no refcounts, ticks, or
        counters move (PrefixRegistry.probe), so the router can probe
        every replica per request. Unready (in-flight-prefill) nodes
        count — a burst's mates must probe the donor's replica as a
        match while its prefill is still running."""
        c = self.config
        pg = c.page_size
        usable_full = (len(ids) - 1) // pg
        if usable_full <= 0 or not c.share_prefix:
            return {
                "registry_tokens": 0,
                "host_tokens": 0,
                "scope": self.chain_scope(),
            }
        chain = tuple(int(t) for t in ids[: usable_full * pg])
        best = (0, 0)
        with self._lock:
            for registry in self._registries:
                _, t = registry.probe(ids)
                k = t // pg
                h = 0
                if self._offload is not None:
                    # One batched run_len probe per registry (PR 17):
                    # over the remote store this is a single RTT for
                    # the whole capped extension walk instead of up to
                    # _PROBE_HOST_PAGES sequential __contains__ calls.
                    cap = min(usable_full - k, _PROBE_HOST_PAGES)
                    if cap > 0:
                        keys = [
                            self._store_key(chain[: (k + j + 1) * pg])
                            for j in range(cap)
                        ]
                        rl = getattr(self._offload, "run_len", None)
                        if rl is not None:
                            h = rl(keys)
                        else:
                            for key in keys:
                                if key not in self._offload:
                                    break
                                h += 1
                best = max(best, (t, h * pg))
        return {
            "registry_tokens": best[0],
            "host_tokens": best[1],
            "scope": self.chain_scope(),
        }

    def chain_scope(self) -> dict:
        """WHOSE chains this batcher's probe counts (PR 18): the model
        name and a weights-fingerprint prefix (plus the draft pairing
        when one is mounted). A heterogeneous fleet's front tier
        aggregates residency across members whose caches are mutually
        unrestorable — without the scope, ``/debug/chains`` counts
        them as one anonymous pool. Fingerprint computed lazily once:
        it walks every param leaf (the PR-14 store-key walk), a debug
        cost the first probe pays, never construction or serving."""
        if self._probe_scope is None:
            doc = {
                "model": self.cfg.name,
                "weights": _weights_fingerprint(self.params)[1][:12],
            }
            if self._draft_cfg is not None:
                doc["draft_model"] = self._draft_cfg.name
                if self._vocab_map is not None:
                    doc["draft_vocab_coverage"] = round(
                        self._vocab_map.coverage, 4
                    )
            self._probe_scope = doc
        return dict(self._probe_scope)

    def load_cost(self) -> float:
        """Modeled outstanding HBM bytes of this replica's admitted
        work — the router's least-loaded signal (PR 14): the KV terms
        of :meth:`_program_cost` integrated over each admitted
        request's remaining schedule (remaining prefill writes, plus
        every remaining decode step reading the whole committed
        context and writing one token), per slot and per waiting
        request. Weight reads amortize over whatever batch each
        request joins and are identical across replicas, so they
        cancel out of a load COMPARISON and are left out. A
        32k-context request weighs what it costs, not one unit of
        queue depth."""
        kvb = self._kv_token_bytes + self._draft_kv_token_bytes
        total = 0
        with self._lock:
            for s in self._slots:
                if s is None:
                    continue
                done = len(s.generated)
                rem = max(0, s.request.max_new_tokens - done)
                L = s.prompt_len + done
                if s.phase == "prefill":
                    total += max(0, s.prompt_len - s.next_pos)
                    rem = s.request.max_new_tokens
                    L = s.prompt_len
                total += rem * L + rem * (rem - 1) // 2 + rem
            for r in self._waiting:
                # A waiting request's whole schedule: the SAME tokens
                # modeled_request_cost charges at the admission door
                # (one formula, two surfaces — the unit-normalization
                # contract of PR 15's cost-budget admission).
                total += self._cost_tokens(len(r.prompt_ids), r.max_new_tokens)
        return float(total * kvb)

    @staticmethod
    def _cost_tokens(L: int, rem: int) -> int:
        """KV-token units of one not-yet-started request's whole
        schedule: L prefill writes, then rem decode steps each reading
        the full committed context (L + j at step j) and writing one
        token — THE formula load_cost integrates and
        modeled_request_cost prices, kept in one place so the router
        and the admission bound can never drift units."""
        return L + rem * L + rem * (rem - 1) // 2 + rem

    def modeled_request_cost(
        self, prompt_tokens: int, max_new_tokens: int | None = None
    ) -> float:
        """Modeled HBM bytes of one request's whole schedule — the
        cost-budget admission unit (PR 15). Deliberately the same
        KV-term formula and byte unit as :meth:`load_cost`, so the
        gateway queue bound, the overflow hard cap, and the fleet
        router's least-loaded comparison all speak modeled bytes: a
        32k-context request weighs what it costs, not one unit of
        queue depth."""
        c = self.config
        if max_new_tokens is None:
            max_new_tokens = c.max_new_tokens
        L = max(1, min(int(prompt_tokens), c.seq_buckets[-1]))
        kvb = self._kv_token_bytes + self._draft_kv_token_bytes
        return float(self._cost_tokens(L, int(max_new_tokens)) * kvb)

    def waiting_depth(self) -> int:
        """Requests admitted to this batcher but not yet slotted — the
        router's congestion signal for rebalancing (cheap; stats()
        walks the registries and is too heavy per routed request)."""
        with self._lock:
            return len(self._waiting)

    def device_programs_total(self) -> int:
        """All device programs this batcher has dispatched (the
        per-replica split of the process-global
        gateway_device_programs_total)."""
        with self._lock:
            return sum(self._programs.values())

    def prefix_hit_rate(self) -> float:
        """Committed prefix-registry hit rate (hits / lookups; 0.0
        before the first lookup)."""
        with self._lock:
            lookups = sum(r.lookups for r in self._registries)
            hits = sum(r.hits for r in self._registries)
        return hits / max(1, lookups)

    def cached_chain_pages(self) -> int:
        """ALL registry-resident chain pages, pinned-by-live-slots
        included (cheap — a node count, no tree walk). The fleet
        hook's is-there-anything-to-preserve signal: pinned chains
        become demotable as their slots retire, so a non-zero count
        means overload admission degrades to restore latency; zero
        means the offered traffic registers nothing shareable and
        classic shedding is the only backpressure left."""
        with self._lock:
            return sum(r.cached_pages for r in self._registries)

    @property
    def host_page_bytes(self) -> int:
        """Approximate host-tier bytes one demoted page occupies
        (target + draft planes; int8 pools' scale planes add a few
        percent on top) — the router's store-headroom unit."""
        return (
            self._kv_token_bytes + self._draft_kv_token_bytes
        ) * self.config.page_size

    def request_preempt(self, n_pages: int) -> None:
        """Ask the worker to demote up to ``n_pages`` reclaimable
        registry pages to the host tier NOW — the fleet's
        preempt-instead-of-shed lever: an overload storm frees device
        pages at restore-latency cost instead of 429ing. Enqueued;
        the worker's next iteration executes it (callable from any
        thread). The backlog is the MAX of outstanding requests, not
        the sum: a storm can call this hundreds of times between two
        worker ticks, and summing would wipe the victim's entire
        prefix cache in one giant evict walk + device_get under the
        admission lock — one bounded demotion per worker iteration
        while overflow persists is the intent."""
        if self._offload is None or n_pages <= 0:
            return
        with self._lock:
            self._preempt_req = max(self._preempt_req, int(n_pages))
        self._work.set()

    def request_group_cap(self, n: int) -> None:
        """Ask the worker to resize the shared-prefix group-formation
        cap (``GroupTracker.max_groups`` — how many prefix groups the
        grouped decode program batches per dispatch) at its next
        iteration. The fleet controller (PR 19) sizes this from
        fleet-level sharing pressure; the tracker itself is
        worker-owned, so the change rides the same enqueued-request
        discipline as preempts. Clamped to [1, max_slots]."""
        n = max(1, min(int(n), self.config.max_slots))
        with self._lock:
            self._group_cap_req = n
        self._work.set()

    def group_cap(self) -> int:
        """Current shared-prefix group-formation cap (steered value
        once a ``request_group_cap`` has been applied)."""
        return int(self._groups.max_groups)

    def active_requests(self) -> int:
        """Admitted-but-unfinished requests on this batcher: waiting +
        slotted. The elastic-retire drain barrier — a draining replica
        is closeable once this reaches zero (cheap: two length reads
        under the admission lock)."""
        with self._lock:
            return len(self._waiting) + sum(
                1 for s in self._slots if s is not None
            )

    def request_export(
        self, ids, stream_until: float | None = None
    ) -> threading.Event:
        """Ask the worker to spill the READY resident pages of this
        prompt's registered prefix chain to the (shared) host store
        WITHOUT evicting them — the rebalance transport: the chain
        stays hot here and becomes restorable on any replica sharing
        the store. Returns an Event set when the spill has run (set
        immediately when the tier is off — nothing to do).

        With ``stream_until`` (a ``time.monotonic`` deadline) the
        export STREAMS (PR 17): each worker iteration spills the pages
        that became ready since the last pass — so while a chunked
        prefill is still computing the chain's tail, the head is
        already crossing the wire — and the export re-arms itself
        until every usable chain page is out (then the event sets) or
        the deadline passes (the event sets with whatever made it;
        the coordinator's own wait bounds the handoff either way)."""
        done = threading.Event()
        if self._offload is None:
            done.set()
            return done
        with self._lock:
            self._exports.append(
                [np.asarray(ids, np.int32), done, stream_until, 0]
            )
        self._work.set()
        return done

    # -- route-driven restore prefetch (PR 17) --------------------------
    # When the fleet router picks THIS replica as a request's
    # destination, the chain's host-store pages are known before the
    # request clears the gateway queue + admission. prefetch_chain()
    # pulls them store -> local staging (the expensive remote hop) on a
    # side thread so admission's restore plan starts from staged planes
    # instead of a cold round trip; the device_put half still happens
    # on the worker (restore discipline unchanged). Wrong-guess safety:
    # entries are chain-keyed (content deterministic in the key), so a
    # stale or evicted guess can never corrupt — it just falls through
    # to get_run/recompute. The staging dict is byte-bounded by entry
    # COUNT (a few chains' worth) and LRU-evicts, counted as "expired".

    def prefetch_chain(self, ids) -> bool:
        """Queue a speculative store->host pull of this prompt's chain
        (gateway/router thread; non-blocking). Returns False when
        there is nothing to prefetch (no offload tier, sharing off,
        sub-page prompt, or the queue is saturated)."""
        c = self.config
        if self._offload is None or not c.share_prefix:
            return False
        if (len(ids) - 1) // c.page_size <= 0:
            return False
        with self._prefetch_lock:
            if len(self._prefetch_q) >= 32:
                return False  # saturated: drop, never block the router
            self._prefetch_q.append(np.asarray(ids, np.int32))
            if self._prefetch_thread is None:
                self._prefetch_thread = threading.Thread(
                    target=self._prefetch_loop,
                    name="kv-prefetch",
                    daemon=True,
                )
                self._prefetch_thread.start()
        self._prefetch_have.set()
        return True

    def _prefetch_loop(self) -> None:
        while not self._stop.is_set():
            self._prefetch_have.wait(timeout=0.2)
            while True:
                with self._prefetch_lock:
                    if not self._prefetch_q:
                        self._prefetch_have.clear()
                        break
                    ids = self._prefetch_q.popleft()
                if self._stop.is_set():
                    return
                try:
                    self._prefetch_one(ids)
                except Exception:  # noqa: BLE001 — advisory path
                    log.exception("kv prefetch failed (ignored)")

    def _prefetch_one(self, ids) -> None:
        """Pull one chain's restorable pages store -> staging. Probes
        the registries first so device-resident pages aren't refetched;
        skips keys already staged; stages the contiguous run the store
        holds past that point."""
        c = self.config
        pg = c.page_size
        usable_full = (len(ids) - 1) // pg
        chain = tuple(int(t) for t in ids[: usable_full * pg])
        with self._lock:
            k = 0
            for reg in self._registries:
                _, t = reg.probe(ids)
                k = max(k, t // pg)
        keys = [
            self._store_key(chain[: (j + 1) * pg])
            for j in range(k, usable_full)
        ]
        with self._prefetch_lock:
            while keys and keys[0] in self._prefetched:
                self._prefetched.move_to_end(keys[0])
                keys.pop(0)
        if not keys:
            return
        store = self._offload
        gr = getattr(store, "get_run", None)
        if gr is not None:
            run = gr(keys)
        else:
            run = []
            for key in keys:
                planes = store.get(key)
                if planes is None:
                    break
                run.append(planes)
        if not run:
            return
        expired = 0
        with self._prefetch_lock:
            for key, planes in zip(keys, run):
                self._prefetched[key] = planes
                self._prefetched.move_to_end(key)
            while len(self._prefetched) > self._prefetch_cap:
                self._prefetched.popitem(last=False)
                expired += 1
            self._prefetch_fetched += len(run)
            self._prefetch_expired += expired
        _M_PREFETCH.labels(event="fetched").inc(len(run))
        if expired:
            _M_PREFETCH.labels(event="expired").inc(expired)
        _flight.flight_recorder().record(
            "prefetch", time.perf_counter(), pages=len(run),
            expired=expired,
        )

    def _prefetch_take(self, keys: list) -> list:
        """Consume the staged contiguous prefix of ``keys`` (admission
        path, caller holds ``self._lock`` — lock order is always
        _lock -> _prefetch_lock). Taken entries leave the staging dict:
        their planes transfer to the restore plan."""
        out: list = []
        with self._prefetch_lock:
            for key in keys:
                planes = self._prefetched.pop(key, None)
                if planes is None:
                    break
                out.append(planes)
            if out:
                self._prefetch_hits += len(out)
        if out:
            _M_PREFETCH.labels(event="hit").inc(len(out))
        return out

    def _prefetch_stats(self) -> dict:
        """Stats()-shaped prefetch counters (lock order: the caller
        holds ``self._lock``; _prefetch_lock nests inside it)."""
        with self._prefetch_lock:
            return {
                "prefetch_fetched_pages": self._prefetch_fetched,
                "prefetch_hit_pages": self._prefetch_hits,
                "prefetch_expired_pages": self._prefetch_expired,
                "prefetch_staged_pages": len(self._prefetched),
            }

    def _steer_step(self) -> None:
        """Worker-side application of a queued group-cap resize (PR
        19). The tracker re-forms its group view lazily, so the new
        cap takes effect at the next grouped-decode array build."""
        if self._group_cap_req is None:
            return
        with self._lock:
            n, self._group_cap_req = self._group_cap_req, None
        if n is not None and n != self._groups.max_groups:
            self._groups.max_groups = n
            self._groups._dirty = True

    def _preempt_step(self) -> None:
        """Worker-side execution of queued preempt requests: one
        registry evict walk whose on_evict hook demotes the victims
        (the PR-4 path — preemption IS eviction pointed at the host
        tier, requested by the router instead of by a short pool)."""
        if not self._preempt_req:
            return
        with self._lock:
            n, self._preempt_req = self._preempt_req, 0
            freed = 0
            for reg in self._registries:
                if freed >= n:
                    break
                freed += reg.evict(n - freed)
            self._preempted_pages += freed
        if freed:
            if self.controller is not None:
                # Restore-pacing debt (PR 15): preempt-demoted bytes
                # that the restore path will have to repay.
                self.controller.note_preempt_demote(
                    freed * self.host_page_bytes
                )
            _flight.flight_recorder().record(
                "preempt", time.perf_counter(), pages=freed
            )

    def _export_step(self) -> None:
        """Worker-side execution of ONE queued chain export per loop
        iteration (the same bounded-stall discipline as restores):
        probe the registries for the chain's resident nodes, spill the
        ready ones the store doesn't already hold.

        STREAMING exports (PR 17, ``stream_until`` set) spill only the
        DELTA of pages that became ready since their last pass, then
        re-arm at the back of the queue until the whole usable chain is
        out or the deadline passes — overlapping the wire transfer with
        the chunked prefill that is still computing the chain's tail.
        Re-arming deliberately does NOT set ``_work``: the worker's
        idle tick (the 0.1 s ``_work.wait`` timeout) repolls a pending
        stream without busy-spinning an otherwise idle loop."""
        if not self._exports:
            return
        streaming = False
        with self._lock:
            if not self._exports:
                return
            entry = self._exports.popleft()
            ids, done, stream_until, spilled = entry
            nodes: list = []
            for reg in self._registries:
                cand, _ = reg.probe(ids)
                if len(cand) > len(nodes):
                    nodes = cand
            ready = [n for n in nodes if n.ready]
            fetched = 0
            if len(ready) > spilled:
                # Delta-spill: earlier passes of this streamed export
                # already pushed ready[:spilled] (ready order is chain
                # order — pages become ready root-first).
                fetched, _ = self._spill_nodes(ready[spilled:])
                entry[3] = len(ready)
            self._exported_pages += fetched
            if stream_until is not None:
                expected = (len(ids) - 1) // self.config.page_size
                if (
                    len(ready) < expected
                    and time.monotonic() < stream_until
                ):
                    streaming = True
                    self._exports.append(entry)
        if fetched or not streaming:
            # Quiet re-poll passes (streamed export waiting on prefill
            # progress) don't spam the flight ring.
            _flight.flight_recorder().record(
                "export", time.perf_counter(), pages=fetched,
                resident=len(ready), streaming=streaming,
            )
        if not streaming:
            done.set()

    def stats(self) -> dict:
        """Live serving counters — a consistent snapshot (the worker
        mutates slots/pages/counters under the same lock).

        ``free_pages`` counts reclaimable prefix-registry pages (held by
        nobody but the registry — evicted on demand at admission) as
        free: they are available capacity, exactly like OS page-cache
        memory. ``cached_pages`` reports the registry-resident total.
        """
        with self._lock:
            regs = self._registries
            return {
                "active_slots": self._decoding(),
                "prefilling_slots": sum(
                    s is not None and s.phase == "prefill"
                    for s in self._slots
                ),
                "max_slots": self.config.max_slots,
                "waiting": len(self._waiting),
                "free_pages": sum(p.available for p in self._pools)
                + sum(r.reclaimable_pages() for r in regs),
                "total_pages": self.config.n_pages - 1,
                "cached_pages": sum(r.cached_pages for r in regs),
                "completed_requests": self._completed,
                "generated_tokens": self._generated_tokens,
                "decode_steps": self._decode_steps,
                "prefill_chunks": self._prefill_chunks,
                "prefix_lookups": sum(r.lookups for r in regs),
                "prefix_hits": sum(r.hits for r in regs),
                "prefix_pages_shared": sum(r.pages_shared for r in regs),
                "prefix_pages_copied": sum(r.pages_copied for r in regs),
                "prefix_evictions": sum(r.evictions for r in regs),
                # Group-aware decode attention (PR 3): KV bytes the
                # grouped kernel did not re-read, the largest active
                # group right now (0 = ungrouped program), and the
                # lifetime peak group size.
                "shared_kv_bytes_saved": self._kv_bytes_saved,
                "decode_group_size": self._groups.largest_group,
                "decode_group_peak": self._groups.peak_group,
                # Host-RAM offload tier (PR 4). Demoted counts every
                # eviction that reached the host store (including
                # refreshes of already-spilled chains); restored counts
                # pages promoted back instead of re-prefilled — each
                # one is page_size prompt tokens the chip never
                # recomputed; dropped is LRU pressure within the host
                # budget.
                "offload_demoted_pages": (
                    self._offload.demoted_pages if self._offload else 0
                ),
                "offload_restored_pages": self._offload_restored,
                "offload_dropped_pages": (
                    self._offload.dropped_pages if self._offload else 0
                ),
                "offload_host_bytes": (
                    self._offload.bytes_used if self._offload else 0
                ),
                "offload_host_pages": (
                    len(self._offload) if self._offload else 0
                ),
                # Fleet hooks (PR 14): pages demoted by router-
                # requested preemption (a subset of offload_demoted),
                # and ready chain pages spilled by rebalance exports
                # (resident here AND restorable fleet-wide).
                "preempted_pages": self._preempted_pages,
                "exported_pages": self._exported_pages,
                # Route-driven restore prefetch (PR 17): pages staged
                # store->host ahead of admission, staged pages the
                # restore planner consumed, and staged pages the LRU
                # cap expired unconsumed (mirrors of
                # gateway_kv_prefetch_total, lockstep tested). Wire
                # bytes mirror the remote store client's own counters
                # (0 for an in-process tier — no wire).
                **self._prefetch_stats(),
                "offload_wire_tx_bytes": (
                    getattr(self._offload, "tx_bytes", 0)
                    if self._offload
                    else 0
                ),
                "offload_wire_rx_bytes": (
                    getattr(self._offload, "rx_bytes", 0)
                    if self._offload
                    else 0
                ),
                # Span-derived step telemetry (PR 5): the same
                # observations that feed gateway_decode_step_seconds /
                # gateway_sched_overhead_seconds — one instrumentation
                # site, two surfaces (lockstep tested).
                "decode_step_seconds_sum": self._decode_step_sum,
                "decode_step_seconds_count": self._decode_step_count,
                "sched_overhead_seconds_sum": self._sched_overhead_sum,
                "sched_overhead_seconds_count": self._sched_overhead_count,
                # Pipelined decode dispatch (PR 6): programs currently
                # dispatched-not-fetched, and drains forced by
                # stable-cache operations (restores, CoW copies, dense
                # prefill) — the same observations behind
                # gateway_dispatch_inflight /
                # gateway_pipeline_flushes_total (lockstep tested).
                "dispatch_inflight": len(self._inflight),
                "pipeline_flushes": self._pipeline_flushes,
                # Fused scheduler step (PR 8): device programs by kind
                # (fused = decode rows + a prefill chunk in ONE
                # program), ragged-row occupancy, and the count of loop
                # iterations that ran any program — programs/iteration
                # == 1 is the fusion working; the same observations
                # behind gateway_device_programs_total /
                # gateway_ragged_rows_per_program (lockstep tested).
                "device_programs_fused": self._programs["fused"],
                "device_programs_decode": self._programs["decode"],
                "device_programs_prefill": self._programs["prefill"],
                "device_programs_spec": self._programs["spec"],
                "device_programs_draft": self._programs["draft"],
                "ragged_rows_sum": self._ragged_rows_sum,
                "ragged_rows_count": self._ragged_rows_count,
                "work_iterations": self._work_iterations,
                # Multi-round on-device decode (PR 12) — the same
                # observations behind gateway_device_rounds_total /
                # gateway_decode_rounds_per_program (lockstep tested):
                # total decode rounds dispatched, and the histogram's
                # sum/count over decode-advancing programs
                # (decode/fused pass their window, spec passes 1 —
                # rounds count once per PROGRAM, not per row).
                # device_rounds_total / decode_rounds_count is the
                # realized rounds per program, and device programs per
                # generated token drops ~R× at R for a fixed batch
                # shape — the cross-check the bench leg gates.
                "device_rounds_total": self._device_rounds,
                "decode_rounds_sum": self._decode_rounds_sum,
                "decode_rounds_count": self._decode_rounds_count,
                # Mesh topology (PR 13) — the same numbers behind
                # gateway_mesh_shards{axis} (lockstep tested): 1 on a
                # single chip; the serving features engage either way
                # (README engage matrix).
                "mesh_data_shards": self._dp,
                "mesh_model_shards": self._mp,
                # Speculative decoding (PR 9) — the same observations
                # behind gateway_spec_draft_tokens_total /
                # gateway_spec_accepted_tokens_total /
                # gateway_spec_acceptance / gateway_spec_verified_tokens
                # (lockstep tested). drafted counts k per STREAM per
                # round (one shared stream per agreeing panel group);
                # shared_draft_rows counts row-rounds that reused a
                # donor stream — per-sequence drafting would have
                # drafted for those rows too, so this is the panel
                # amortization realized.
                "spec_draft_tokens": self._spec_drafted,
                "spec_accepted_tokens": self._spec_accepted,
                "spec_cross_model_accepted_tokens": (
                    self._spec_xmodel_accepted
                ),
                "spec_acceptance_sum": self._spec_acc_sum,
                "spec_acceptance_count": self._spec_acc_count,
                "spec_verified_tokens_last": self._spec_verified_last,
                "spec_shared_draft_rows": self._spec_shared_rows,
                # Per-request token timeline (PR 10) — the same
                # observations behind gateway_tbt_seconds (lockstep
                # tested); ttft here is the batcher's submit-to-first-
                # token (the gateway's gateway_ttft_seconds keeps its
                # arrival-to-first-byte view; both move once per
                # request).
                "ttft_seconds_sum": self._ttft_sum,
                "ttft_seconds_count": self._ttft_count,
                "tbt_seconds_sum": self._tbt_sum,
                "tbt_seconds_count": self._tbt_count,
                # Roofline attribution (PR 10): per-program-kind sums
                # of the static cost model (modeled HBM bytes, FLOPs,
                # target-pool KV tokens touched) next to the measured
                # program seconds — gateway_program_mbu's inputs, so
                # MBU is derivable offline against any peak bandwidth.
                **{
                    f"mbu_{key}_{kind}": m[key]
                    for kind, m in self._mbu.items()
                    for key in (
                        "hbm_bytes",
                        "flops",
                        "kv_read_tokens",
                        "kv_write_tokens",
                        "seconds",
                        "programs",
                    )
                },
                # Adaptive control (PR 15): the controller's own
                # mirrors of gateway_autotune_value/_decisions_total —
                # absent without a controller (the knobs are static
                # config then, and a missing key is honest about it).
                **(
                    self.controller.stats()
                    if self.controller is not None
                    else {}
                ),
            }

    def close(self) -> None:
        self._stop.set()
        self._work.set()
        self._prefetch_have.set()  # wake the prefetch loop to exit
        self._thread.join(timeout=10)
        if self._prefetch_thread is not None:
            self._prefetch_thread.join(timeout=5)
        with self._lock:
            # Pending rebalance exports never run now — release their
            # waiters rather than leaving them to time out.
            for _, ev, *_rest in self._exports:
                ev.set()
            self._exports.clear()
            for req in self._waiting:
                if not req.future.done():
                    req.future.set_exception(RuntimeError("batcher stopped"))
            for slot in self._slots:
                if slot and not slot.request.future.done():
                    slot.request.future.set_exception(
                        RuntimeError("batcher stopped")
                    )

    # -- host loop ------------------------------------------------------

    def _decoding(self) -> int:
        """Slots currently in the decode phase — THE definition of
        "active" every surface (gauge, stats, step accounting) shares."""
        return sum(
            s is not None and s.phase == "decode" for s in self._slots
        )

    @staticmethod
    def _group_key(slot: _Slot) -> int:
        """A slot's shared-prefix group identity for the adaptive
        controller (PR 15): its FIRST table page — panel mates mapping
        one registered header share it (the GroupTracker bucket key's
        first element), unique prompts each own theirs. Page-id
        recycling can alias groups across time; the acceptance EWMA is
        advisory, so staleness costs one wrong-k window, never
        correctness."""
        return int(slot.pages[0]) if slot.pages else -1

    def _bucket(self, n: int) -> int:
        return _next_bucket(n, self.config.seq_buckets)

    def _chunk_width(self, bucket: int) -> int:
        """Per-request prefill-chunk width: the largest divisor of the
        prompt bucket <= ``config.prefill_chunk`` (power-of-two buckets
        keep it at prefill_chunk). Dividing the bucket makes an
        UNSHARED chunked prefill cover exactly [0, bucket) — the same
        page footprint as the legacy dense path, so admission
        feasibility cannot regress."""
        chunk = min(self.config.prefill_chunk, bucket)
        while bucket % chunk:
            chunk -= 1
        return chunk

    def _pages_needed(self, req: _Request) -> int:
        """Table width in pages for an UNSHARED admission — the
        admit-ever feasibility bound (a request that only fits via
        sharing must not wait forever on an empty registry; chunked or
        dense, the unshared footprint is identical)."""
        bucket = self._bucket(len(req.prompt_ids))
        return self._table_pages(bucket, bucket, req)

    def _table_pages(self, bucket: int, prefill_end: int, req: _Request) -> int:
        # + depth * round_tokens - 1: a row finishing mid-chunk keeps
        # writing K/V until the decode-chunk boundary, and under
        # pipelined dispatch its retirement lags up to depth - 1 MORE
        # already-enqueued programs (all those tokens are discarded on
        # host); its pages must absorb the full overshoot. Under
        # speculative decoding a round writes up to spec_k + 1 K/V
        # positions of which a rejected tail is rewound — the same
        # budget covers it (_round_tokens). depth 1, chunk 1, spec off
        # reduces this to the classic + 0.
        # prefill_end: last position (+1) the chunked prefill may touch
        # — a shared-prefix start off the chunk grid can overhang the
        # bucket by up to chunk-1 positions of masked padding garbage.
        # Depth counts from the CONFIG, not the adaptive effective
        # depth (PR 15): a row admitted while the controller steered
        # depth low must stay budgeted when it steers back up —
        # exactly the live-flip rule _round_tokens already applies to
        # spec_k and decode_rounds.
        total = (
            max(bucket, prefill_end)
            + req.max_new_tokens
            + max(1, self.config.pipeline_depth) * self._round_tokens
            - 1
        )
        pg = self.config.page_size
        return -(-total // pg)

    def _admit(self) -> None:
        c = self.config
        while self._waiting:
            with self._lock:
                if not self._waiting:
                    return
                req = self._waiting[0]
                n_pages = self._pages_needed(req)
                # Largest shard-local pool that can EVER hold the
                # request: page 0 (the reserved NULL page) lives in
                # shard 0's range, so only the dp=1 pool loses it from
                # the max.
                per_shard = c.n_pages // self._dp
                fits_ever = min(
                    c.pages_per_seq,
                    per_shard - (1 if self._dp == 1 else 0),
                )
                if n_pages > fits_ever:
                    self._waiting.popleft()
                    req.future.set_exception(
                        ValueError(
                            f"request needs {n_pages} pages but the "
                            f"configuration caps a sequence at {fits_ever} "
                            f"(pages_per_seq={c.pages_per_seq}, usable "
                            f"per-shard pool="
                            f"{per_shard - (1 if self._dp == 1 else 0)})"
                        )
                    )
                    continue
                admitted = (
                    self._admit_chunked(req)
                    if c.prefill_chunk > 0
                    else self._admit_dense(req)
                )
                if not admitted:
                    return  # no slot/pages; retry after retirements
                self._waiting.popleft()
                _M_WAITING.set(len(self._waiting))
            if c.prefill_chunk == 0:
                # Legacy path: the dense prefill runs OUTSIDE the lock
                # (device work must not block submit()).
                self._dense_prefill_pending()
            elif self._pending_copy is not None:
                # The admission staged a CoW boundary copy: dispatch it
                # outside the lock (flush-then-copy; _flush_pipeline's
                # fetch bookkeeping takes the admission lock).
                self._boundary_copy_pending()

    # -- admission: chunked + prefix-sharing path ------------------------

    def _admit_chunked(self, req: _Request) -> bool:
        """Claim a slot + pages for ``req`` and stage it as a prefilling
        slot (caller holds the lock). Returns False when nothing fits.

        Per candidate slot (= per data shard): match the prompt against
        the shard's prefix registry, size the table from the true chunk
        coverage, evict registry-only pages if the free list falls
        short, allocate, optionally copy the boundary page, and
        register this prompt's own full pages for successors.
        """
        c = self.config
        ids = req.prompt_ids
        L = len(ids)
        pg = c.page_size
        bucket = self._bucket(L)
        chunk = self._chunk_width(bucket)
        if self.controller is not None:
            # Chunk steering (PR 15): the effective width for THIS
            # admission, from the menu {chunk, chunk/2} (chunk_for
            # guarantees the half still divides the bucket — the
            # unshared-footprint invariant — so at most ONE extra
            # compiled (chunk, bucket) trace per bucket can ever
            # exist: the no-recompile-storm bound).
            chunk = min(chunk, max(1, self.controller.chunk_for(bucket, chunk)))

        # One candidate slot per SHARD: every slot of a shard draws on
        # the same pool/registry, so retrying a failed plan on a
        # sibling slot would redo identical match/evict work for the
        # same answer.
        seen_shards: set[int] = set()
        for i in range(c.max_slots):
            if self._slots[i] is not None:
                continue
            shard = self._shard_of_slot[i]
            if shard in seen_shards:
                continue
            seen_shards.add(shard)
            pool = self._pools[shard]
            registry = self._registries[shard]
            # Plan A shares the registered prefix; plan B admits
            # unshared (exactly the legacy footprint) when the shared
            # table would overhang the page budget — a prefix start off
            # the chunk grid pads the final chunk past the bucket, up
            # to chunk-1 positions.
            for use_share in (True, False) if c.share_prefix else (False,):
                match = None
                shared_pages: list[int] = []
                start0 = 0
                boundary = 0
                restore_plan: list = []
                if use_share:
                    # Boundary copies must beat recompute: a whole-page
                    # device copy for a trivial overlap (every prompt
                    # shares BOS) is pure overhead.
                    match = registry.match(
                        ids, min_boundary=max(2, c.page_size // 4)
                    )
                    _M_PREFIX_LOOKUPS.inc()
                    shared_pages = match.pages
                    start0 = match.shared_tokens
                    if match.boundary_page is not None:
                        boundary = match.boundary_common
                    # Fall through registry-miss -> host-hit (PR 4):
                    # extend the matched chain through pages the
                    # offload tier still holds. Each hit is page_size
                    # prompt tokens promoted back by a device_put
                    # instead of recomputed; full-page restores
                    # supersede the partial boundary copy (their
                    # ranges would overlap).
                    if self._offload is not None:
                        k = start0 // pg
                        usable_full = (L - 1) // pg
                        if k < usable_full:
                            # One int conversion for the whole probe
                            # range; per-page keys are O(1) slices of
                            # it, not per-iteration re-tuplings.
                            chain = tuple(
                                int(t) for t in ids[: usable_full * pg]
                            )
                            keys = [
                                self._store_key(chain[: (j + 1) * pg])
                                for j in range(k, usable_full)
                            ]
                            # Route-driven prefetch hits first (PR 17):
                            # planes the prefetch loop already pulled
                            # store->host for this chain are consumed
                            # here without touching the store again.
                            restore_plan = self._prefetch_take(keys)
                            if len(restore_plan) < len(keys):
                                # One batched get_run for the rest —
                                # over the remote transport the whole
                                # restore plan is a single round trip
                                # (scatter-gather reply), not one RTT
                                # per page.
                                gr = getattr(self._offload, "get_run", None)
                                if gr is not None:
                                    restore_plan.extend(
                                        gr(keys[len(restore_plan):])
                                    )
                                else:
                                    for key in keys[len(restore_plan):]:
                                        planes = self._offload.get(key)
                                        if planes is None:
                                            break
                                        restore_plan.append(planes)
                        if restore_plan:
                            # Full-page restores supersede the partial
                            # boundary ON THE MATCH TOO: record_commit
                            # reads match.boundary_common, and the
                            # stats()/Prometheus hit counters must
                            # agree (PR 2 contract).
                            boundary = 0
                            match.boundary_page = None
                            match.boundary_common = 0
                    if not shared_pages and not boundary and not restore_plan:
                        continue  # registry miss: plan B is identical
                start = start0 + len(restore_plan) * pg + boundary
                end = start + -(-(L - start) // chunk) * chunk
                total = self._table_pages(bucket, end, req)
                need_new = total - len(shared_pages)
                # Infeasibility first: evicting cached prefixes to make
                # room for a plan the NEXT check rejects anyway would
                # self-destroy the registry this feature depends on.
                if total > c.pages_per_seq:
                    for p in shared_pages:
                        pool.release(p)
                    continue
                if pool.available < need_new:
                    registry.evict(need_new - pool.available)
                if pool.available < need_new:
                    # Give the refs back; plan B (or another slot's
                    # shard, or a later retirement) may fit.
                    for p in shared_pages:
                        pool.release(p)
                    continue
                if use_share:
                    registry.record_commit(match, copied=bool(boundary))
                    if shared_pages or boundary:
                        # record_commit's definition of a hit: a pure
                        # host-tier restore is counted by the offload
                        # families, not the registry's — the two
                        # surfaces must agree (PR 2 contract).
                        _M_PREFIX_HITS.inc()
                    _M_PREFIX_SHARED.inc(len(shared_pages))
                new_pages = pool.alloc(need_new)
                pages = shared_pages + new_pages
                table = np.full((c.pages_per_seq,), NULL_PAGE, np.int32)
                table[: len(pages)] = pages
                if boundary:
                    # Copy-on-write: the donor's boundary page extends
                    # our prefix mid-page; copy its content into our
                    # first private page and resume prefill after the
                    # common run. The device copy is STAGED here and
                    # dispatched by _admit's post-lock epilogue (after
                    # a pipeline flush — a stable-cache operation);
                    # this slot's first chunk cannot run before it
                    # (same worker thread, _prefill_step comes later).
                    _M_PREFIX_COPIED.inc()
                    self._pending_copy = (match.boundary_page, new_pages[0])
                # Offer our own full prompt pages to successors
                # (pending until our prefill writes past each page) —
                # unless sharing is off: a registry nobody consults
                # must not pin retired requests' pages either.
                reg_nodes = (
                    registry.register(ids, pages) if c.share_prefix else []
                )
                restore_nodes: list = []
                if restore_plan:
                    # Pages the host tier is about to repopulate:
                    # register() just created their nodes (the match
                    # walk stopped exactly where the tree thinned out),
                    # unready until the install lands. They leave
                    # reg_nodes — THIS prefill starts past them and
                    # never writes them — and gate both our own first
                    # chunk and any same-prefix burst-mate, exactly
                    # like an in-flight prefill.
                    restore_nodes = [
                        n for n, end_pos in reg_nodes if end_pos <= start
                    ]
                    reg_nodes = [
                        (n, e) for n, e in reg_nodes if e > start
                    ]
                    assert len(restore_nodes) == len(restore_plan)
                    for node, planes in zip(restore_nodes, restore_plan):
                        self._restores.append((node, planes, req.trace))
                padded = np.full((end,), self.tokenizer.pad_id, np.int32)
                padded[:L] = ids
                deps = restore_nodes + [
                    n
                    for n in (match.nodes if match else [])
                    if not n.ready
                ]
                self._slots[i] = _Slot(
                    request=req,
                    pages=pages,
                    generated=[],
                    prompt_len=L,
                    phase="prefill",
                    table=table,
                    next_pos=start,
                    chunk=chunk,
                    padded_ids=padded,
                    s_bucket=bucket,
                    deps=deps,
                    reg_nodes=reg_nodes,
                    pages_shared_n=len(shared_pages),
                    pages_restored_n=len(restore_plan),
                )
                _flight.flight_recorder().record(
                    "admit",
                    time.perf_counter(),
                    trace_id=_tracing.trace_id_of(req.trace),
                    id=req.rid,
                    slot=i,
                    prompt_tokens=L,
                    pages_shared=len(shared_pages),
                    pages_restored=len(restore_plan),
                    boundary_copy=bool(boundary),
                )
                return True
        return False

    def _boundary_copy_pending(self) -> None:
        """Dispatch the CoW boundary copy staged by :meth:`_admit_chunked`
        (outside the admission lock). Flushes the decode pipeline first:
        the copy is a stable-cache operation, and draining also settles
        retirement bookkeeping before the copy + first-chunk sequence
        occupies the device queue."""
        src, dst = self._pending_copy
        self._pending_copy = None
        self._flush_pipeline()
        _flight.flight_recorder().record(
            "cow_copy", time.perf_counter(), src=int(src), dst=int(dst)
        )
        self.cache = self._jit_copy_page(
            self.cache, jnp.int32(src), jnp.int32(dst)
        )
        if self.draft_cache is not None:
            # The draft pool shares the page geometry: its boundary
            # page carries the draft's K/V for the same tokens and
            # must CoW with the target's.
            self.draft_cache = self._jit_copy_page(
                self.draft_cache, jnp.int32(src), jnp.int32(dst)
            )

    def _flush_pipeline(self) -> None:
        """Drain every in-flight decode program (fetch + bookkeeping).

        The flush points are the operations that want a stable cache
        and settled host bookkeeping underneath them: host-tier page
        restores (install_page), CoW boundary copies, and legacy dense
        prefill. Each drain of a non-empty pipeline counts once in
        ``gateway_pipeline_flushes_total`` — the price the pipeline
        pays to keep those paths simple. (Registry demotions read
        pages with ``device_get``, which already blocks on the
        dispatched stream and needs no flush.) Must be called WITHOUT
        the admission lock: fetch bookkeeping takes it.
        """
        if not self._inflight:
            return
        _M_PIPELINE_FLUSHES.inc()
        _flight.flight_recorder().record(
            "flush", time.perf_counter(), inflight=len(self._inflight)
        )
        with self._lock:
            self._pipeline_flushes += 1
        while self._inflight:
            self._fetch_one()

    def _store_key(self, chain: tuple) -> tuple:
        """Host-tier key for a token chain: the batcher's store scope
        (config/weights identity — see __init__) prepended, so a
        fleet-shared store never cross-restores between heterogeneous
        replicas. Private stores pay the same prefix harmlessly."""
        return (self._store_scope, chain)

    def _spill_nodes(self, nodes) -> tuple[int, int]:
        """Spill the given registry nodes' pages to the host tier:
        ONE batched device_get covers every page the store doesn't
        already hold — a spill burst costs one host transfer, not N
        sequential round trips stalling the decode loop. Chains that
        round-tripped before skip the fetch entirely (recency refresh
        only; a refresh that LOSES the race with a concurrent LRU drop
        falls through to the fetch — the fleet-shared store's touch()
        says which happened). Returns (pages fetched+put, refreshed).

        The Prometheus families move by the STORE's own deltas, so a
        put() the budget refuses (oversize page) never counts as a
        demotion on either surface — and on a SHARED store the deltas
        are this call's own (computed around our puts; concurrent
        replicas' puts land in their own deltas).

        Worker thread only (both callers — the evict hook and the
        export step — run there): the device_get must not race a
        dispatch-time buffer donation.
        """
        store = self._offload
        keys = [
            self._store_key(PrefixRegistry.chain_tokens(node))
            for node in nodes
        ]
        refreshed = demoted = dropped = 0
        # Batched recency probe (PR 17): over the remote transport
        # touch_many is ONE round trip for the whole spill plan instead
        # of a serial RTT per chain. In-process stores answer the same
        # surface; a store without it falls back to the per-key loop.
        tm = getattr(store, "touch_many", None)
        touched = (
            tm(keys) if tm is not None else [store.touch(k) for k in keys]
        )
        fetch: list[tuple[tuple, int]] = []
        for key, node, hit in zip(keys, nodes, touched):
            if hit:
                refreshed += 1
                demoted += 1
            else:
                fetch.append((key, node.page))
        if fetch:
            pages = jnp.asarray([p for _, p in fetch], jnp.int32)
            planes_dev = [self.cache.k[:, pages], self.cache.v[:, pages]]
            if self.draft_cache is not None:
                # Demote the draft pool's planes for the same pages in
                # the SAME batched device_get: a restored prefix then
                # comes back with its draft context (PR 9) — the store
                # budget accounts all four planes' bytes.
                planes_dev += [
                    self.draft_cache.k[:, pages],
                    self.draft_cache.v[:, pages],
                ]
            got = jax.device_get(tuple(planes_dev))  # [L, n, page, Hkv, Dh]
            # Contiguous copies: a view into the batch buffer would
            # pin the whole [L, n, ...] fetch alive in the store.
            items = [
                (
                    key,
                    tuple(np.ascontiguousarray(pl[:, i]) for pl in got),
                )
                for i, (key, _) in enumerate(fetch)
            ]
            # One put_many per spill burst: remotely that's one frame
            # carrying every page's planes scatter-gathered (the v2
            # batched put), locally it loops put_counted under the hood.
            pm = getattr(store, "put_many", None)
            deltas = (
                pm(items)
                if pm is not None
                else [store.put_counted(k, p) for k, p in items]
            )
            for _, d, dr in deltas:
                demoted += d
                dropped += dr
        if demoted:
            _M_OFF_DEMOTED.inc(demoted)
        if dropped:
            _M_OFF_DROPPED.inc(dropped)
        _M_OFF_HOST_BYTES.set(store.bytes_used)
        return len(fetch), refreshed

    def _demote_nodes(self, nodes) -> None:
        """PrefixRegistry.on_evict hook: spill an evict() walk's ready
        victims to the host tier instead of losing them (worker thread,
        inside the admission lock — evictions happen at admission and
        in the fleet's preempt step, both worker-side)."""
        fetched, refreshed = self._spill_nodes(nodes)
        _flight.flight_recorder().record(
            "demote",
            time.perf_counter(),
            pages=fetched,
            refreshed=refreshed,
        )

    def _restore_step(self) -> bool:
        """Promote queued host-tier pages back into the device pool.

        The restore counterpart of :meth:`_prefill_step`: a bounded
        BATCH of ``device_put`` + installs runs between decode steps,
        so running slots pay a bounded stall — and each readiness flip
        releases every admission gated on that page (the admitting
        slot's first chunk, plus any same-prefix burst-mate that
        deduped against the in-flight restore). The batch size comes
        from :meth:`AdaptiveController.restore_batch` — the pipeline
        flush below is paid ONCE per call, so a host-bound loop drains
        more pages per flush while a saturated decode lane stays at
        the historical one page per iteration (the controller-less
        fallback). Returns True when at least one page was restored.
        """
        if not self._restores:
            return False
        batch = (
            self.controller.restore_batch()
            if self.controller is not None
            else 1
        )
        # Stable-cache operation: drain in-flight decode programs
        # before installing host content into pool pages (once for the
        # whole batch — the amortization restore_batch sizes).
        self._flush_pipeline()
        group: list = []
        while self._restores and len(group) < batch:
            group.append(self._restores.popleft())
        # Batched install (PR 17, the page_planes docstring's demote
        # symmetry): ONE stacked device_put + scatter covers the whole
        # group instead of a dispatch per page — restore bursts (a
        # handoff's chain, a promote-back after preemption) cost one
        # transfer the way a demote burst costs one device_get.
        t0 = time.perf_counter()
        pages = jnp.asarray([int(n.page) for n, _, _ in group], jnp.int32)
        self.cache = self._jit_install_pages(
            self.cache,
            pages,
            jnp.asarray(np.stack([p[0] for _, p, _ in group], axis=1)),
            jnp.asarray(np.stack([p[1] for _, p, _ in group], axis=1)),
        )
        draft_idx = [
            i for i, (_, p, _) in enumerate(group) if len(p) >= 4
        ]
        if self.draft_cache is not None and draft_idx:
            # Draft planes demoted alongside the target's (PR 9):
            # the restored prefix keeps its draft context, so
            # acceptance doesn't silently collapse after an
            # eviction round trip.
            self.draft_cache = self._jit_install_pages(
                self.draft_cache,
                pages[jnp.asarray(draft_idx, jnp.int32)],
                jnp.asarray(
                    np.stack([group[i][1][2] for i in draft_idx], axis=1)
                ),
                jnp.asarray(
                    np.stack([group[i][1][3] for i in draft_idx], axis=1)
                ),
            )
        # The install must COMPLETE before readers are released (same
        # contract as a prefill chunk's block). The histogram stays a
        # per-PAGE promotion latency: the batch's wall time amortizes
        # evenly over its pages (dur/n observed n times), keeping the
        # family's count == restored-pages lockstep with
        # offload_restored_total.
        jax.block_until_ready(self.cache.length)
        dur = time.perf_counter() - t0
        per = dur / len(group)
        for i, (node, _, trace) in enumerate(group):
            ti = t0 + i * per
            _M_RESTORE_SECONDS.observe(per)
            if trace is not None:
                trace.add_span("kv_restore", ti, per, page=int(node.page))
            _flight.flight_recorder().record(
                "restore",
                ti,
                per,
                trace_id=_tracing.trace_id_of(trace),
                page=int(node.page),
            )
            node.ready = True
            _M_OFF_RESTORED.inc()
            if self.controller is not None:
                self.controller.note_restore(self.host_page_bytes)
        with self._lock:
            self._offload_restored += len(group)
        return True

    def _count_program(
        self,
        kind: str,
        rows: int | None = None,
        rounds: int | None = None,
    ):
        """One device program dispatched by the scheduler loop: feed
        the Prometheus families, the stats() mirrors, AND the flight
        recorder from the same site (lockstep — the Chrome export's
        device track reconstructs exactly the programs this counted).
        ``rows``: ragged-row occupancy for fused/decode programs
        (decode rows + chunk lanes). ``rounds`` (PR 12): decode rounds
        this program folds — decode/fused pass their window (R under
        decode_rounds, steps_per_sync on the legacy chunk), spec
        passes 1 (the verify round IS the multi-token step), prefill/
        draft pass None (they advance no decode row) — feeding
        gateway_device_rounds_total + the per-program histogram and
        riding the PROGRAM flight event's meta so the Chrome export's
        device track stays count-exact at R > 1 (one slice still means
        one program, its ``rounds`` arg says how much decoding it
        held). Returns the flight event (None when recording is off)
        so pipelined callers can fill in the true device window in
        place once the fetch lands."""
        _M_DEVICE_PROGRAMS.labels(kind=kind).inc()
        with self._lock:
            self._programs[kind] += 1
            if rows is not None:
                self._ragged_rows_sum += rows
                self._ragged_rows_count += 1
            if rounds is not None:
                self._device_rounds += rounds
                self._decode_rounds_sum += rounds
                self._decode_rounds_count += 1
        if rows is not None:
            _M_RAGGED_ROWS.observe(rows)
        if rounds is not None:
            _M_DEVICE_ROUNDS.inc(rounds)
            _M_DECODE_ROUNDS.observe(rounds)
        meta = {"kind": kind}
        if rows is not None:
            meta["rows"] = rows
        if rounds is not None:
            meta["rounds"] = rounds
        if kind == "draft":
            # Draft mirror programs are dispatched async and never
            # individually fetched (their completion is implied by
            # stream order behind the carrying program) — their event
            # is a dispatch-stamp annotation, not a measured window.
            meta["untimed"] = 1
        return _flight.flight_recorder().record(
            "program", time.perf_counter(), meta=meta
        )

    def _program_cost(
        self,
        kind: str,
        rows_now: list,
        k: int,
        chunk_ext: tuple[int, int] | None = None,
        streams: int = 0,
    ) -> dict:
        """Static HBM/FLOPs model for ONE dispatched program (PR 10).

        ``kv_read/write_tokens`` count the TARGET pool only and mirror
        what the program actually touches: a decode row at committed
        length L reads L + j positions at step j (k steps per
        program); a speculative verify row reads its pages ONCE for
        all k+1 queries (the ragged kernel folds each page one time —
        the reason a spec program's KV read equals a plain decode
        program's over the same rows) and writes k+1 positions of
        which a rejected tail is rewound (written traffic either way);
        a chunk lane (``chunk_ext = (read_end, width)``) reads the
        pages covering [0, read_end) and writes its width. Group-
        shared prefix reads are deducted exactly as
        :meth:`_dispatch_tail` counts them saved — the two accountings
        cannot drift apart without a test noticing. The draft side of
        a spec program adds k+1 reads of the draft tree plus the
        streams' draft KV to hbm_bytes/flops only (the kv_*_tokens
        fields stay target-pool so the spec-on/off write-parity
        invariant is assertable).
        """
        kv_read = kv_write = tokens = 0
        lengths = []
        for _, s in rows_now:
            L = s.prompt_len + len(s.generated)
            lengths.append(L)
            if kind == "spec":
                kv_read += L + k
                kv_write += k + 1
                tokens += k + 1
            else:
                kv_read += k * L + k * (k - 1) // 2
                kv_write += k
                tokens += k
        if self._group_decode and rows_now:
            shared_steps = 1 if kind == "spec" else k
            kv_read -= min(
                kv_read, self._groups.saved_tokens_per_step * shared_steps
            )
        if chunk_ext is not None:
            read_end, width = chunk_ext
            kv_read += read_end
            kv_write += width
            tokens += width
        cost = program_hbm_cost(
            self.cfg,
            weight_bytes=self._weight_bytes,
            weight_params=self._weight_params,
            kv_token_bytes=self._kv_token_bytes,
            kv_read_tokens=kv_read,
            kv_write_tokens=kv_write,
            tokens=tokens,
        )
        if kind == "spec":
            mean_len = sum(lengths) // max(1, len(lengths))
            d_tokens = (k + 1) * max(1, streams)
            d = program_hbm_cost(
                self._draft_cfg,
                # The draft scan streams the draft tree once per step.
                weight_bytes=(k + 1) * self._draft_weight_bytes,
                weight_params=self._draft_weight_params,
                kv_token_bytes=self._draft_kv_token_bytes,
                kv_read_tokens=d_tokens * mean_len,
                kv_write_tokens=d_tokens,
                tokens=d_tokens,
            )
            cost["hbm_bytes"] += d["hbm_bytes"]
            cost["flops"] += d["flops"]
        return cost

    def _mbu_account(self, kind: str, cost: dict | None, dur: float) -> None:
        """Fold one fetched program's modeled cost + measured duration
        into the per-kind accumulators and — with a configured peak
        bandwidth — the gateway_program_mbu{kind} gauge. One site,
        two surfaces (stats mbu_* mirrors; lockstep tested)."""
        if self.controller is not None:
            # Roofline-position feed (PR 15): modeled weight fraction
            # + decode-MBU EWMAs come from the same (cost, dur) pairs
            # the gauge and stats sums fold.
            self.controller.note_program(kind, cost, dur)
        if cost is None:
            return
        with self._lock:
            m = self._mbu[kind]
            m["hbm_bytes"] += cost["hbm_bytes"]
            m["flops"] += cost["flops"]
            m["kv_read_tokens"] += cost["kv_read_tokens"]
            m["kv_write_tokens"] += cost["kv_write_tokens"]
            m["seconds"] += dur
            m["programs"] += 1
        peak = self.config.hbm_gbps * 1e9
        if peak > 0 and dur > 0:
            _M_PROGRAM_MBU.labels(kind=kind).set(
                cost["hbm_bytes"] / dur / peak
            )

    def _pick_prefill_slot(self) -> int | None:
        """Next ready prefilling slot — deps satisfied and chunks still
        to run (a slot whose FINAL chunk is already in flight under the
        fused path waits for its fetch-side activation). Round-robin
        for fairness; advances the pointer, so callers must run the
        returned slot's next chunk. None when nothing is ready."""
        n = self.config.max_slots
        for off in range(n):
            i = (self._prefill_rr + off) % n
            s = self._slots[i]
            if (
                s is not None
                and s.phase == "prefill"
                and s.next_pos < s.prompt_len
                and all(node.ready for node in s.deps)
            ):
                self._prefill_rr = (i + 1) % n
                return i
        return None

    def _prefill_step(self, idx: int) -> bool:
        """Run ONE prefill chunk for slot ``idx`` as a STANDALONE
        device program (the pre-fusion path, and still the path when no
        decode batch exists to ride or ``ragged_attention`` is off).

        The unit of decode stall under chunked prefill: between any two
        decode steps at most one of these runs, so admission latency
        costs running requests one bounded chunk, never a whole prompt.
        """
        slot = self._slots[idx]
        if self._inflight:
            # Let in-flight decode work clear the device queue so the
            # stall histogram times ONLY this chunk. A device-order
            # wait, NOT a flush: the pending fetches stay pipelined
            # and cost ~nothing afterwards.
            jax.block_until_ready(self.cache.length)
        t0 = time.perf_counter()
        ev = self._count_program("prefill")
        chunk_ids = slot.padded_ids[slot.next_pos : slot.next_pos + slot.chunk]
        hidden, self.cache = self._chunk_fn(slot.chunk, slot.s_bucket)(
            self.params,
            jnp.asarray(chunk_ids[None]),
            jnp.asarray(slot.table),
            jnp.int32(slot.next_pos),
            self.cache,
        )
        if self.draft_cache is not None:
            self._draft_prefill_chunk(slot, chunk_ids, slot.next_pos)
        written_end = slot.next_pos + slot.chunk
        done = written_end >= slot.prompt_len
        if done:
            # Sample the first token from the last REAL position's
            # hidden state (a [D] gather + D x V unembed — never a
            # [C, V] logits buffer per chunk).
            h = hidden[0, slot.prompt_len - 1 - slot.next_pos]
            logits = self._jit_unembed(self.params, h)
            first = self._sample_first(slot.request, logits)
        # The device work above must COMPLETE before (a) the stall
        # histogram records it and (b) successors read the pages this
        # chunk wrote.
        jax.block_until_ready(self.cache.length)
        dur = time.perf_counter() - t0
        _M_PREFILL_STALL.observe(dur)
        if ev is not None:
            # Standalone chunk programs are host-blocking: the device
            # window IS [t0, t0 + dur] — fill the flight event now.
            # Meta is REPLACED, not mutated: a concurrent /debug/flight
            # export may be iterating the old dict.
            ev.t0 = t0
            ev.dur = dur
            ev.meta = {
                **ev.meta, "slot": idx, "pos": slot.next_pos,
                "width": slot.chunk,
            }
        self._mbu_account(
            "prefill",
            self._program_cost(
                "prefill", [], 0, chunk_ext=(written_end, slot.chunk)
            ),
            dur,
        )
        trace = slot.request.trace
        if trace is not None:
            trace.add_span(
                "prefill_chunk", t0, dur, pos=slot.next_pos, chunk=slot.chunk
            )
        written_real = min(written_end, slot.prompt_len)
        for node, end_pos in slot.reg_nodes:
            if not node.ready and end_pos <= written_real:
                node.ready = True
        slot.next_pos = written_end
        with self._lock:
            self._prefill_chunks += 1
        if not done:
            return True
        # Final chunk landed: make the row visible to the decode program
        # (table + true length in one pass) and flip to decoding.
        self.cache = install_seq(
            self.cache,
            jnp.int32(idx),
            jnp.asarray(slot.table),
            jnp.int32(slot.prompt_len),
        )
        self._install_draft_seq(idx, slot)
        self._activate(idx, slot, first)
        return True

    def _install_draft_seq(self, idx: int, slot: _Slot) -> None:
        """Mirror a slot activation into the draft pool: same table,
        same length — the draft's committed-minus-one invariant starts
        in sync with the target's."""
        if self.draft_cache is None:
            return
        self.draft_cache = install_seq(
            self.draft_cache,
            jnp.int32(idx),
            jnp.asarray(slot.table),
            jnp.int32(slot.prompt_len),
        )

    def _sample_first(self, req: _Request, logits) -> int:
        """First generated token, sampled from prefill logits — the
        same (seed, 0) PRNG draw both admission paths share."""
        key = jax.random.fold_in(jax.random.PRNGKey(req.seed), 0)
        tok, _ = sample_token_per_request(
            logits[None],
            key[None],
            jnp.asarray([req.temperature], jnp.float32),
            jnp.asarray([req.top_k], jnp.int32),
            jnp.asarray([req.top_p], jnp.float32),
            filters_active=(req.top_k != 0 or req.top_p != 1.0),
        )
        return int(tok[0])

    def _activate(self, idx: int, slot: _Slot, first: int) -> None:
        """Flip a slot to decoding with its first sampled token."""
        req = slot.request
        slot.generated.append(first)
        slot.phase = "decode"
        slot.deps = []
        # First generated token: the request's TTFT anchor (batcher
        # side — submit to first token; the gateway's
        # gateway_ttft_seconds keeps its arrival-to-first-byte view)
        # and the origin of the inter-token-gap timeline.
        now = time.perf_counter()
        slot.t_first = now
        slot.t_last_tok = now
        if self._group_decode or self.draft_cache is not None:
            # The row's prompt-prefix page run (full pages only — the
            # boundary page takes decode writes and must stay suffix).
            # Same page ids across rows == same tokens (sharing happens
            # only through the registry), so the tracker groups rows by
            # common run prefix: the panel's donor AND its mappers.
            # With a draft configured the tracker ALSO runs on
            # non-Pallas backends: its first-page buckets are the
            # shared-draft-stream candidate sets (the grouped KERNEL
            # still engages only under _group_decode — arrays() is
            # consulted only there).
            self._groups.add(
                idx, slot.pages[: slot.prompt_len // self.config.page_size]
            )
            _flight.flight_recorder().record(
                "group",
                now,
                trace_id=_tracing.trace_id_of(req.trace),
                slot=idx,
                largest=self._groups.largest_group,
            )
        with self._lock:
            _M_ACTIVE.set(self._decoding())
            self._ttft_sum += now - req.t_submit
            self._ttft_count += 1
        self._last_tokens[idx] = first
        # The next dispatch must feed THIS row from the host mirror:
        # its first token came from prefill logits, not from the
        # in-flight program's output row (which is stale or garbage
        # for a freshly (re)activated slot).
        self._tok_dirty[idx] = True
        self._seeds[idx] = req.seed
        self._counts[idx] = 1  # token 0 sampled from prefill
        self._topks[idx] = req.top_k
        self._topps[idx] = req.top_p
        if (
            first == self.tokenizer.eos_id
            or req.max_new_tokens <= 1
            or self._hit_stop(slot)
        ):
            self._retire(idx)

    # -- admission: legacy blocking dense-prefill path -------------------

    def _admit_dense(self, req: _Request) -> bool:
        """Claim a slot + pages (caller holds the lock); the dense
        prefill itself runs from :meth:`_dense_prefill_pending` outside
        the lock. Returns False when nothing fits."""
        c = self.config
        n_pages = self._pages_needed(req)
        free_slot = next(
            (
                i
                for i, s in enumerate(self._slots)
                if s is None
                and self._pools[self._shard_of_slot[i]].available >= n_pages
            ),
            None,
        )
        if free_slot is None:
            # Registry pages are reclaimable capacity even on this path
            # (a prior chunked-config batcher cannot have populated it —
            # but evict defensively so the two paths agree on capacity).
            for i, s in enumerate(self._slots):
                if s is None:
                    shard = self._shard_of_slot[i]
                    self._registries[shard].evict(
                        n_pages - self._pools[shard].available
                    )
                    if self._pools[shard].available >= n_pages:
                        free_slot = i
                        break
            if free_slot is None:
                return False
        pool = self._pools[self._shard_of_slot[free_slot]]
        pages = pool.alloc(n_pages)
        self._slots[free_slot] = _Slot(
            request=req,
            pages=pages,
            generated=[],
            prompt_len=len(req.prompt_ids),
            phase="prefill",  # not decodable until the prefill lands
        )
        self._dense_pending = free_slot
        _flight.flight_recorder().record(
            "admit",
            time.perf_counter(),
            trace_id=_tracing.trace_id_of(req.trace),
            id=req.rid,
            slot=free_slot,
            prompt_tokens=len(req.prompt_ids),
            dense=1,
        )
        return True

    def _dense_prefill_pending(self) -> None:
        """Blocking dense prefill for the slot staged by _admit_dense.
        Flushes the decode pipeline first (stable-cache operation: the
        whole-prompt prefill rewrites a slot's table and pages)."""
        self._flush_pipeline()
        c = self.config
        idx = self._dense_pending
        slot = self._slots[idx]
        req = slot.request
        t0 = time.perf_counter()
        ev = self._count_program("prefill")
        s_bucket = self._bucket(len(req.prompt_ids))
        slot.s_bucket = s_bucket  # program-family key (draft catch-up)
        padded = np.full((1, s_bucket), self.tokenizer.pad_id, np.int32)
        padded[0, : len(req.prompt_ids)] = req.prompt_ids
        table = np.full((c.pages_per_seq,), NULL_PAGE, np.int32)
        table[: len(slot.pages)] = slot.pages
        self.cache = assign_pages(
            self.cache, jnp.int32(idx), jnp.asarray(table)
        )
        logits, self.cache = self._prefill_fn(s_bucket)(
            self.params,
            self.cache,
            jnp.asarray(padded),
            jnp.int32(len(req.prompt_ids)),
            jnp.int32(idx),
        )
        if self.draft_cache is not None:
            # Mirror the legacy dense admission into the draft pool:
            # same table, the draft's own dense prefill + scatter.
            self._count_program("draft")
            self.draft_cache = assign_pages(
                self.draft_cache, jnp.int32(idx), jnp.asarray(table)
            )
            self.draft_cache = self._prefill_fn_d(s_bucket)(
                self._draft_params,
                self.draft_cache,
                jnp.asarray(padded),
                jnp.int32(len(req.prompt_ids)),
                jnp.int32(idx),
            )
        first = self._sample_first(req, logits)
        jax.block_until_ready(self.cache.length)
        dur = time.perf_counter() - t0
        # The whole-prompt stall this path pays per admission — the
        # number the chunked scheduler bounds to one chunk.
        _M_PREFILL_STALL.observe(dur)
        if ev is not None:
            ev.t0 = t0
            ev.dur = dur
            ev.meta = {
                **ev.meta, "slot": idx, "pos": 0,
                "width": s_bucket, "dense": 1,
            }
        # Dense prefill computes attention in-program (no paged KV
        # reads); its pool traffic is the prompt's K/V scatter.
        self._mbu_account(
            "prefill",
            program_hbm_cost(
                self.cfg,
                weight_bytes=self._weight_bytes,
                weight_params=self._weight_params,
                kv_token_bytes=self._kv_token_bytes,
                kv_read_tokens=0,
                kv_write_tokens=len(req.prompt_ids),
                tokens=s_bucket,
            ),
            dur,
        )
        self._activate(idx, slot, first)

    def _decoded_text(self, slot: _Slot) -> str:
        ids = [t for t in slot.generated if t != self.tokenizer.eos_id]
        return self.tokenizer.decode(ids)

    def _hit_stop(self, slot: _Slot) -> bool:
        """True when any stop sequence appears in the decoded text so
        far. Host-checked after EVERY sampled token — multi-token stops
        terminate immediately, with no overshoot to EOS/length (the
        engine's batch path can only device-stop single-token stops).

        Window sizing, visible-token filtering, and the full-decode
        confirm on candidate hits all live in
        :meth:`utils.stops.VisibleIdFilter.confirmed_stop_hit` — the
        one copy the engine's ``_chunked_stop_decode`` shares, so the
        two retiring surfaces cannot drift.
        """
        return self._vis_filter.confirmed_stop_hit(
            slot.generated,
            slot.request.stop,
            slot.request.stop_window,
            lambda: self._decoded_text(slot),
        )

    def _request_summary(self, slot: _Slot) -> dict:
        """The per-request serving timeline (PR 10): TTFT, inter-token
        gap percentiles, speculation tallies, and header-page
        provenance. Retained in the process RequestLog (served at
        ``GET /debug/requests``) and attached to the ServeResult so the
        gateway can surface it as response meta."""
        req = slot.request
        end = time.perf_counter()
        gaps = slot.gaps
        return {
            "id": req.rid,
            "trace_id": _tracing.trace_id_of(req.trace),
            "prompt_tokens": slot.prompt_len,
            "new_tokens": len(slot.generated),
            "ttft_s": (
                slot.t_first - req.t_submit
                if slot.t_first is not None
                else None
            ),
            "duration_s": end - req.t_submit,
            "tbt_p50_s": _flight.percentile(gaps, 50),
            "tbt_p99_s": _flight.percentile(gaps, 99),
            "tbt_max_s": max(gaps) if gaps else 0.0,
            "tbt_count": len(gaps),
            "spec_rounds": slot.spec_rounds,
            "spec_accepted_tokens": slot.spec_accepted_toks,
            "spec_accepted_per_round": (
                slot.spec_accepted_toks / slot.spec_rounds
                if slot.spec_rounds
                else 0.0
            ),
            "header_pages_shared": slot.pages_shared_n,
            "header_pages_restored": slot.pages_restored_n,
            "finished_at": time.time(),
        }

    def _retire(self, idx: int) -> None:
        slot = self._slots[idx]
        assert slot is not None
        # Groups shrink incrementally as members retire; a group left
        # with one member stops emitting (its row falls back to the
        # plain per-row walk — nothing left to dedup).
        self._groups.remove(idx)
        self._stream_src_prev.pop(idx, None)
        self.cache = release_seq(self.cache, jnp.int32(idx))
        if self.draft_cache is not None:
            self.draft_cache = release_seq(self.draft_cache, jnp.int32(idx))
        pool = self._pools[self._shard_of_slot[idx]]
        with self._lock:
            # Refcounted release: private pages return to the free
            # list; prefix-shared pages stay resident for their other
            # readers (and the registry's own hold keeps a retired
            # donor's prefix warm for future admissions).
            for p in slot.pages:
                pool.release(p)
            self._slots[idx] = None
            self._completed += 1
            self._generated_tokens += len(slot.generated)
            _M_ACTIVE.set(self._decoding())
        _M_COMPLETED.inc()
        _M_TOKENS.inc(len(slot.generated))
        text = self._decoded_text(slot)
        # Engine stop contract: trim at the earliest occurrence of any
        # stop, removing the stop itself. num_tokens keeps the honest
        # decoded count (here at most the stop's own tokens past the cut).
        cut = earliest_stop_cut(text, slot.request.stop)
        if cut >= 0:
            text = text[:cut]
        summary = self._request_summary(slot)
        _flight.request_log().add(summary)
        # The Chrome export's per-request track: one slice spanning
        # submit to retirement, joined to /debug/traces by trace id.
        _flight.flight_recorder().record(
            "request",
            slot.request.t_submit,
            summary["duration_s"],
            trace_id=summary.get("trace_id"),
            id=summary["id"],
            tokens=len(slot.generated),
        )
        if not slot.request.future.done():
            slot.request.future.set_result(
                ServeResult(
                    text=text,
                    num_tokens=len(slot.generated),
                    timing=summary,
                )
            )

    def _stop_plan(
        self, rows_now: list, R: int
    ) -> tuple[np.ndarray, np.ndarray, int]:
        """Per-row device stop data for ONE multi-round dispatch
        (PR 12): emit budgets (each row's remaining max-new-tokens in
        the HOST mirror — exact at depth 1, an over-allowance under
        retirement lag, where the fetch's host trim discards the
        overshoot exactly as it always has), the -1-padded
        [max_slots, _SCREEN_W] stop-candidate screen, and the window's
        effective round count: R, or 1 when any decoding row's stop
        sequences admit no bounded screen — those stops need the
        host's byte-level look at every token, so the window collapses
        to the pre-PR-12 cadence until the row retires (stop sequences
        BOUND R; they never break text parity either way)."""
        c = self.config
        budgets = np.full((c.max_slots,), R, np.int32)
        screen = np.full((c.max_slots, _SCREEN_W), -1, np.int32)
        r_eff = R
        for i, s in rows_now:
            budgets[i] = max(
                1, s.request.max_new_tokens - len(s.generated)
            )
            scr = s.request.stop_screen
            if scr is None:
                r_eff = 1
            elif scr:
                screen[i, : len(scr)] = scr
        return budgets, screen, r_eff

    def _counts_device_arg(self, dirty_np, rows):
        """Device-resident PRNG-count input for a data-dependent
        dispatch (spec or multi-round): the previous program's
        ``counts_out`` with (re)activated rows patched from the host
        mirror exactly like their input token, or the mirror itself
        over an empty window. ONE copy for both branches — this is
        race-sensitive bookkeeping (the snapshot rule of ``rows()``),
        and the two callers drifting is how the PR-8 class of bug
        comes back."""
        if self._inflight:
            counts_dev = self._inflight[-1].counts_out
            if dirty_np.any():
                counts_dev = jnp.where(
                    jnp.asarray(dirty_np),
                    jnp.asarray(np.array(self._counts)),
                    counts_dev,
                )
            return counts_dev
        return rows(self._counts)

    def _dispatch(
        self,
        chunk_idx: int | None = None,
        spec: bool = False,
        rounds: int = 1,
        rounds_choice: bool = False,
    ) -> None:
        """Enqueue ONE decode program for the current decode batch.

        In pipelined mode (``pipeline_depth > 1``) this runs BEFORE the
        previous program's tokens reach the host: the input token row is
        the device-resident final-token output of the previous dispatch
        (no host->device round trip on the input side; the cache already
        flows through ``donate_argnums``), so the host's fetch and
        bookkeeping for program *n* overlap program *n+1*'s device
        execution. Rows (re)activated since the previous dispatch are
        patched in from the host mirror (``_tok_dirty``).

        ``chunk_idx`` (PR 8): a ready prefilling slot whose next chunk
        rides THIS program (the fused scheduler step) instead of
        running standalone. The chunk's device work is ordered on the
        stream at dispatch — its registry nodes flip ready HERE, since
        every consumer is a later program on the same stream or a
        flush-first host operation — while its host bookkeeping
        (activation, first-token sampling off the returned logits)
        happens at the fetch, inside the pipeline's overlap window.

        ``spec`` (PR 9): dispatch the speculative draft/verify program
        instead — one device program whose per-row token yield is
        data-dependent (accepted drafts + 1). It rides the SAME
        pipeline: the emit buffer is the fetch target, the last
        emitted token the next dispatch's input, and the PRNG counts
        thread device-resident program-to-program (the host mirror
        syncs at fetch). Mutually exclusive with ``chunk_idx`` —
        chunks run standalone while speculation is engaged.

        ``rounds`` (PR 12): the multi-round engage state from _run's
        once-per-iteration read (1 = legacy single-round; _run passes
        1 whenever ``spec`` is set). > 1 dispatches the R-round masked
        program — :meth:`_rounds_sample`, or the fused step's
        multi-round tail when a chunk rides — with the same
        device-resident count threading as a spec round; the
        per-dispatch effective window may still collapse to 1
        (:meth:`_stop_plan`) without leaving the rounds counts-mode.

        ``rounds_choice`` (PR 15): this dispatch's ``rounds`` was the
        adaptive controller's FREE regime choice (not a near-stop
        force) — such windows are evidence for the two-arm rate
        arbitration. An adaptive arm-1 window is a PLAIN legacy
        dispatch (``rounds == 1``): the masked 1-round program would
        pay the masking machinery + an extra emit-count host fetch
        the plain program doesn't, and the whole point of the arm is
        to measure what single-round dispatch really costs — the
        mode-flush rules above already drain the pipeline on the
        counts-mode change.
        """
        c = self.config
        k = self._sync_chunk
        temps = np.zeros((c.max_slots,), np.float32)
        rows_now: list[tuple[int, _Slot]] = []
        for i, slot in enumerate(self._slots):
            if slot is not None and slot.phase == "decode":
                temps[i] = slot.request.temperature
                rows_now.append((i, slot))
        filters_active = any(
            s.request.top_k != 0 or s.request.top_p != 1.0
            for _, s in rows_now
        )

        def rows(x):
            # SNAPSHOT (np.array copies) before device_put: jax's CPU
            # runtime zero-copies suitably-aligned numpy buffers, so
            # handing it the live mutable array lets the post-dispatch
            # host mutations (the += k counter advance below, fetch-time
            # _last_tokens updates) race the async program's read —
            # observed as a dispatched program folding count+k into the
            # PRNG and re-sampling an already-emitted index. Alignment
            # made the old code's luck allocation-dependent.
            arr = jnp.asarray(np.array(x))
            if self._row_sharding is not None:
                arr = jax.device_put(arr, self._row_sharding)
            return arr

        groups = self._groups.arrays() if self._group_decode else None
        t0 = time.perf_counter()
        # Un-overlapped host time: the gap since the pipeline drained
        # (retirement, admission, prefill chunks, group rebuilds that
        # no in-flight program hid). A dispatch issued with a program
        # still in flight spent its host time in that program's shadow
        # and observes 0, keeping depth-1 and depth-2 distributions
        # count-comparable; idle waits reset _last_step_end and never
        # count.
        overhead = None
        if self._last_step_end is not None:
            overhead = t0 - self._last_step_end
        elif self._inflight:
            overhead = 0.0
        if overhead is not None:
            _M_SCHED_OVERHEAD.observe(overhead)
            if self.controller is not None:
                # Chunk/depth steering signal (PR 15): the same
                # un-overlapped observation the histogram gets.
                self.controller.note_overhead(overhead)
            with self._lock:
                self._sched_overhead_sum += overhead
                self._sched_overhead_count += 1
            if overhead > 0 and self._last_step_end is not None:
                # The Chrome export's host track: un-overlapped
                # scheduler work between the pipeline draining and this
                # dispatch (overlapped dispatches observe 0 and emit
                # nothing — the track shows exactly the time the device
                # sat idle waiting on the host).
                _flight.flight_recorder().record(
                    "host", self._last_step_end, overhead
                )
        self._last_step_end = None
        # Snapshot rule as rows(): _tok_dirty is reset and _last_tokens
        # mutated right after this dispatch; the spec branch reuses the
        # same snapshot for its counts patch.
        dirty_np = np.array(self._tok_dirty)
        if self._inflight:
            tokens = self._inflight[-1].next_input
            if dirty_np.any():
                tokens = jnp.where(
                    jnp.asarray(dirty_np),
                    jnp.asarray(np.array(self._last_tokens)),
                    tokens,
                )
        else:
            tokens = rows(self._last_tokens)
        self._tok_dirty[:] = False
        if spec:
            # Effective spec window (PR 15): the controller shrinks k
            # within [1, spec_k] from per-group measured acceptance —
            # menu {1, spec_k}, so the _jit_spec trace family stays
            # two entries. Everything downstream (stream plan, cost
            # model, drafted counter, the _Inflight record the fetch's
            # acceptance accounting divides by) uses THIS k.
            k_spec = c.spec_k
            if self.controller is not None:
                k_spec = max(
                    1,
                    min(
                        c.spec_k,
                        self.controller.spec_k_for(
                            [self._group_key(s) for _, s in rows_now],
                            c.spec_k,
                        ),
                    ),
                )
            # Device-resident PRNG counts: the previous spec program's
            # counts_out (data-dependent — the host can't advance them
            # at dispatch), with (re)activated rows patched from the
            # host mirror exactly like their input token. A mode flip
            # drains the pipeline first (_run), so a spec window only
            # ever chains spec outputs.
            counts_dev = self._counts_device_arg(dirty_np, rows)
            src, fill, off, streams, shared = self._spec_stream_plan(
                rows_now, k_spec
            )
            # Flight events for stream-plan CHANGES only (the plan
            # itself re-runs every round): a mate picking up a new
            # donor, or falling back to drafting for itself (diverge).
            for i, _ in rows_now:
                cur = int(src[i])
                prev = self._stream_src_prev.get(i)
                if prev is not None and prev != cur:
                    _flight.flight_recorder().record(
                        "stream_donor",
                        t0,
                        slot=i,
                        donor=cur,
                        prev=prev,
                        diverged=cur == i,
                    )
                self._stream_src_prev[i] = cur
            emit, emit_cnt, self.cache, self.draft_cache, next_in, cnt_out = (
                self._jit_spec(
                    k_spec,
                    self.params,
                    self._draft_params,
                    self.cache,
                    self.draft_cache,
                    tokens,
                    rows(self._seeds),
                    counts_dev,
                    rows(temps),
                    rows(self._topks),
                    rows(self._topps),
                    filters_active,
                    all(
                        s.request.temperature <= 0.0 for _, s in rows_now
                    ),
                    groups,
                    rows(src),
                    rows(fill),
                    rows(off),
                )
            )
            # rounds=1: the verify round IS the multi-token step — one
            # decode-advancing round per spec program (the
            # device-rounds algebra the decode_rounds leg gates on).
            ev = self._count_program("spec", rows=len(rows_now), rounds=1)
            cost = self._program_cost(
                "spec", rows_now, k_spec, streams=streams
            )
            drafted = k_spec * streams
            _M_SPEC_DRAFTED.inc(drafted)
            with self._lock:
                self._spec_drafted += drafted
                self._spec_shared_rows += shared
            # Host counts do NOT advance here (the plain path's += k):
            # the yield is data-dependent; _fetch_one syncs the mirror.
            rec = _Inflight(
                tokens=emit,
                next_input=next_in,
                t0=t0,
                k=1,
                rows=rows_now,
                spec=True,
                spec_k=k_spec,
                emit_cnt=emit_cnt,
                counts_out=cnt_out,
                flight=ev,
                cost=cost,
            )
            return self._dispatch_tail(rec, groups, k)
        # Multi-round window (PR 12): like the spec branch, the yield
        # is data-dependent once rows can freeze mid-window, so PRNG
        # counts thread device-resident program-to-program (host
        # mirror syncs at fetch), with (re)activated rows patched from
        # the mirror exactly like their input token. A window only
        # ever chains programs of one mode (_run drains on change) —
        # ``rounds`` is threaded from _run's one read of the engage
        # state per iteration, exactly like ``spec``, so a live
        # config flip between the mode check and this dispatch cannot
        # split the two decisions.
        R = rounds
        rounds_now = 0
        counts_arg = None
        budgets_dev = screen_dev = None
        emit_cnt = cnt_out = None
        if self.controller is not None and self._draft_cfg is not None:
            # Probe clock for a disengaged spec controller: plain
            # windows counted at the dispatch site (idle loop
            # iterations must not advance it).
            self.controller.note_plain_window()
        rounds_clean = rounds_choice and rounds == 1
        if R > 1:
            counts_arg = self._counts_device_arg(dirty_np, rows)
            budgets_np, screen_np, rounds_now = self._stop_plan(rows_now, R)
            if rounds_choice:
                # Chosen full window (PR 15): clean unless the stop
                # plan collapsed it (an unscreenable stop is forced,
                # not evidence about the window arms). The regime
                # choice itself happened in _run, at the same
                # once-per-iteration altitude as the engage state.
                rounds_clean = rounds_now == R
            budgets_dev = jnp.asarray(budgets_np)
            screen_dev = jnp.asarray(screen_np)
            k = rounds_now
        else:
            counts_arg = rows(self._counts)
        args = (
            self.params,
            self.cache,
            tokens,
            rows(self._seeds),
            counts_arg,
            rows(temps),
            rows(self._topks),
            rows(self._topps),
            filters_active,
            groups,
        )
        chunk_rec = None
        if chunk_idx is None:
            if rounds_now:
                # Same prepared device args as the legacy program
                # (args[9] is groups — _rounds_sample takes it after
                # the stop data).
                next_tok, _, self.cache, next_in, cnt_out, emit_cnt = (
                    self._jit_rounds(
                        rounds_now, *args[:9], budgets_dev, screen_dev,
                        args[9],
                    )
                )
            else:
                next_tok, _, self.cache, next_in = self._jit_decode(*args)
            ev = self._count_program(
                "decode", rows=len(rows_now), rounds=k
            )
            cost = self._program_cost("decode", rows_now, k)
        else:
            slot = self._slots[chunk_idx]
            chunk_ids = slot.padded_ids[
                slot.next_pos : slot.next_pos + slot.chunk
            ]
            written_end = slot.next_pos + slot.chunk
            chunk_done = written_end >= slot.prompt_len
            out = self._fused_fn(slot.chunk, slot.s_bucket)(
                *args,
                jnp.asarray(chunk_ids[None]),
                jnp.asarray(slot.table),
                jnp.int32(slot.next_pos),
                jnp.int32(slot.prompt_len - 1),
                chunk_done,
                *(
                    (rounds_now, budgets_dev, screen_dev)
                    if rounds_now
                    else ()
                ),
            )
            if rounds_now:
                (
                    next_tok, _, self.cache, next_in, chunk_logits,
                    emit_cnt, cnt_out,
                ) = out
            else:
                next_tok, _, self.cache, next_in, chunk_logits = out
            ev = self._count_program(
                "fused", rows=len(rows_now) + 1, rounds=k
            )
            cost = self._program_cost(
                "fused", rows_now, k, chunk_ext=(written_end, slot.chunk)
            )
            _flight.flight_recorder().record(
                "chunk",
                t0,
                trace_id=_tracing.trace_id_of(slot.request.trace),
                slot=chunk_idx,
                pos=slot.next_pos,
                width=slot.chunk,
                fused=1,
            )
            if self.draft_cache is not None:
                # The draft's mirror of the riding chunk — its own
                # small program right behind the fused dispatch (the
                # two touch disjoint pools; stream order is irrelevant
                # between them, only their fetch/flush consumers care).
                self._draft_prefill_chunk(slot, chunk_ids, slot.next_pos)
            written_real = min(written_end, slot.prompt_len)
            # Device-stream readiness: the pages this chunk covers are
            # written by an ALREADY-DISPATCHED program, and every
            # consumer is either a later program on the same stream
            # (dependent chunks, decode reads) or a host operation
            # that flushes the pipeline first (restore installs, CoW
            # copies, demotion device_gets block on the stream).
            for node, end_pos in slot.reg_nodes:
                if not node.ready and end_pos <= written_real:
                    node.ready = True
            chunk_rec = _InflightChunk(
                idx=chunk_idx,
                slot=slot,
                done=chunk_done,
                logits=chunk_logits,
                pos=slot.next_pos,
                width=slot.chunk,
            )
            slot.next_pos = written_end
        # Host counters track the DEVICE stream at dispatch: the
        # program advances every participating row by k regardless of
        # what the fetch later keeps, so a surviving row's next
        # dispatch folds the right PRNG indices. With a draft
        # configured, a plain program also widens the row's draft lag
        # (the mirror never saw these tokens — _spec_catch_up replays
        # them when speculation re-engages). A MULTI-ROUND program's
        # advance is data-dependent (frozen rows stop folding), so
        # both mirrors sync at fetch instead — the spec discipline.
        if not rounds_now:
            for i, s in rows_now:
                self._counts[i] += k
                if self.draft_cache is not None:
                    s.draft_lag += k
        rec = _Inflight(
            tokens=next_tok, next_input=next_in, t0=t0, k=k,
            rows=rows_now, chunk=chunk_rec, rounds=rounds_now,
            rounds_clean=rounds_clean,
            emit_cnt=emit_cnt, counts_out=cnt_out, flight=ev, cost=cost,
        )
        self._dispatch_tail(rec, groups, k)

    def _dispatch_tail(self, rec: "_Inflight", groups, k: int) -> None:
        """Enqueue the dispatched program and account the window —
        shared by the spec and plain branches so the bookkeeping
        cannot drift. ``k`` is the steps this program reads the shared
        prefix (spec programs pass 1: _spec_ok pins steps_per_sync to
        1, and the verify round reads the group's shared pages once)."""
        self._inflight.append(rec)
        _M_DISPATCH_INFLIGHT.set(len(self._inflight))
        _M_GROUP_SIZE.set(
            self._groups.largest_group if groups is not None else 0
        )
        if groups is not None:
            # Shared pages read once per group instead of once per
            # member: count the reads this program skips.
            saved = (
                self._groups.saved_tokens_per_step * self._kv_token_bytes * k
            )
            _M_KV_SAVED.inc(saved)
            with self._lock:
                self._kv_bytes_saved += saved

    def _fetch_one(self) -> None:
        """Fetch the OLDEST in-flight program's tokens and run its host
        bookkeeping — stop scans, retirement, future resolution.

        Retirement necessarily lags dispatch by the in-flight depth: a
        row that finished in program *n* keeps decoding through the
        already-enqueued programs *n+1..n+depth-1*. Those tokens are
        discarded here — rows are credited by _Slot IDENTITY, so a slot
        retired (or retired and re-admitted) since dispatch never sees
        a stale program's output, and the stop-trim semantics stay
        byte-identical to depth 1 — and the page overshoot is
        pre-budgeted by :meth:`_table_pages`.
        """
        rec = self._inflight.popleft()
        next_np = np.asarray(rec.tokens)  # [slots, k] — THE host sync
        cnt_np = (
            np.asarray(rec.emit_cnt)
            if (rec.spec or rec.rounds)
            else None
        )
        step_end = time.perf_counter()
        # Device-step latency: at depth 1 the program started at its
        # own dispatch; deeper, it started when its predecessor
        # finished — approximated from the host side by the previous
        # fetch's completion.
        start = rec.t0
        if self._last_fetch_end is not None:
            start = max(start, self._last_fetch_end)
        dur = step_end - start
        self._last_fetch_end = step_end
        # The pipeline drained: host time from here to the next
        # dispatch is un-overlapped. With programs still in flight the
        # gap is hidden and the next dispatch observes 0.
        self._last_step_end = step_end if not self._inflight else None
        self._hb_step = time.monotonic()
        _M_STEP_SECONDS.observe(dur)
        if rec.flight is not None:
            # Fill the dispatch-time flight event with the TRUE device
            # window (same correction _M_STEP_SECONDS uses): the Chrome
            # export's device track is these windows back to back.
            rec.flight.t0 = start
            rec.flight.dur = dur
        self._mbu_account(
            "spec" if rec.spec else ("fused" if rec.chunk else "decode"),
            rec.cost,
            dur,
        )
        _M_DISPATCH_INFLIGHT.set(len(self._inflight))
        alive = [(i, s) for i, s in rec.rows if self._slots[i] is s]
        with self._lock:
            self._decode_steps += rec.k
            self._decode_step_sum += dur
            self._decode_step_count += 1
        # One "decode_step" span per DISTINCT trace among the program's
        # surviving participants: a batched step belongs to every
        # request it advanced (the per-trace span budget bounds long
        # decodes; retired requests take no post-retirement spans).
        step_traces: dict[int, object] = {}
        for _, slot in alive:
            if slot.request.trace is not None:
                step_traces[id(slot.request.trace)] = slot.request.trace
        for tr in step_traces.values():
            # Same window as _M_STEP_SECONDS: [start, step_end], where
            # start is the corrected dispatch/predecessor-fetch stamp.
            tr.add_span(
                "decode_step", start, dur, active=len(rec.rows), k=rec.k
            )
        _M_STEPS.inc(rec.k)
        if rec.rows:
            _M_OCCUPANCY.observe(len(rec.rows))
        if rec.spec:
            # Sync the host PRNG-count mirror (the spec program's yield
            # is data-dependent, so dispatch couldn't advance it), and
            # feed the speculation metrics from one site. Rows whose
            # slot was retired/reused mid-flight are skipped exactly
            # like their tokens; a reused slot's activation reset its
            # count and marked it dirty, so the mirror stays right.
            emitted = 0
            accepted = 0
            accept_samples = []
            for i, s in alive:
                n = int(cnt_np[i])
                self._counts[i] += n
                emitted += n
                accepted += n - 1
                # Per-request speculation tallies (the "spec tokens
                # accepted per round" line of the request summary).
                s.spec_rounds += 1
                s.spec_accepted_toks += n - 1
                accept_samples.append(
                    (self._group_key(s), n - 1, rec.spec_k)
                )
            if self.controller is not None and accept_samples:
                # Per-group acceptance EWMAs (PR 15) — fed from the
                # SAME per-row counts gateway_spec_acceptance's
                # fraction aggregates, keyed by the GroupTracker
                # bucket identity.
                self.controller.note_spec_round(accept_samples)
            if alive:
                _M_SPEC_ACCEPTED.inc(accepted)
                frac = accepted / (rec.spec_k * len(alive))
                _M_SPEC_ACCEPTANCE.observe(frac)
                _M_SPEC_VERIFIED.set(emitted)
                xmodel = (
                    self._vocab_map is not None
                    and not self._vocab_map.identity
                )
                if xmodel and accepted > 0:
                    # Cross-model speculation (PR 18): these accepts
                    # crossed a tokenizer boundary through the vocab
                    # remap. The flight event is the bench's "≥ 1
                    # cross-model accept" witness.
                    _M_SPEC_XMODEL.inc(accepted)
                    _flight.flight_recorder().record(
                        "spec_xmodel_accept",
                        time.perf_counter(),
                        accepted=accepted,
                        rows=len(alive),
                        spec_k=rec.spec_k,
                    )
                with self._lock:
                    self._spec_accepted += accepted
                    if xmodel:
                        self._spec_xmodel_accepted += accepted
                    self._spec_acc_sum += frac
                    self._spec_acc_count += 1
                    self._spec_verified_last = emitted
        if rec.rounds and not rec.spec:
            # Multi-round program (PR 12): sync the host PRNG-count
            # mirror by each surviving row's real yield (frozen rounds
            # folded nothing), and widen the draft lag by the same —
            # the spec discipline, minus the speculation metrics. Rows
            # whose slot was retired/reused mid-flight are skipped
            # exactly like their tokens (a reused slot's activation
            # reset its count and marked it dirty).
            for i, s in alive:
                n = int(cnt_np[i])
                self._counts[i] += n
                if self.draft_cache is not None:
                    s.draft_lag += n
        emitted_total = 0
        tbt_sum, tbt_count = 0.0, 0
        for i, slot in alive:
            done = False
            n_emit = int(cnt_np[i]) if cnt_np is not None else rec.k
            for j in range(n_emit):
                tok = int(next_np[i, j])
                slot.generated.append(tok)
                self._last_tokens[i] = tok
                # Token-timeline stamp (PR 10): tokens surface at the
                # fetch — the first of this fetch carries the gap since
                # the row's previous token, the rest arrived with it
                # (gap 0), which is exactly what a streaming client
                # observes. One observation per generated token past
                # the request's first (that one is TTFT's).
                gap = step_end - slot.t_last_tok if j == 0 else 0.0
                slot.t_last_tok = step_end
                slot.gaps.append(gap)
                _M_TBT.observe(gap)
                tbt_sum += gap
                tbt_count += 1
                emitted_total += 1
                done = (
                    tok == self.tokenizer.eos_id
                    or len(slot.generated) >= slot.request.max_new_tokens
                    or self._hit_stop(slot)
                )
                if done:
                    # Tokens past this point were decoded on device
                    # but never belonged to the request.
                    break
            if done:
                self._retire(i)
        if tbt_count:
            with self._lock:
                self._tbt_sum += tbt_sum
                self._tbt_count += tbt_count
        if (
            self.controller is not None
            and not rec.spec
            and rec.rows
            and self.config.decode_rounds > 1
        ):
            # Two-arm rounds feed (PR 15): this window's realized
            # emissions, attributed to the running regime (a plain
            # window is the arm-1 regime while the controller
            # arbitrates; rec.rounds_clean says whether the length
            # was chosen or forced).
            self.controller.note_rounds_window(
                rec.rounds if rec.rounds else 1,
                emitted_total,
                clean=rec.rounds_clean,
            )
        if rec.flight is not None:
            # Replace, never mutate: a concurrent export may hold the
            # old meta dict.
            rec.flight.meta = {**rec.flight.meta, "tokens": emitted_total}
        ch = rec.chunk
        if ch is not None and self._slots[ch.idx] is ch.slot:
            # Fused prefill chunk (PR 8): host bookkeeping deferred to
            # the fetch — its device work completed with the program
            # whose tokens we just pulled. The chunk did not stall the
            # decode loop (it rode the dispatch), so the stall
            # histogram observes 0 — count-lockstep with
            # prefill_chunks, value-honest about the fusion.
            slot = ch.slot
            _M_PREFILL_STALL.observe(0.0)
            with self._lock:
                self._prefill_chunks += 1
            trace = slot.request.trace
            if trace is not None:
                trace.add_span(
                    "prefill_chunk", start, dur,
                    pos=ch.pos, chunk=ch.width, fused=1,
                )
            if ch.done:
                # Final chunk: sample the first token from the logits
                # the fused program already computed (same PRNG draw,
                # same unembed as the standalone path), make the row
                # visible to the decode program, flip to decoding.
                first = self._sample_first(slot.request, ch.logits)
                self.cache = install_seq(
                    self.cache,
                    jnp.int32(ch.idx),
                    jnp.asarray(slot.table),
                    jnp.int32(slot.prompt_len),
                )
                self._install_draft_seq(ch.idx, slot)
                self._activate(ch.idx, slot, first)

    def _run(self) -> None:
        while not self._stop.is_set():
            self._hb_tick = time.monotonic()
            # Fleet requests first (PR 14): preemption frees pages the
            # admission below may need; exports are bounded spills.
            self._steer_step()
            self._preempt_step()
            self._export_step()
            self._admit()
            progress = False
            ran_program = False
            # At most ONE prefill work unit per iteration — a host-tier
            # page restore (which unblocks gated prefills) or a prefill
            # chunk: running slots pay a bounded stall per admission
            # instead of a whole prompt's prefill.
            chunk_idx = None
            if self.config.prefill_chunk > 0:
                if self._restore_step():
                    progress = True
                else:
                    chunk_idx = self._pick_prefill_slot()
            # Speculative decoding (PR 9): read the engage state once
            # per iteration (the bench flips config.spec_decode between
            # bursts). While speculation is on, chunks run standalone —
            # the verify program IS the decode dispatch, and a chunk
            # lane on it is future work.
            spec_now = self._spec_ok
            if spec_now and self.controller is not None:
                # Adaptive spec gate (PR 15): the controller may
                # DISENGAGE speculation when every decoding group's
                # measured acceptance sits below the floor (and
                # re-probe periodically). The flip takes the same
                # drain + catch-up path as a live spec_decode flip —
                # the PR-9 rules this composes with.
                spec_now = self.controller.spec_gate(
                    [
                        self._group_key(s)
                        for s in self._slots
                        if s is not None and s.phase == "decode"
                    ]
                )
            # Multi-round engage state, read ONCE per iteration next to
            # spec_now and threaded into _dispatch the same way: the
            # mode-flush decision and the dispatched program must come
            # from the same read, or a live decode_rounds flip between
            # the two would chain a counts-mode mismatch into the
            # window (a flip is a between-bursts event, but the
            # scheduler must stay correct if one lands mid-burst).
            rounds_now = 1 if spec_now else self._rounds
            rounds_choice = False
            if rounds_now > 1 and self.controller is not None:
                # Roofline-adaptive R (PR 15): the controller's
                # two-arm regime choice over {plain 1-round, R-round
                # window}, consulted at the same once-per-iteration
                # altitude as the engage state itself — an arm-1
                # choice dispatches PLAIN programs (the mode flush
                # below drains on the transition, bounded by the
                # stretch cadence), an arm-R choice keeps the masked
                # window, and a batch about to retire forces 1 (the
                # masked tail rounds would decode nothing). Byte
                # parity vs any fixed R is the PR-12 masking
                # contract; the {1, R} menu adds ZERO compiled
                # traces.
                max_rem = max(
                    (
                        s.request.max_new_tokens - len(s.generated)
                        for s in self._slots
                        if s is not None and s.phase == "decode"
                    ),
                    default=0,
                )
                cap = max(
                    1,
                    min(
                        rounds_now,
                        self.controller.rounds_cap(max_rem, rounds_now),
                    ),
                )
                rounds_choice = max_rem >= rounds_now
                rounds_now = cap
            if self._draft_cfg is not None:
                # Flight event on TRANSITIONS only (spec_decode is read
                # per iteration; steady state records nothing).
                if (
                    self._spec_flip_prev is not None
                    and self._spec_flip_prev != spec_now
                ):
                    _flight.flight_recorder().record(
                        "spec_flip", time.perf_counter(), on=spec_now
                    )
                self._spec_flip_prev = spec_now
            # The fused scheduler step (PR 8): a ready chunk rides the
            # decode dispatch as one more ragged-kernel row — ONE
            # device program per iteration instead of chunk-then-
            # decode. With no decode batch to ride (or fusion off) the
            # chunk runs standalone, still one program this iteration.
            fused = (
                chunk_idx is not None
                and self._fused_ok
                and self._decoding()
                and not spec_now
            )
            if chunk_idx is not None and not fused:
                self._prefill_step(chunk_idx)
                progress = True
                ran_program = True
                if self._fused_ok:
                    # A standalone chunk only runs under fusion when
                    # the decode batch was EMPTY; if its final chunk
                    # just activated the slot, dispatching in the same
                    # pass would make this the one iteration that runs
                    # two programs. Defer to the next pass (the loop
                    # spins straight back) — one program per iteration
                    # stays exact, which is the metric the A/B gates.
                    with self._lock:
                        self._work_iterations += 1
                    continue
            if self._decoding():
                # Software pipeline: enqueue the next program FIRST,
                # then fetch the oldest once the window is full — the
                # fetch's host sync lands while the newer program(s)
                # run. depth 1 reduces to dispatch -> fetch -> bookkeep
                # (the serialized parity baseline); the while also
                # drains excess depth after a live depth reduction.
                if self._inflight:
                    # A plain program feeds the next dispatch from
                    # host-advanced counts; spec and multi-round
                    # programs from their device counts_out. Mixing
                    # modes in one window would desync the PRNG
                    # mirror — drain first (a flip is a between-bursts
                    # event, never hot-path). Multi-round flush
                    # semantics extend unchanged otherwise: an R-round
                    # window drains like any other (its programs'
                    # fetches credit data-dependent yields), so every
                    # stable-cache operation keeps working under R.
                    tail = self._inflight[-1]
                    tail_mode = (
                        "spec"
                        if tail.spec
                        else ("rounds" if tail.rounds else "plain")
                    )
                    mode_now = (
                        "spec"
                        if spec_now
                        else ("rounds" if rounds_now > 1 else "plain")
                    )
                    if tail_mode != mode_now:
                        self._flush_pipeline()
                if spec_now:
                    # Rows that decoded through an off window need
                    # their draft mirror replayed first — no-op in the
                    # steady state (every lag-free iteration).
                    self._spec_catch_up()
                self._dispatch(
                    chunk_idx if fused else None,
                    spec=spec_now,
                    rounds=rounds_now,
                    rounds_choice=rounds_choice,
                )
                while len(self._inflight) >= self._depth:
                    self._fetch_one()
                progress = True
                ran_program = True
            else:
                if self._inflight:
                    # The decode batch went empty (every known row
                    # retired) with programs still in flight: drain
                    # them — late retirements and futures resolve here.
                    self._fetch_one()
                    progress = True
                if not self._decoding():
                    # No device step pending: the gap to the next one
                    # is not scheduling overhead.
                    self._last_step_end = None
            if ran_program:
                # Denominator of "device programs per scheduler
                # iteration" — the bench's fusion gate.
                with self._lock:
                    self._work_iterations += 1
            if not progress:
                self._last_step_end = None
                self._work.wait(timeout=0.1)
                self._work.clear()


class ContinuousBackend(_backend_base.Backend):
    """Backend seam over a :class:`ContinuousBatcher`.

    The Coordinator's panel fan-out (``generate_batch``) rides token-level
    continuous batching: each request joins the running decode batch at
    step granularity instead of waiting for a whole-batch program. This
    closes the reference's L1 seam (``call_gemini``, src/main.rs:82-86)
    over the throughput-serving path.
    """

    def __init__(self, batcher: ContinuousBatcher):
        self.batcher = batcher

    async def generate_batch(self, requests):
        import asyncio

        BackendError = _backend_base.BackendError
        GenerationResult = _backend_base.GenerationResult

        # Per-request top_k/top_p/stop ride as decode-step data
        # (sample_token_per_request + host stop checks), so the full
        # SamplingParams surface passes through — protocol-identical
        # behavior to LocalBackend.
        futs = []
        try:
            for r in requests:
                futs.append(
                    self.batcher.submit(
                        r.prompt,
                        max_new_tokens=r.params.max_new_tokens,
                        temperature=r.params.temperature,
                        seed=r.params.seed,
                        top_k=r.params.top_k,
                        top_p=r.params.top_p,
                        stop=r.params.stop,
                    )
                )
        except (RuntimeError, ValueError) as e:
            # A mid-batch submit failure (stopped batcher, rejected
            # prompt) leaves earlier futures in flight: cancel the ones
            # still waiting so their device work isn't silently orphaned
            # (_admit/_retire skip done futures).
            for f in futs:
                f.cancel()
            raise BackendError(f"continuous submit failed: {e}") from e
        outs = await asyncio.gather(*(asyncio.wrap_future(f) for f in futs))
        return [
            GenerationResult(
                text=o.text, num_tokens=o.num_tokens, meta=o.timing
            )
            for o in outs
        ]

    def health(self) -> dict:
        """Gateway readiness probe surface: the batcher heartbeat."""
        return self.batcher.heartbeat()

    @property
    def tokenizer(self):
        """The batcher tokenizer — the gateway's ``/debug/chains``
        handler encodes ``?prompt=`` probes with it (PR 16)."""
        return self.batcher.tokenizer

    def prefix_probe(self, ids) -> dict:
        """``/debug/chains`` probe surface: how much of this prompt's
        prefix chain is resident here (PR 16 peer routing)."""
        return self.batcher.prefix_probe(ids)

    def prefetch(self, prompt: str) -> bool:
        """Gateway enqueue-time prefetch hook (PR 17): the single-
        replica deployment's destination is always THIS batcher, so
        the admission-queue wait is free overlap — stage the prompt's
        host-store pages now and the restore plan at admission finds
        them staged. Non-blocking (a queue append); advisory (a wrong
        guess falls through to get_run/recompute)."""
        ids = self.batcher.tokenizer.encode(prompt)
        return self.batcher.prefetch_chain(
            ids[-self.batcher.config.seq_buckets[-1]:]
        )

    def request_cost(self, prompt: str, max_new_tokens: int) -> float:
        """Modeled bytes of one request's whole schedule — the
        gateway's cost-budget admission consults this (PR 15) so its
        queue bound counts the same unit the router's load_cost
        compares. Tokenizes once (ByteTokenizer is O(len) on the
        event loop; the submit path re-encodes — correctness over a
        cached double-encode here)."""
        return self.batcher.modeled_request_cost(
            len(self.batcher.tokenizer.encode(prompt)), max_new_tokens
        )

    async def close(self) -> None:
        self.batcher.close()
