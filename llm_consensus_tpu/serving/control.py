"""Roofline-adaptive runtime control: close the loop on the cost model.

Since PR 10 the batcher has MODELED every device program's HBM bytes
and FLOPs at dispatch and MEASURED its wall-clock window
(``gateway_program_mbu{kind}``, ``gateway_spec_acceptance``,
``gateway_sched_overhead_seconds``) — but every knob that signal
should drive stayed a static config value. This module turns the
attribution plane into a feedback loop (ROADMAP item 4; ClusterFusion++
and TPLA in PAPERS.md are the framing: the right dispatch shape is a
function of where the workload sits on the roofline, and prefill and
decode sit in different places):

- **spec_k auto-tune.** Measured draft acceptance is tracked per
  shared-prefix group (EWMA over the same per-round fractions
  ``gateway_spec_acceptance`` observes; group identity = the row's
  first prefix page, the GroupTracker bucket key) and the effective k
  of each speculative dispatch moves within ``[1, spec_k]`` — menu
  ``{1, spec_k}``, so the jitted program family stays TWO traces. A
  workload whose groups all reject (adversarial draft) stops paying
  full-width verify rows; high-acceptance self-draft groups keep the
  whole window. When every group's EWMA sits below the disengage
  floor, speculation DISENGAGES entirely (the PR-9 live-flip drain
  rules make this safe mid-burst: the pipeline drains on the mode
  change and ``_spec_catch_up`` replays the draft on re-engage) and a
  bounded probe window re-engages periodically so a draft that starts
  accepting again regrows to the full k.
- **Roofline-adaptive R.** Each plain multi-round dispatch picks its
  window from ``{1, R}`` (the SAME two traces ``decode_rounds``
  already compiles — stop-bound windows collapse to 1 today): R when
  every decoding row has budget for the whole window and the modeled
  decode roofline position says weight-read-bound (weights dominate
  the modeled bytes — the ClusterFusion++ regime where folding rounds
  amortizes dispatch overhead against a weight-dominated program), 1
  when the batch is about to retire (max remaining budget < R: the
  masked tail rounds would decode nothing while stretching retirement
  lag). Riding PR 12's early-exit masking keeps text byte-identical
  to ANY fixed R by construction.
- **Chunk/depth steering.** The effective prefill-chunk width for NEW
  admissions moves within the menu ``{chunk, chunk/2}`` (one extra
  compiled (chunk, bucket) trace per bucket, AT MOST — never a
  recompile storm; decisions only ever flip between menu widths) from
  measured un-overlapped scheduler overhead: a host-bound loop keeps
  full-width chunks (fewer programs amortize the host work), a fully
  overlapped loop with a bandwidth-starved chunk lane halves them
  (bounded decode-lane stall per fused window). Pipeline depth moves
  within ``[1, pipeline_depth]`` by probing: un-overlapped overhead
  OBSERVES 0 once hidden, so the controller periodically probes one
  depth lower and backs off the moment overhead re-appears.
- **Modeled-cost admission + restore pacing.** The admission
  controller's cost-budget mode (server/admission.py) uses
  :meth:`llm_consensus_tpu.serving.continuous.ContinuousBatcher.
  modeled_request_cost` — the SAME modeled-bytes unit ``load_cost``
  routes on — for the queue bound AND the overflow hard cap, so a
  32k-context request is no longer one unit of work; and the PR-14
  preempt-to-host-tier hook consults :meth:`AdaptiveController.
  restore_pacing_ok` before demoting — preemption stops once the
  modeled restore debt (bytes demoted by preemption and not yet
  restored) would thrash the host tier instead of absorbing the storm.

Every decision is recorded as an ``autotune`` flight event (on value
CHANGES, like spec flips), counted in
``gateway_autotune_decisions_total{knob}`` and mirrored as the
``gateway_autotune_value{knob}`` gauge + the batcher's ``stats()``
``autotune_*`` keys (lockstep tested). Pin any knob via
:class:`ControlConfig` (``tune_* = False``) to freeze it at its
configured value; with an unresolvable ``--hbm-gbps auto`` the
MBU-driven decisions disable themselves (acceptance and overhead
steering keep working) — :func:`resolve_hbm_gbps`.

``bench.py --serve-adaptive`` gates adaptive mode >= every fixed
(spec_k x R) grid point on a mixed burst with per-pair byte-identical
greedy text and zero recompiles after warmup.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass

from llm_consensus_tpu.server.metrics import (
    AUTOTUNE_DECISIONS as _M_DECISIONS,
)
from llm_consensus_tpu.server.metrics import (
    AUTOTUNE_VALUE as _M_VALUE,
)

log = logging.getLogger(__name__)

__all__ = [
    "ControlConfig",
    "AdaptiveController",
    "resolve_hbm_gbps",
    "HBM_GBPS_TABLE",
]

#: Knob names — the ``knob`` label of gateway_autotune_* and the
#: stats() mirror keys.
KNOBS = ("spec_k", "rounds", "chunk", "depth", "restore_batch")

#: Per-platform peak HBM bandwidth (GB/s, 1e9 bytes/s) for
#: ``--hbm-gbps auto``: matched as a lowercase substring of
#: ``jax.devices()[0].device_kind``. The CPU sentinel is deliberately
#: tiny and non-zero — it keeps the MBU plumbing live on smoke runs
#: without pretending a laptop core has TPU HBM (CPU "MBU" values are
#: a plumbing check, the PR-10 caveat).
HBM_GBPS_TABLE: tuple[tuple[str, float], ...] = (
    ("v5p", 2765.0),
    ("v5 lite", 819.0),
    ("v5e", 819.0),
    ("v4", 1228.0),
    ("cpu", 10.0),
)


def resolve_hbm_gbps(spec) -> float:
    """Resolve an ``--hbm-gbps`` value: a number passes through,
    ``"auto"`` looks the running platform up in
    :data:`HBM_GBPS_TABLE`. Unresolvable auto returns 0.0 with ONE
    warning — MBU-driven steering disables itself at 0 (the
    controller's acceptance/overhead loops keep working), exactly the
    ``hbm_gbps == 0`` contract the gauge already has."""
    if not isinstance(spec, str):
        return float(spec)
    s = spec.strip().lower()
    if s != "auto":
        return float(s)
    import jax

    try:
        dev = jax.devices()[0]
        kind = f"{dev.platform} {dev.device_kind}".lower()
    except Exception:  # noqa: BLE001 - no backend is "unresolvable"
        kind = ""
    for sub, gbps in HBM_GBPS_TABLE:
        if sub in kind:
            return gbps
    log.warning(
        "--hbm-gbps auto: no roofline entry for device kind %r — "
        "MBU-driven adaptive decisions disabled (acceptance and "
        "overhead steering still run); pass a numeric peak to enable",
        kind or "<none>",
    )
    return 0.0


@dataclass
class ControlConfig:
    """Knob enables + thresholds for :class:`AdaptiveController`.

    Set ``tune_<knob> = False`` to PIN that knob at its configured
    value (the disable-steering lever the README documents); the
    controller still collects signals so a re-enable starts warm.
    """

    # -- knob enables ---------------------------------------------------
    tune_spec_k: bool = True
    tune_rounds: bool = True
    tune_chunk: bool = True
    tune_depth: bool = True

    # -- shared EWMA smoothing ------------------------------------------
    #: Weight of the newest observation in every EWMA here (acceptance,
    #: overhead, MBU). 0.2 ~ a 5-sample memory: fast enough to catch a
    #: burst's character, slow enough that one jittered round doesn't
    #: flip a knob.
    ewma_alpha: float = 0.2

    # -- spec_k auto-tune -----------------------------------------------
    #: Per-group acceptance EWMA below this => the group's recommended
    #: k is 1 (stop wasting verify width on rejects).
    accept_low: float = 0.3
    #: EWMA at/above this => full spec_k again (regrow hysteresis gap
    #: vs accept_low prevents flapping at the boundary).
    accept_high: float = 0.6
    #: When EVERY decoding group's EWMA sits below this, speculation
    #: disengages entirely (the k=1 floor still pays a draft scan +
    #: 2-wide verify for ~nothing) — the PR-9 live-flip drain rules
    #: make the mode change safe mid-burst.
    accept_disengage: float = 0.15
    #: Acceptance samples a group needs before shrink/disengage apply
    #: (optimistic start: unknown groups get the full window).
    accept_min_samples: int = 3
    #: While disengaged, re-probe with one spec window every this many
    #: plain decode windows (a draft that starts accepting again must
    #: be able to regrow; each probe costs one catch-up replay).
    spec_probe_every: int = 64

    # -- roofline-adaptive R --------------------------------------------
    #: Modeled weight fraction (weight bytes / modeled program bytes,
    #: EWMA over fetched decode-kind programs) at/above which the
    #: workload counts as weight-read-bound => full R windows. Below
    #: it (KV-dominated long contexts) the per-program window matters
    #: less and R follows the budget rule only. Ignored (treated as
    #: weight-bound) when hbm_gbps is unresolved — the budget rule is
    #: the non-MBU half of the decision. This is the COLD-START prior
    #: only: once both window arms have measured rates, the measured
    #: throughput arbitrates (see rounds_probe_every).
    weight_bound_frac: float = 0.5
    #: The rounds decision is a measured two-arm choice over {1, R},
    #: arbitrated at STRETCH granularity: the controller runs one arm
    #: for ``rounds_stretch_windows`` consecutive windows, measures
    #: the stretch's wall-clock tokens/sec (Σ tokens between the
    #: first and last fetch — the realized burst throughput of that
    #: regime, prefill interleave and host gaps included; per-window
    #: ratios are far too noisy to rank arms ~10% apart), folds it
    #: into the arm's decayed rate, and picks the better-measured arm
    #: for the next stretch — after first CALIBRATING the unmeasured
    #: arm, and re-probing the losing arm every
    #: ``rounds_probe_stretches`` stretches so a shifted workload (a
    #: tunnel's RTT appearing, contexts growing KV-bound) can flip
    #: the choice back. A gap longer than ``rounds_stretch_gap_s``
    #: between fetches (idle batcher between bursts) discards the
    #: open stretch instead of counting the idle as regime time.
    rounds_stretch_windows: int = 12
    #: A stretch cut short by an idle gap (burst boundary) still
    #: folds when it accumulated at least this many windows — bursts
    #: shorter than a full stretch are measurements too, or a bursty
    #: workload would never calibrate the second arm.
    rounds_stretch_min: int = 5
    rounds_stretch_gap_s: float = 0.25
    rounds_probe_stretches: int = 8
    #: Flip hysteresis: the challenger arm must measure at least this
    #: fraction FASTER than the incumbent to take the regime. Stretch
    #: rates on a contended box jitter ±5-10%; without a margin a
    #: single misranked fold flips the regime and costs a whole
    #: stretch at the slower arm before the next fold corrects it.
    #: Real regime gaps (tunnel RTT, tail-masking waste) are tens of
    #: percent, far past the band.
    rounds_flip_margin: float = 0.05
    #: Probe backoff: a probe that LOSES (the regime snaps back)
    #: doubles its interval up to this many multiples — steady
    #: workloads stop paying a recurring probe tax, while the first
    #: few probes after a real shift still land quickly. Applies to
    #: the rounds-regime probe and the depth-lowering probe alike.
    probe_backoff_max: int = 16

    # -- chunk/depth steering -------------------------------------------
    #: Un-overlapped scheduler overhead per dispatch (EWMA, seconds)
    #: above this => the host is visible between programs: keep
    #: full-width chunks and the full pipeline depth.
    overhead_high_s: float = 0.002
    #: EWMA below this => the host loop is fully hidden; chunk may
    #: halve (bounded fused-window stall) and depth may probe lower.
    overhead_low_s: float = 0.0005
    #: Dispatches between depth-lowering probes, and the probe's
    #: length in dispatches. Probing is how a hidden (0-observing)
    #: overhead signal is re-measured at lower depth.
    depth_probe_every: int = 64
    depth_probe_len: int = 16

    # -- restore-batch sizing (host-tier promotion) ---------------------
    tune_restore_batch: bool = True
    #: Most pages one worker iteration may promote from the host tier
    #: (each restore flushes the decode pipeline and blocks on the
    #: installs). The controller moves the effective batch within
    #: ``[1, restore_batch_max]`` from the SAME un-overlapped-overhead
    #: EWMA chunk/depth steering reads: a host-bound loop takes the
    #: full batch (the flush it amortizes was already stalling on the
    #: host), a fully hidden loop takes 1 (bound the stall injected
    #: into a saturated decode lane). Controller absent => 1, the
    #: exact pre-PR-16 one-page-per-iteration behavior.
    restore_batch_max: int = 8

    # -- restore pacing (fleet preempt hook) ----------------------------
    #: Cap on the modeled restore debt preemption may accumulate,
    #: as a fraction of the host tier's byte budget: past it the
    #: preempt hook stops granting overflow admissions (demoting more
    #: chains that all must restore soon thrashes the tier instead of
    #: absorbing the storm; classic 429 backpressure resumes).
    restore_debt_frac: float = 0.5


class AdaptiveController:
    """Online knob controller for ONE :class:`ContinuousBatcher`.

    The batcher feeds measurements at its existing instrumentation
    sites (``note_*``) and consults decisions at its existing knob
    reads (``spec_gate`` / ``spec_k_for`` / ``rounds_cap`` /
    ``chunk_for`` / ``depth_for``); everything here is cheap host
    arithmetic under one lock. One controller per batcher — fleet
    replicas each get their own (their signals are per-replica).
    """

    def __init__(self, config: ControlConfig | None = None):
        self.config = config or ControlConfig()
        self._lock = threading.Lock()
        # Per-group draft-acceptance EWMAs: group key -> (ewma, n).
        # Bounded evict-oldest — group keys are page ids (recycled),
        # but a pathological workload must not grow this without
        # bound.
        self._accept: dict[int, tuple[float, int]] = {}
        self._accept_max = 1024
        # Spec engage state machine: engaged | disengaged (+ probe).
        self._spec_engaged = True
        self._plain_windows = 0  # windows since disengage (probe clock)
        self._probe_left = 0
        # Overhead / step-duration EWMAs (seconds).
        self._ovh_ewma: float | None = None
        self._dur_ewma: float | None = None
        # Fleet-steered restore-batch ceiling (PR 19); None = the
        # configured restore_batch_max stands alone.
        self._restore_cap: int | None = None
        # Modeled weight fraction of decode-kind programs (EWMA) and
        # the decode-MBU EWMA when a peak is configured.
        self._wf_ewma: float | None = None
        self._mbu_ewma: float | None = None
        # Two-arm rounds arbitration state (see ControlConfig): the
        # per-arm decayed (tokens, seconds) sums stretches fold into,
        # per-arm window counts (the FIRST window of an arm carries
        # its jit compile — seconds on a window worth milliseconds —
        # and is discarded), the active regime + its open stretch,
        # and the probe clock.
        self._rate_tok: dict[int, float] = {}
        self._rate_sec: dict[int, float] = {}
        self._rate_n: dict[int, int] = {}
        self._regime_arm: int | None = None
        self._r_max_seen: int | None = None
        self._stretch_t0: float | None = None
        self._stretch_tokens = 0.0
        self._stretch_windows = 0
        self._last_note_t: float | None = None
        self._stretches_since_probe = 0
        self._rounds_probe_backoff = 1
        self._rounds_probing = False
        self._stretch_dirty = False
        self._depth_probe_backoff = 1
        # Chunk hysteresis state (see chunk_for).
        self._chunk_half = False
        # Depth probe state.
        self._depth_eff: int | None = None
        self._since_probe = 0
        self._probe_depth: int | None = None
        self._probe_dispatches = 0
        # Restore-pacing debt (modeled bytes demoted by preemption,
        # not yet restored).
        self._restore_debt = 0
        # Peak bandwidth + modeled terms bound from the batcher.
        self._hbm_gbps = 0.0
        self._weight_bytes = 0
        self._kv_token_bytes = 0
        self._host_budget = 0
        # Last decision per knob (change detection for flight events)
        # + decision counters (stats mirrors of the Prometheus
        # families; one site, three surfaces).
        self._last: dict[str, float | int | None] = {k: None for k in KNOBS}
        self._decisions: dict[str, int] = {k: 0 for k in KNOBS}

    # -- binding --------------------------------------------------------

    def bind(
        self,
        *,
        hbm_gbps: float = 0.0,
        weight_bytes: int = 0,
        kv_token_bytes: int = 0,
        host_budget_bytes: int = 0,
    ) -> None:
        """Attach the batcher's static modeled terms (called once at
        batcher construction). ``hbm_gbps == 0`` disables the
        MBU-driven clauses; everything else keeps steering."""
        with self._lock:
            self._hbm_gbps = float(hbm_gbps)
            self._weight_bytes = int(weight_bytes)
            self._kv_token_bytes = int(kv_token_bytes)
            self._host_budget = int(host_budget_bytes)

    @property
    def mbu_driven(self) -> bool:
        """Whether roofline-position clauses are live (a resolved
        non-zero peak bandwidth)."""
        return self._hbm_gbps > 0

    # -- decision plumbing ----------------------------------------------

    def _ewma(self, prev: float | None, x: float) -> float:
        a = self.config.ewma_alpha
        return x if prev is None else (1 - a) * prev + a * x

    def _decide(self, knob: str, value: float | int) -> None:
        """Record one knob decision: gauge + stats mirror always, a
        counter bump + flight event on CHANGES only (steady state is
        silent, like spec_flip events). Caller holds the lock."""
        prev = self._last[knob]
        _M_VALUE.labels(knob=knob).set(float(value))
        if prev == value:
            return
        self._last[knob] = value
        self._decisions[knob] += 1
        if knob in ("chunk", "depth"):
            # A chunk/depth move changes the very thing a rounds
            # stretch measures (and the first use of a fresh width
            # carries its jit): poison the open stretch so the arms'
            # rates never absorb another knob's transition.
            self._stretch_dirty = True
        _M_DECISIONS.labels(knob=knob).inc()
        # Lazy import mirrors continuous.py's _flight usage: control is
        # imported by serving/__init__ consumers that may not want the
        # whole flight module at import time.
        from llm_consensus_tpu.serving import flight as _flight

        _flight.flight_recorder().record(
            "autotune",
            time.perf_counter(),
            knob=knob,
            value=value,
            prev=prev,
        )

    # -- signal feeds (batcher instrumentation sites) -------------------

    def note_overhead(self, seconds: float) -> None:
        """One un-overlapped sched-overhead observation (the same
        number gateway_sched_overhead_seconds observes)."""
        with self._lock:
            self._ovh_ewma = self._ewma(self._ovh_ewma, seconds)

    def note_program(self, kind: str, cost: dict | None, dur: float) -> None:
        """One fetched program's modeled cost + measured window (the
        _mbu_account site). Decode-kind programs feed the roofline
        position: modeled weight fraction and — with a peak bound —
        the decode-MBU EWMA."""
        with self._lock:
            self._dur_ewma = self._ewma(self._dur_ewma, dur)
            if cost is None or kind not in ("decode", "fused"):
                return
            hbm = max(1, cost["hbm_bytes"])
            kv_bytes = (
                cost["kv_read_tokens"] + cost["kv_write_tokens"]
            ) * self._kv_token_bytes
            self._wf_ewma = self._ewma(
                self._wf_ewma, max(0.0, hbm - kv_bytes) / hbm
            )
            if self._hbm_gbps > 0 and dur > 0:
                self._mbu_ewma = self._ewma(
                    self._mbu_ewma, hbm / dur / (self._hbm_gbps * 1e9)
                )

    def note_spec_round(self, samples: list[tuple[int, int, int]]) -> None:
        """Per-row acceptance from one fetched spec program:
        ``(group_key, accepted, k)`` triples (group key = the row's
        first prefix page — the GroupTracker bucket key). Feeds the
        per-group EWMAs and ends a probe window that measured well."""
        cfg = self.config
        with self._lock:
            for key, accepted, k in samples:
                ewma, n = self._accept.get(key, (None, 0))
                self._accept[key] = (
                    self._ewma(ewma, accepted / max(1, k)),
                    n + 1,
                )
                if len(self._accept) > self._accept_max:
                    self._accept.pop(next(iter(self._accept)))
            if self._probe_left > 0:
                self._probe_left -= 1
                if any(
                    accepted >= k
                    or self._accept.get(key, (0.0, 0))[0]
                    >= cfg.accept_high
                    for key, accepted, k in samples
                ):
                    # The probe found acceptance again (a fully-
                    # accepted window, or the EWMA recovered): stay
                    # engaged — spec_k_for regrows toward full k as
                    # the EWMAs climb past accept_low.
                    self._spec_engaged = True
                    self._probe_left = 0
                elif self._probe_left == 0 and not self._spec_engaged:
                    # The probe ran out still rejecting: the knob
                    # value must read disengaged again (spec_k_for
                    # recorded 1 for the probe windows; leaving that
                    # standing would contradict the "0 = disengaged"
                    # gauge contract).
                    self._decide("spec_k", 0)

    def note_plain_window(self) -> None:
        """One dispatched PLAIN decode window while a draft is
        configured — the probe clock of a disengaged controller
        (counted at the dispatch site, so idle loop iterations never
        advance it)."""
        cfg = self.config
        with self._lock:
            if self._spec_engaged or self._probe_left > 0:
                return
            self._plain_windows += 1
            if self._plain_windows >= cfg.spec_probe_every:
                # Arm a bounded probe: the next iterations re-engage
                # speculation at the k=1 floor to re-measure
                # acceptance (note_spec_round counts the windows and
                # re-engages for real if they accept).
                self._plain_windows = 0
                self._probe_left = 4

    # -- decisions ------------------------------------------------------

    def spec_gate(self, group_keys: list[int]) -> bool:
        """Whether speculation should run this iteration (consulted
        next to ``_spec_ok``; the flip composes with the PR-9 drain
        rules). ``group_keys``: the decoding rows' group keys."""
        cfg = self.config
        if not cfg.tune_spec_k:
            return True
        with self._lock:
            if not self._spec_engaged:
                # Disengaged: run only armed probe windows.
                return self._probe_left > 0
            known = [
                self._accept[k] for k in group_keys if k in self._accept
            ]
            if (
                known
                and len(known) == len(group_keys)
                and all(n >= cfg.accept_min_samples for _, n in known)
                and all(e < cfg.accept_disengage for e, _ in known)
            ):
                # Every group rejects: stop paying draft+verify (the
                # k=1 floor still costs a draft scan + 2-wide verify).
                self._spec_engaged = False
                self._probe_left = 0
                self._plain_windows = 0
                self._decide("spec_k", 0)
                return False
            return True

    def spec_k_for(self, group_keys: list[int], k_max: int) -> int:
        """Effective k for ONE speculative dispatch: the max of the
        decoding groups' recommendations over the menu ``{1, k_max}``
        (a single high-acceptance group keeps the full window — the
        program-wide k can't help one group without paying for all,
        and the winner is the one with something to gain)."""
        cfg = self.config
        if not cfg.tune_spec_k or k_max <= 1:
            return k_max
        with self._lock:
            if self._probe_left > 0 and not self._spec_engaged:
                # Probe windows run at the k=1 floor: cheapest way to
                # re-measure acceptance.
                self._decide("spec_k", 1)
                return 1
            rec = 1
            for key in group_keys:
                ewma, n = self._accept.get(key, (None, 0))
                if ewma is None or n < cfg.accept_min_samples:
                    rec = k_max  # optimistic start
                    break
                if ewma >= cfg.accept_low:
                    rec = k_max
                    break
            self._decide("spec_k", rec)
            return rec

    def note_rounds_window(
        self,
        arm: int,
        tokens: int,
        clean: bool = True,
        now: float | None = None,
    ) -> None:
        """One fetched window while rounds are arbitrated: ``arm`` is
        the dispatched window length, ``tokens`` its total emitted
        tokens. Feeds the active regime's open STRETCH — consecutive
        fetches measured on the note-to-note wall clock, which tiles
        the burst and therefore captures everything a regime costs
        (device rounds, host gaps, prefill interleave, its own forced
        tails) — and a complete stretch folds into the regime's
        decayed rate and re-decides. ``clean`` = False marks a window
        whose length was FORCED (near-stop cap, unscreenable-stop
        collapse): its tokens and time still belong to the running
        regime, it just isn't evidence that the OTHER arm ran.
        ``now``: test seam for the wall clock."""
        cfg = self.config
        if now is None:
            now = time.perf_counter()
        with self._lock:
            n = self._rate_n.get(arm, 0)
            self._rate_n[arm] = n + 1
            if n == 0:
                # The arm's first window EVER carries its jit compile
                # (clean or not — a near-stop cap can be the first
                # rounds(1) window): discard it AND restart the
                # stretch so the compile seconds never enter a rate.
                self._stretch_t0 = None
                self._last_note_t = now
                return
            prev_note = self._last_note_t
            if (
                prev_note is not None
                and now - prev_note > cfg.rounds_stretch_gap_s
            ):
                # Idle gap (quiesced batcher between bursts): fold
                # what the cut stretch measured — ending at the LAST
                # pre-gap fetch, so the idle never counts as regime
                # time — then re-anchor.
                self._fold_stretch(prev_note)
                self._stretch_t0 = None
            self._last_note_t = now
            if self._regime_arm is None:
                self._regime_arm = arm if clean else None
            if self._stretch_t0 is None:
                # Anchor at this fetch; tokens accumulate from the
                # NEXT one (rate = tokens after anchor / time since).
                self._stretch_t0 = now
                self._stretch_tokens = 0.0
                self._stretch_windows = 0
                return
            self._stretch_tokens += tokens
            self._stretch_windows += 1
            if self._stretch_windows >= cfg.rounds_stretch_windows:
                self._fold_stretch(now)

    def _fold_stretch(self, end: float) -> None:
        """Fold the open stretch into its regime arm's decayed rate
        and re-decide the regime (caller holds the lock). A stretch
        below ``rounds_stretch_min`` windows is discarded — too
        little signal to rank arms on."""
        cfg = self.config
        cur = self._regime_arm
        if (
            self._stretch_t0 is None
            or cur is None
            or self._stretch_windows < cfg.rounds_stretch_min
        ):
            return
        if self._stretch_dirty:
            # The stretch absorbed a chunk/depth transition (or the
            # jit of a freshly-steered width) — it measures the
            # transition, not the arm. Discard it and measure the
            # next one clean; the regime stands.
            self._stretch_dirty = False
            self._stretch_t0 = end
            self._stretch_tokens = 0.0
            self._stretch_windows = 0
            return
        span = end - self._stretch_t0
        if span > 0:
            decay = 1.0 - cfg.ewma_alpha
            self._rate_tok[cur] = (
                self._rate_tok.get(cur, 0.0) * decay
                + self._stretch_tokens
            )
            self._rate_sec[cur] = (
                self._rate_sec.get(cur, 0.0) * decay + span
            )
        self._stretch_t0 = end
        self._stretch_tokens = 0.0
        self._stretch_windows = 0
        other = 1 if cur != 1 else self._r_max_seen
        if other is None:
            return
        if self._arm_rate(other) is None:
            # Calibration: measure the unmeasured arm next.
            self._regime_arm = other
            return
        r_cur, r_oth = self._arm_rate(cur), self._arm_rate(other)
        if r_cur is None:
            return
        # Incumbency hysteresis: the challenger needs a real margin,
        # not a lucky stretch (see rounds_flip_margin).
        best = (
            other
            if r_oth > r_cur * (1.0 + cfg.rounds_flip_margin)
            else cur
        )
        if self._rounds_probing:
            # A probe stretch just folded: if it lost (the other arm
            # still wins), back off the probe cadence — a steady
            # workload must not pay a recurring probe tax.
            self._rounds_probing = False
            if best != cur:
                self._rounds_probe_backoff = min(
                    cfg.probe_backoff_max,
                    self._rounds_probe_backoff * 2,
                )
            else:
                self._rounds_probe_backoff = 1
        self._stretches_since_probe += 1
        if (
            best == cur
            and self._stretches_since_probe
            >= cfg.rounds_probe_stretches * self._rounds_probe_backoff
        ):
            # Periodic probe of the losing arm: a shifted workload
            # (RTT appearing, KV-bound growth) must be able to flip
            # the choice back.
            self._stretches_since_probe = 0
            self._rounds_probing = True
            self._regime_arm = other
        else:
            self._regime_arm = best

    def _arm_rate(self, arm: int | None) -> float | None:
        """The arm's decayed stretch tokens/sec (None before any full
        stretch)."""
        if arm is None:
            return None
        sec = self._rate_sec.get(arm, 0.0)
        if sec <= 0:
            return None
        return self._rate_tok.get(arm, 0.0) / sec

    def rounds_cap(self, max_remaining: int, r_max: int) -> int:
        """Window cap for ONE plain multi-round dispatch, menu
        ``{1, r_max}`` (exactly the trace family _stop_plan already
        compiles — adaptive R adds ZERO traces).

        Decision order: (1) near-stop — the whole batch is about to
        retire (``max_remaining < r_max``): 1, masked tail rounds
        would decode nothing while stretching retirement lag. (2) the
        active measured REGIME (see note_rounds_window — stretch-
        level realized throughput arbitrates: a host-RTT-dominated
        chip measures the R regime faster, a dispatch-cheap box
        measures it slower; ClusterFusion++'s "the right R is a
        function of where the workload sits on the roofline", decided
        by where it actually sits). (3) cold start: r_max — the
        configured intent — unless the modeled-MBU prior is live and
        says KV-dominated with the batch near its budget."""
        cfg = self.config
        if not cfg.tune_rounds or r_max <= 1:
            return r_max
        with self._lock:
            self._r_max_seen = r_max
            if max_remaining < r_max:
                self._decide("rounds", 1)
                return 1
            choice = self._regime_arm
            if choice is None:
                choice = r_max
                if (
                    self.mbu_driven
                    and self._wf_ewma is not None
                    and self._wf_ewma < cfg.weight_bound_frac
                    and max_remaining < 2 * r_max
                ):
                    # Cold-start MBU prior: KV-dominated near the
                    # budget — the weight-amortization win is gone.
                    choice = 1
            self._decide("rounds", choice)
            return choice

    def chunk_for(self, bucket: int, full: int) -> int:
        """Effective prefill-chunk width for ONE admission, menu
        ``{full, full // 2}`` (full//2 only when it still divides the
        bucket — the unshared-footprint invariant — and is a real
        width). At most one extra compiled (chunk, bucket) trace per
        bucket, ever: the no-recompile-storm bound.

        Halving is an MBU-DRIVEN decision: it engages only when the
        host loop is fully hidden AND the measured decode/fused
        program MBU says the lane is bandwidth-STARVED (< 0.5 of the
        resolved peak) — a half-width chunk then bounds the fused
        window's decode stall at no bandwidth cost. Without a
        resolved peak the configured width stands: halving doubles
        the per-prompt program count, and "host hidden" alone is no
        evidence that's free (the overhead signal cannot see
        per-program fixed cost that is ALREADY overlapped; halving on
        overhead evidence alone measured ~10% tok/s loss on the CPU
        smoke). Hysteresis: once halved, full width returns when
        overhead RE-APPEARS (> overhead_high_s) or the lane stops
        measuring starved (>= 0.6) — never at the engage threshold
        itself, so the choice cannot flap on a boundary-riding EWMA.
        """
        cfg = self.config
        half = full // 2
        if (
            not cfg.tune_chunk
            or half < 1
            or full % 2
            or bucket % half
        ):
            return full
        with self._lock:
            ovh = self._ovh_ewma
            starved = (
                self.mbu_driven
                and self._mbu_ewma is not None
                and self._mbu_ewma < 0.5
            )
            if self._chunk_half:
                if (
                    (ovh is not None and ovh > cfg.overhead_high_s)
                    or not self.mbu_driven
                    or self._mbu_ewma is None
                    or self._mbu_ewma >= 0.6
                ):
                    self._chunk_half = False
            elif (
                ovh is not None
                and ovh <= cfg.overhead_low_s
                and starved
            ):
                self._chunk_half = True
            choice = half if self._chunk_half else full
            self._decide("chunk", choice)
            return choice

    def depth_for(self, cfg_depth: int) -> int:
        """Effective pipeline depth this iteration, within
        ``[1, cfg_depth]``. Overhead visible => the configured depth
        (hide it). Overhead at ~0 => periodically PROBE one lower for
        ``depth_probe_len`` dispatches; commit if it stays hidden,
        revert the moment it re-appears. Probing exists because a
        fully overlapped loop observes 0 by construction — the signal
        must be re-exposed to be re-measured."""
        cfg = self.config
        if not cfg.tune_depth or cfg_depth <= 1:
            return cfg_depth
        with self._lock:
            if self._depth_eff is None:
                self._depth_eff = cfg_depth
            ovh = self._ovh_ewma
            if ovh is not None and ovh > cfg.overhead_high_s:
                # Host visible: use everything the config allows. A
                # probe that ran into this loses — back its cadence
                # off (the workload keeps proving it needs depth).
                if self._probe_depth is not None:
                    self._depth_probe_backoff = min(
                        cfg.probe_backoff_max,
                        self._depth_probe_backoff * 2,
                    )
                self._probe_depth = None
                self._depth_eff = cfg_depth
                self._decide("depth", cfg_depth)
                return cfg_depth
            if self._probe_depth is not None:
                self._probe_dispatches += 1
                if self._probe_dispatches >= cfg.depth_probe_len:
                    # Probe survived (a re-appearing overhead would
                    # have taken the revert branch above): commit,
                    # and reset the backoff — a committed probe won.
                    self._depth_eff = self._probe_depth
                    self._probe_depth = None
                    self._depth_probe_backoff = 1
                    self._decide("depth", self._depth_eff)
                return (
                    self._probe_depth
                    if self._probe_depth is not None
                    else self._depth_eff
                )
            self._since_probe += 1
            if (
                self._depth_eff > 1
                and ovh is not None
                and ovh <= cfg.overhead_low_s
                and self._since_probe
                >= cfg.depth_probe_every * self._depth_probe_backoff
            ):
                self._since_probe = 0
                self._probe_depth = self._depth_eff - 1
                self._probe_dispatches = 0
                self._decide("depth", self._probe_depth)
                return self._probe_depth
            self._decide("depth", self._depth_eff)
            return self._depth_eff

    # -- restore-batch sizing (host-tier promotion) ---------------------

    def steer_restore_cap(self, cap: int | None) -> None:
        """Fleet-steered override of the restore-batch ceiling (PR 19):
        the fleet controller narrows or widens ``restore_batch_max``
        from fleet-level restore-debt pressure without touching the
        per-replica overhead steering below it. None clears the
        override (back to the configured cap)."""
        with self._lock:
            self._restore_cap = (
                None if cap is None else max(1, int(cap))
            )

    def restore_batch(self) -> int:
        """Pages ``_restore_step`` may promote THIS iteration, within
        ``[1, restore_batch_max]`` — steered by the same un-overlapped
        overhead EWMA as chunk/depth (see ControlConfig). Unknown
        overhead (cold start) takes the full batch: before any decode
        dispatch the loop has nothing to stall. A fleet-steered cap
        (``steer_restore_cap``) bounds the ceiling from above."""
        cfg = self.config
        cap = max(1, cfg.restore_batch_max)
        with self._lock:
            if self._restore_cap is not None:
                cap = min(cap, self._restore_cap)
        if not cfg.tune_restore_batch or cap <= 1:
            if cap != max(1, cfg.restore_batch_max):
                with self._lock:
                    self._decide("restore_batch", cap)
            return cap
        with self._lock:
            ovh = self._ovh_ewma
            if ovh is None or ovh > cfg.overhead_high_s:
                value = cap
            elif ovh <= cfg.overhead_low_s:
                value = 1
            else:
                # Between the hysteresis edges: half the cap — the
                # host is partly visible, so some amortization pays
                # without a full-batch stall.
                value = max(1, cap // 2)
            self._decide("restore_batch", value)
            return value

    # -- restore pacing (fleet preempt hook) ----------------------------

    def note_preempt_demote(self, bytes_: int) -> None:
        """Pages demoted by router-requested preemption (modeled
        bytes) — the debt side of restore pacing."""
        with self._lock:
            self._restore_debt += int(bytes_)

    def note_restore(self, bytes_: int) -> None:
        """Pages promoted back from the host tier — debt repaid."""
        with self._lock:
            self._restore_debt = max(0, self._restore_debt - int(bytes_))

    def restore_pacing_ok(self, pages: int, page_bytes: int) -> bool:
        """Whether the preempt hook may demote ``pages`` more pages:
        the modeled restore debt this would add must stay under
        ``restore_debt_frac`` x the host tier's budget. Past it,
        preemption is demoting chains faster than the one-page-per-
        iteration restore path can repay — further grants would
        thrash the tier, so classic backpressure resumes."""
        with self._lock:
            if self._host_budget <= 0:
                return True
            cap = self.config.restore_debt_frac * self._host_budget
            return self._restore_debt + pages * page_bytes <= cap

    @property
    def restore_debt_bytes(self) -> int:
        with self._lock:
            return self._restore_debt

    # -- observability --------------------------------------------------

    def group_acceptance(self, key: int) -> float | None:
        """The group's acceptance EWMA (None = no samples yet)."""
        with self._lock:
            hit = self._accept.get(key)
            return hit[0] if hit else None

    def stats(self) -> dict:
        """The batcher stats() mirror of gateway_autotune_* — last
        decided value per knob (-1 = no decision yet) and the decision
        counters (lockstep tested)."""
        with self._lock:
            out = {
                f"autotune_{k}": (
                    self._last[k] if self._last[k] is not None else -1
                )
                for k in KNOBS
            }
            out.update(
                {f"autotune_decisions_{k}": self._decisions[k] for k in KNOBS}
            )
            out["autotune_spec_engaged"] = int(self._spec_engaged)
            out["autotune_restore_debt_bytes"] = self._restore_debt
            out["autotune_restore_cap"] = (
                self._restore_cap if self._restore_cap is not None else -1
            )
            return out
