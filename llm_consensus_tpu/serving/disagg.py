"""Disaggregated prefill/decode serving: role-specialized replicas
(PR 16, with :mod:`llm_consensus_tpu.serving.remote_store`).

TPLA (PAPERS.md) argues prefill and decode sit at different roofline
points and want different shardings; "Move the Query, Not the Cache"
supplies the placement rule. The repo already has every seam this
needs — the fleet's shared page store with scoped chain keys (PR 14),
the export/restore transport, the PrefixRouter, per-replica
controllers (PR 15). This module adds the ROLE split on top:

- ``FleetConfig(role=...)`` — ``"mixed"`` (the pre-PR-16 fleet),
  ``"prefill"``/``"decode"`` fleet-wide, or a per-replica tuple like
  ``("prefill", "decode")``.
- **Prefill replicas** run admission + chunked prefill only:
  :func:`role_config` pins ``spec_decode=False`` and
  ``decode_rounds=1`` (speculation and R-round windows are decode-
  phase machinery — a replica that hands chains off right after the
  header lands never amortizes them), while chunk width and mesh
  shape stay per-replica levers (``--serve-prefill-chunk``,
  ``meshes=`` — an mp-heavy mesh suits the prefill roofline, a
  dp-heavy one suits decode; the PR-15 controller then tunes each
  replica toward ITS role's roofline instead of compromise settings).
- **Decode replicas** keep the fleet's shared live config (spec +
  R-round windows) and stream tokens; the router routes real requests
  to decode-capable replicas ONLY — decode phase by prefix affinity,
  the prefill phase by load (the least-loaded prefill replica takes
  each warm-up).
- :class:`HandoffCoordinator` is the seam between them: the first
  request of a cold chain triggers a WARM request (``max_new_tokens=1``)
  on a prefill replica, then exports the finished chain through the
  fleet page store via the PR-14 export path; the decode replica's
  admission host-hits and restores the header bit-identically, so the
  panel's text is byte-identical to a mixed-role fleet (the PR-4
  restore contract) with ZERO header pages re-prefilled on the decode
  side. Each completed handoff counts ``gateway_role_handoffs_total``
  and records a ``handoff`` flight event.

Blocking discipline (the fleet's standing rule): the coordinator
waits for the warm prefill + export ONLY off the asyncio event loop
(bench/test threads). On the gateway loop the handoff runs on a
daemon thread — the triggering request itself goes cache-cold on its
decode replica (correct, just not accelerated) and the panel mates
behind it restore once the export lands, exactly the
``rebalance_export_wait_s`` trade.

Cross-PROCESS disaggregation is this plus
``ReplicaSet(host_store=RemotePageStore(...))``: the store the
export lands in and the decode admission restores from is then the
remote authoritative tier, and the handoff crosses process (or host)
boundaries without any code here changing.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import replace

from llm_consensus_tpu.server.metrics import (
    HANDOFF_SECONDS as _M_HANDOFF_SECONDS,
)
from llm_consensus_tpu.server.metrics import ROLE_HANDOFFS as _M_HANDOFFS
from llm_consensus_tpu.serving import flight as _flight
from llm_consensus_tpu.serving.continuous import ContinuousConfig
from llm_consensus_tpu.utils import tracing as _tracing

log = logging.getLogger(__name__)

__all__ = ["ROLES", "resolve_roles", "role_config", "HandoffCoordinator"]

#: Valid replica roles (the ``role`` entry in fleet stats()
#: ``per_replica`` — the per-ROLE split of the process-global,
#: last-writer-wins autotune families).
ROLES = ("prefill", "decode", "mixed")


def resolve_roles(role, k: int) -> tuple[str, ...]:
    """``FleetConfig.role`` -> one role per replica. A string applies
    fleet-wide; a tuple/list names each replica's role. At least one
    replica must be decode-capable (``decode`` or ``mixed``) — a
    prefill-only fleet could never stream a token."""
    roles = (role,) * k if isinstance(role, str) else tuple(role)
    if len(roles) != k:
        raise ValueError(
            f"role tuple has {len(roles)} entries for {k} replicas"
        )
    for r in roles:
        if r not in ROLES:
            raise ValueError(f"unknown replica role {r!r} (use {ROLES})")
    if all(r == "prefill" for r in roles):
        raise ValueError(
            "at least one replica must be decode-capable "
            "('decode' or 'mixed'): a prefill-only fleet cannot "
            "stream tokens"
        )
    return roles


def role_config(config: ContinuousConfig, role: str) -> ContinuousConfig:
    """The replica's effective config for ``role``. Decode/mixed
    replicas SHARE the fleet's live config instance (the knob-flip
    lever stays fleet-wide); a prefill replica gets its own copy with
    the decode-phase machinery off. None of the replaced fields enter
    the PR-14 store-key scope (config dims + page size + pool dtype +
    weights fingerprint), so roled replicas restore each other's pages
    by construction."""
    if role != "prefill":
        return config
    return replace(config, spec_decode=False, decode_rounds=1)


class HandoffCoordinator:
    """Prefill→decode chain handoffs for one roled :class:`ReplicaSet`.

    ``ensure_prefilled`` is consulted on the fleet submit path for
    every request whose prompt has at least one full header page: a
    chain that is already resident on a decode-capable replica (or
    already restorable from the fleet store) passes through untouched;
    a COLD chain is warmed on the least-loaded prefill replica and
    exported into the store first. A bounded-TTL dedup table keyed by
    the chain's first page run (the pending-route-hint convention)
    keeps a panel burst from warming the same header once per mate.
    """

    #: Dedup entries expire after this long — past it the chain is
    #: either registry-resident on its decode home (the probe short-
    #: circuits) or evicted everywhere and worth re-warming.
    DEDUP_TTL_S = 60.0
    DEDUP_MAX = 1024

    def __init__(self, fleet):
        self.fleet = fleet  # ReplicaSet (import cycle: duck-typed)
        self._lock = threading.Lock()
        self._seen: dict[tuple, float] = {}
        #: Completed handoffs (stats() mirror of
        #: ``gateway_role_handoffs_total``'s increments from this
        #: fleet; the Prometheus family is process-global), plus the
        #: claim-to-exported latency mirror of
        #: ``gateway_handoff_seconds`` (PR 17, lockstep tested).
        self.handoffs = 0
        self.handoff_seconds_sum = 0.0
        self.handoff_seconds_count = 0

    def _prefill_candidates(self) -> list[int]:
        healthy = set(self.fleet.router.healthy())
        return [
            i
            for i, r in enumerate(self.fleet.roles)
            if r == "prefill" and i in healthy
        ]

    def _decode_candidates(self) -> list[int]:
        return [
            i
            for i, r in enumerate(self.fleet.roles)
            if r != "prefill"
        ]

    def _dedup_claim(self, chain) -> bool:
        """True when THIS caller claims the chain (first mate of the
        burst); False when a fresh claim already exists."""
        now = time.monotonic()
        key = chain[0]
        with self._lock:
            dl = self._seen.get(key)
            if dl is not None and now < dl:
                return False
            while len(self._seen) >= self.DEDUP_MAX:
                self._seen.pop(next(iter(self._seen)))
            self._seen[key] = now + self.DEDUP_TTL_S
            return True

    @staticmethod
    def _off_loop() -> bool:
        import asyncio

        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return True
        return False

    def ensure_prefilled(self, prompt: str, ids, chain, trace=None) -> bool:
        """Warm-and-export a cold chain through a prefill replica.
        Returns True when a handoff was INITIATED (completed inline
        off-loop; running on a daemon thread on the event loop).
        No-ops — cheap probes only — when the chain is too short, has
        a live claim, is already resident on a decode replica, or is
        already restorable from the fleet store.

        ``trace`` (PR 20): the owning request's trace. The handoff
        worker runs UNDER it (``use_trace``), so the claim→export→
        restore window lands as a ``handoff`` span on the request's
        trace, the store client's ops inside it carry the id on the
        wire, and the ``handoff`` flight event joins the merged fleet
        timeline by the same id."""
        fleet = self.fleet
        page = fleet.config.page_size
        if not chain or len(ids) <= page:
            return False
        prefillers = self._prefill_candidates()
        if not prefillers:
            return False
        if not self._dedup_claim(chain):
            return False
        # Resident or restorable already? Probe decode-capable
        # replicas (registry = resident home; host extension = the
        # store can restore it — either way the warm-up buys nothing).
        for i in self._decode_candidates():
            p = fleet.batchers[i].prefix_probe(ids)
            if p["registry_tokens"] >= page or p["host_tokens"] >= page:
                return False
        src = min(
            prefillers, key=lambda i: fleet.batchers[i].load_cost()
        )
        # The prefill phase routes by LOAD (the role split's routing
        # rule): affinity is a decode-phase concern — a warm-up runs
        # once per chain, so there is no prefix to re-use on the
        # prefill side.
        t0 = time.perf_counter()
        try:
            fut = fleet.batchers[src].submit(
                prompt, max_new_tokens=1, temperature=0.0
            )
        except (RuntimeError, ValueError) as e:
            log.warning("handoff warm-up submit failed: %s", e)
            return False
        wait_s = fleet.fleet_config.handoff_wait_s
        streamed = (
            fleet.fleet_config.handoff_stream and wait_s > 0
        )
        # Streamed handoff (PR 17): issue the STREAMING export NOW —
        # while the warm-up prefill is still computing the chain's
        # tail, the export step is already spilling each chunk's pages
        # as they flip ready, so the store (the wire, when it is
        # remote) transfers OVERLAP the prefill instead of serializing
        # after it. The non-streamed path (handoff_stream=False, the
        # PR-16 shape and the bench A/B's baseline) exports the whole
        # chain in one pass after the warm-up completes.
        ev_stream = None
        if streamed:
            ev_stream = fleet.batchers[src].request_export(
                ids, stream_until=time.monotonic() + wait_s
            )
        deadline = time.monotonic() + wait_s

        def finish() -> None:
            try:
                # The handoff worker runs under the owning request's
                # trace (PR 20): store ops issued from THIS thread
                # attach their spans here and carry the id on the wire.
                with _tracing.use_trace(trace):
                    fut.result(timeout=wait_s)
                    if ev_stream is not None:
                        ev = ev_stream
                    else:
                        ev = fleet.batchers[src].request_export(ids)
                    if not ev.wait(
                        max(0.0, deadline - time.monotonic())
                    ):
                        log.warning(
                            "handoff export from replica %d did not "
                            "land within %.1fs; decode side may "
                            "re-prefill",
                            src,
                            wait_s,
                        )
                        return
            except Exception as e:  # noqa: BLE001 - degrade, never wedge
                log.warning("handoff via replica %d failed: %s", src, e)
                return
            dur = time.perf_counter() - t0
            _M_HANDOFFS.inc()
            # Claim-to-exported latency: the window the decode side
            # would otherwise re-prefill in. The streamed-vs-sync
            # bench A/B reads this family's delta.
            _M_HANDOFF_SECONDS.observe(dur)
            with self._lock:
                self.handoffs += 1
                self.handoff_seconds_sum += dur
                self.handoff_seconds_count += 1
            if trace is not None:
                trace.add_span(
                    "handoff", t0, dur, src=src, chain_pages=len(chain)
                )
            _flight.flight_recorder().record(
                "handoff",
                t0,
                dur,
                trace_id=_tracing.trace_id_of(trace),
                src=src,
                chain_pages=len(chain),
                streamed=streamed,
            )

        if wait_s > 0 and self._off_loop():
            finish()
        else:
            # Gateway event loop: the warm-up + export completes on a
            # daemon thread — the triggering request goes cache-cold
            # on its decode replica, its panel mates restore.
            threading.Thread(
                target=finish, name="disagg-handoff", daemon=True
            ).start()
        return True
