"""Prefix-affinity replica fleet: N batcher replicas behind one gateway.

PR 13 finished scale-UP (every serving feature engages on dp×mp
meshes); this module is the scale-OUT half (ROADMAP item 2): a
:class:`ReplicaSet` owns K :class:`~llm_consensus_tpu.serving.
continuous.ContinuousBatcher` replicas — in-process first, each with
its own page pool, prefix registry, and jit caches (optionally its own
mesh) over ONE shared parameter tree — and a :class:`PrefixRouter`
places every request where its KV already lives:

- **Prefix affinity.** The router fingerprints each request's
  page-aligned prompt-prefix chain (the same
  :func:`~llm_consensus_tpu.models.paged_cache.prefix_chain_key`
  identity the registry and host tier key by) and probes every
  replica's registry/host-tier READ-ONLY
  (:meth:`ContinuousBatcher.prefix_probe`) for the longest resident
  match. Consensus panels re-send the same huge header every
  propose/evaluate/refine round, so "requests sharing a
  radix-registry chain land where the pages already live" is the
  COMMON case — the shared header prefills once FLEET-wide, not once
  per replica. "Move the Query, Not the Cache" (PAPERS.md) is the
  routing thesis: ship the request to the KV, never the KV to the
  request.
- **Least-modeled-cost fallback.** A request with no resident chain
  anywhere goes to the replica with the least OUTSTANDING MODELED
  WORK (:meth:`ContinuousBatcher.load_cost` — the PR-10 cost model's
  KV terms integrated over every admitted request's remaining
  schedule), not the shortest request queue: a 32k-context request is
  not one unit of work.
- **Preempt-to-host-tier instead of 429s.** The ReplicaSet creates ONE
  fleet-scoped :class:`~llm_consensus_tpu.serving.offload.
  HostPageStore` (thread-safe since PR 14; keys carry each replica's
  config/weights scope) shared by every replica. Under overload the
  gateway's admission controller consults
  :meth:`ReplicaSet.preempt_for_admission` before shedding: while any
  replica still holds demotable resident chains AND the shared tier
  has headroom, the victim's lowest-priority chains demote to host
  RAM (the PR-4 eviction path, router-requested) and the request is
  ADMITTED past the queue bound — an overload storm degrades to
  restore latency, not lost work. Shedding resumes when the host tier
  is exhausted too, or when the offered traffic registers no chains
  at all (nothing to ever preempt => keep classic backpressure).
- **Rebalancing.** When the affinity owner is congested (its batcher
  queue deeper than ``FleetConfig.rebalance_waiting``) and another
  healthy replica is less loaded, the owner EXPORTS the chain's ready
  pages through the shared store (:meth:`ContinuousBatcher.
  request_export` — a spill, not an eviction: the chain stays hot at
  the owner) and the request re-homes; the destination's admission
  host-hits and restores the chain remotely.
- **Per-replica readiness.** :meth:`ReplicaSet.heartbeat` aggregates
  every replica's serving-loop heartbeat (one wedged replica flips the
  gateway's ``/readyz`` and is reported by index), and the router
  stops routing to stale/dead replicas while any healthy one remains.

Role-specialized since PR 16 (:mod:`llm_consensus_tpu.serving.disagg`):
``FleetConfig.role`` splits the fleet into prefill-heavy and
decode-heavy replicas — prefill replicas warm cold chains and hand
them through the shared store (the export path), decode replicas
restore and stream; the router routes real requests to decode-capable
replicas only. And the shared store itself may be REMOTE
(:mod:`llm_consensus_tpu.serving.remote_store`): pass
``ReplicaSet(host_store=RemotePageStore(...))`` and the same
preempt/export/restore transport crosses process and host boundaries.

Threading: ``submit``/``route`` run on caller threads (the gateway
event loop, tests); probes take each batcher's admission lock
read-only; preempt/export are enqueued REQUESTS the batcher worker
executes (device transfers must not race dispatch-time buffer
donation). The fleet itself keeps only trivially-locked counters.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass

from llm_consensus_tpu.backends import base as _backend_base
from llm_consensus_tpu.engine.tokenizer import ByteTokenizer, Tokenizer
from llm_consensus_tpu.models.configs import ModelConfig
from llm_consensus_tpu.models.paged_cache import prefix_chain_key
from llm_consensus_tpu.server.metrics import (
    REPLICA_PREEMPTIONS as _M_PREEMPTIONS,
)
from llm_consensus_tpu.server.metrics import (
    REPLICA_PREFIX_HIT_RATE as _M_HIT_RATE,
)
from llm_consensus_tpu.server.metrics import (
    REPLICA_PROGRAMS as _M_PROGRAMS,
)
from llm_consensus_tpu.server.metrics import (
    REPLICA_ROUTED as _M_ROUTED,
)
from llm_consensus_tpu.server.metrics import (
    REPLICA_SHARED_STORE_BYTES as _M_STORE_BYTES,
)
from llm_consensus_tpu.server.metrics import (
    FLEET_REPLICAS as _M_FLEET_REPLICAS,
)
from llm_consensus_tpu.server.metrics import (
    FLEET_SCALE as _M_FLEET_SCALE,
)
from llm_consensus_tpu.server.metrics import (
    ROUTER_WEIGHT as _M_ROUTER_WEIGHT,
)
from llm_consensus_tpu.serving import flight as _flight
from llm_consensus_tpu.utils import tracing as _tracing
from llm_consensus_tpu.serving.continuous import (
    ContinuousBatcher,
    ContinuousConfig,
)
from llm_consensus_tpu.serving.offload import HostPageStore

log = logging.getLogger(__name__)

__all__ = ["FleetConfig", "PrefixRouter", "ReplicaSet", "FleetBackend"]

#: Routing reasons (the ``reason`` label of
#: ``gateway_replica_routed_total`` and the stats() mirror keys).
ROUTE_REASONS = ("prefix", "load", "rebalance", "random")


@dataclass
class FleetConfig:
    #: Batcher replicas behind the one gateway (``serve --replicas``).
    replicas: int = 2
    #: ``"prefix"`` — affinity routing (the subsystem's point).
    #: ``"random"`` — round-robin, the bench leg's control: it
    #: deliberately ignores resident chains so the A/B isolates what
    #: affinity buys.
    policy: str = "prefix"
    #: Minimum RESIDENT full pages for an affinity claim: below it the
    #: match is noise (every prompt shares a BOS-ish page with
    #: something) and least-loaded placement wins.
    affinity_min_pages: int = 1
    #: The router stops routing to a replica whose serving-loop
    #: heartbeat is staler than this (wedged device call / dead loop)
    #: while any healthy replica remains — the same threshold shape as
    #: the gateway's ``/readyz`` probe.
    ready_stall_s: float = 10.0
    #: Rebalance trigger: when the affinity owner's batcher queue is
    #: deeper than this many requests and a less-loaded healthy
    #: replica exists, export the chain through the shared store and
    #: re-home the request. None = 4 × the batcher's ``max_slots`` —
    #: deep enough that a plain panel burst never scatters its mates.
    rebalance_waiting: int | None = None
    #: Pages demoted per router-requested preemption (one overflow
    #: moment frees about one admission's worth of pool pages).
    preempt_pages: int = 8
    #: How long an auto-rebalance waits for the owner's chain export
    #: to land in the shared store before re-homing the request. The
    #: export runs on the owner's worker at its next loop iteration
    #: (ms-scale even mid-burst); without the wait the destination's
    #: admission usually probes the store BEFORE the spill and
    #: re-prefills the whole chain. Applied ONLY off the asyncio
    #: event loop (the gateway path never blocks — its first re-homed
    #: mate goes cache-cold and the hinted mates behind it restore
    #: once the spill lands); bounded, and rebalances only fire at
    #: congestion moments. 0 = always fire-and-forget.
    rebalance_export_wait_s: float = 0.5
    #: Replica role split (PR 16, serving/disagg.py): ``"mixed"``
    #: (every replica runs both phases — the pre-PR-16 fleet),
    #: ``"prefill"``/``"decode"`` fleet-wide, or a per-replica tuple
    #: like ``("prefill", "decode")``. Prefill replicas warm cold
    #: chains and export them through the shared store; the router
    #: sends real requests to decode-capable replicas only.
    role: str | tuple = "mixed"
    #: Bound on a handoff's warm-prefill + export wait (covers the
    #: prefill replica's first-compile on a cold fleet). Applied ONLY
    #: off the asyncio event loop — on the gateway loop the handoff
    #: completes on a daemon thread instead (the same rule as
    #: rebalance_export_wait_s). 0 = always hand off asynchronously.
    handoff_wait_s: float = 60.0
    #: Streamed handoffs (PR 17): the coordinator issues the chain
    #: export as a STREAM alongside the warm-up prefill, so ready
    #: pages cross the (possibly remote) store wire while the tail is
    #: still computing. False restores the PR-16 sequential shape
    #: (prefill completes, then one whole-chain export) — the bench
    #: transport A/B's baseline.
    handoff_stream: bool = True
    #: Route-driven restore prefetch (PR 17): after the router picks a
    #: request's destination replica, speculatively stage the chain's
    #: host-store pages store->local on that replica (a side thread)
    #: so admission's restore plan finds them staged instead of paying
    #: a synchronous store round trip. Advisory only — a wrong or
    #: expired guess falls through to the normal get_run/recompute
    #: path (chain-keyed entries can never corrupt).
    prefetch: bool = True


class PrefixRouter:
    """Routing policy over a ReplicaSet's batchers. Stateless apart
    from a round-robin cursor; every decision re-probes live replica
    state, so evictions, restores, and retirements re-route the next
    request correctly with no cache-invalidation protocol."""

    #: Bound on the pending-route hint table (entries are tiny; the
    #: registry itself takes over once admissions land).
    RECENT_MAX = 1024
    #: Seconds a pending-route hint stays authoritative. It only needs
    #: to cover the submit→admission window of a burst; after that the
    #: owner's REGISTRY holds the chain and the live probe wins.
    RECENT_TTL_S = 30.0

    def __init__(
        self,
        batchers: list[ContinuousBatcher],
        config: FleetConfig,
        page_size: int,
        roles: list | tuple | None = None,
        states: list[str] | None = None,
    ):
        self.batchers = batchers
        self.config = config
        self.page_size = page_size
        #: Per-replica roles (PR 16): prefill-role replicas never take
        #: real requests through route() — they serve handoff warm-ups
        #: only (serving/disagg.py). None = every replica serves.
        self.roles = roles
        #: Per-replica lifecycle states (PR 19) — ALIASED with the
        #: owning ReplicaSet's list, mutated in place on elastic
        #: transitions: the router skips "draining"/"retired" replicas
        #: for NEW work while a draining replica's in-flight requests
        #: finish on its still-running loop. None = every replica
        #: permanently "serving" (the PR-14 static fleet).
        self.states = states
        #: Fleet-steered load weights (PR 19): multiplied into every
        #: load_cost comparison, so weight > 1 repels new work and
        #: weight < 1 attracts it. Missing entries weigh 1.0.
        self._weights: list[float] = []
        self._rr = 0
        self._rr_lock = threading.Lock()
        # Pending-route hints: first prefix-page run -> (replica,
        # deadline). A burst's mates route BEFORE the first request is
        # even admitted (registration happens at admission), so the
        # live registry probe alone would scatter the panel across
        # replicas; the hint pins the chain's home for the
        # submit→admission window. First-page granularity — the same
        # bucket key GroupTracker's stream planning uses.
        self._recent: dict[tuple, tuple[int, float]] = {}

    def set_weights(self, weights: list[float]) -> None:
        """Install fleet-controller load weights (PR 19). Replaces the
        whole vector; replicas past its end weigh 1.0. Each weight is
        also exported as ``gateway_router_weight{replica=}``."""
        with self._rr_lock:
            self._weights = [max(float(w), 1e-6) for w in weights]
        for i, w in enumerate(self._weights):
            _M_ROUTER_WEIGHT.labels(replica=str(i)).set(w)

    def weights(self) -> list[float]:
        """The effective weight per current replica (1.0 = neutral)."""
        with self._rr_lock:
            w = list(self._weights)
        return [
            w[i] if i < len(w) else 1.0
            for i in range(len(self.batchers))
        ]

    def _weight(self, i: int) -> float:
        with self._rr_lock:
            return self._weights[i] if i < len(self._weights) else 1.0

    def _in_service(self, i: int) -> bool:
        return self.states is None or self.states[i] == "serving"

    def healthy(self) -> list[int]:
        """In-service replicas whose serving loop is alive and fresh.
        Draining/retired replicas (PR 19) are skipped deliberately —
        the router must not hand NEW work to a replica that is
        finishing its in-flight requests on the way out. Falls back to
        ALL in-service replicas when none qualify — routing somewhere
        beats failing everywhere, and the gateway's /readyz is already
        reporting the outage."""
        out = []
        candidates = [
            i for i in range(len(self.batchers)) if self._in_service(i)
        ]
        for i in candidates:
            hb = self.batchers[i].heartbeat()
            if hb["alive"] and hb["last_tick_age_s"] <= self.config.ready_stall_s:
                out.append(i)
        return out or candidates or list(range(len(self.batchers)))

    def serving(self) -> list[int]:
        """Healthy replicas eligible for REAL requests: with roles
        active, prefill-only replicas drop out (they serve handoff
        warm-ups through the coordinator, never routed traffic). Falls
        back to every healthy replica when the filter empties — same
        route-somewhere principle as :meth:`healthy`."""
        healthy = self.healthy()
        if self.roles is None:
            return healthy
        out = [i for i in healthy if self.roles[i] != "prefill"]
        return out or healthy

    def _next_rr(self, candidates: list[int]) -> int:
        with self._rr_lock:
            idx = candidates[self._rr % len(candidates)]
            self._rr += 1
        return idx

    def _hint_get(self, chain) -> int | None:
        """Pending-route hint for this chain's first page run, if the
        hinted replica is still plausible (fresh entry, in-range)."""
        if not chain:
            return None
        with self._rr_lock:
            hit = self._recent.get(chain[0])
            if hit is None:
                return None
            idx, deadline = hit
            if time.monotonic() > deadline:
                del self._recent[chain[0]]
                return None
        return idx

    def _hint_put(self, chain, idx: int) -> None:
        if not chain:
            return
        with self._rr_lock:
            while len(self._recent) >= self.RECENT_MAX:
                self._recent.pop(next(iter(self._recent)))
            self._recent[chain[0]] = (
                idx,
                time.monotonic() + self.RECENT_TTL_S,
            )

    @staticmethod
    def _off_loop() -> bool:
        """True when NOT running on an asyncio event loop — the only
        place a blocking wait is acceptable."""
        import asyncio

        try:
            asyncio.get_running_loop()
        except RuntimeError:
            return True
        return False

    def route(self, ids, chain=None) -> tuple[int, str]:
        """Pick a replica for a request with prompt token ids ``ids``.
        Returns ``(replica index, reason)`` — reason is one of
        :data:`ROUTE_REASONS`. ``chain``: the ids' precomputed
        :func:`prefix_chain_key` (the submit path fingerprints ONCE
        and threads it through; None recomputes)."""
        c = self.config
        healthy = self.serving()
        if c.policy == "random":
            # The control policy stays deliberately chain-blind (no
            # hints either) — the A/B isolates what affinity buys.
            return self._next_rr(healthy), "random"
        if chain is None:
            chain = prefix_chain_key(ids, self.page_size)
        # Longest resident chain wins (registry pages first — they are
        # restore-free; host-tier tokens break registry ties).
        best_score = (0, 0)
        owner = None
        for i in healthy:
            p = self.batchers[i].prefix_probe(ids)
            score = (p["registry_tokens"], p["host_tokens"])
            if score > best_score:
                best_score, owner = score, i
        floor = c.affinity_min_pages * self.page_size
        if best_score[0] < floor and len(chain) >= c.affinity_min_pages:
            # No device-RESIDENT chain clears the floor (a host-tier
            # hit ties across replicas — the store is fleet-shared),
            # but a burst-mate may have been routed milliseconds ago
            # and not admitted yet — the pending-route hint is the
            # affinity signal for that window, and it also keeps a
            # post-preempt burst together so the chain restores ONCE
            # instead of once per scattered mate.
            hinted = self._hint_get(chain)
            if hinted is not None and hinted in healthy:
                owner = hinted
                best_score = (floor, 0)
        if owner is not None and best_score[0] >= floor:
            limit = c.rebalance_waiting
            if limit is None:
                limit = 4 * self.batchers[owner].config.max_slots
            if self.batchers[owner].waiting_depth() > limit:
                # The chain's owner is congested: move the chain, not
                # the cache-miss — export its ready pages through the
                # shared store (spill, not eviction) and re-home the
                # request to a healthy alternative, whose admission
                # will restore the chain remotely. If a mate already
                # moved this chain (the hint names a non-owner), FOLLOW
                # IT: burst mates must coalesce on one destination —
                # re-running min-load per mate scatters the chain onto
                # several replicas and re-exports it once per mate.
                others = [i for i in healthy if i != owner]
                if others:
                    hinted = self._hint_get(chain)
                    if hinted is not None and hinted in others:
                        return hinted, "rebalance"
                    dst = min(
                        others,
                        key=lambda i: self.batchers[i].load_cost()
                        * self._weight(i),
                    )
                    ev = self.batchers[owner].request_export(ids)
                    if c.rebalance_export_wait_s > 0 and self._off_loop():
                        # Let the spill land before the destination's
                        # admission probes the store — otherwise the
                        # re-homed request re-prefills the chain the
                        # export was about to make restorable.
                        # Bounded, and NEVER on an asyncio event loop
                        # (a synchronous wait there would freeze the
                        # whole gateway under exactly the load spike
                        # rebalancing exists to absorb) — the async
                        # path goes cache-cold for this first mate and
                        # the hinted mates behind it restore once the
                        # spill lands.
                        ev.wait(c.rebalance_export_wait_s)
                    _flight.flight_recorder().record(
                        "rebalance",
                        time.perf_counter(),
                        src=owner,
                        dst=dst,
                        chain_pages=best_score[0] // self.page_size,
                    )
                    # The chain is moving: follow-up mates land at the
                    # destination too (the hint check above).
                    self._hint_put(chain, dst)
                    return dst, "rebalance"
            self._hint_put(chain, owner)
            return owner, "prefix"
        # No affinity anywhere: least outstanding MODELED work (the
        # PR-10 cost model integrated over admitted requests), ties by
        # index for determinism. The hint makes this request's replica
        # the chain's home for burst-mates behind it.
        dst = min(
            healthy,
            key=lambda i: (
                self.batchers[i].load_cost() * self._weight(i),
                i,
            ),
        )
        self._hint_put(chain, dst)
        return dst, "load"


class ReplicaSet:
    """K continuous-batcher replicas + the router + the shared store.

    Construction mirrors :class:`ContinuousBatcher`: one model config
    and parameter tree (shared by every replica — jax arrays are
    immutable; a per-replica mesh re-shards without copying the
    original), one :class:`ContinuousConfig` INSTANCE all replicas
    read live (the bench's knob-flip lever works fleet-wide), and an
    optional draft model passed through to every replica. With
    ``config.host_cache_bytes > 0`` the fleet creates ONE
    :class:`HostPageStore` with that (fleet-wide) budget and hands it
    to every replica — the preempt/rebalance transport.
    """

    def __init__(
        self,
        cfg: ModelConfig,
        params: dict,
        tokenizer: Tokenizer | None = None,
        config: ContinuousConfig | None = None,
        fleet: FleetConfig | None = None,
        mesh=None,
        meshes: list | None = None,
        draft: tuple[ModelConfig, dict] | None = None,
        draft_map=None,
        control=None,
        host_store=None,
    ):
        from llm_consensus_tpu.serving.disagg import (
            HandoffCoordinator,
            resolve_roles,
            role_config,
        )

        self.cfg = cfg
        if isinstance(config, (list, tuple)):
            # The fleet's whole control surface — live knob flips
            # (spec_decode, decode_rounds, ragged_attention: the bench
            # and the adaptive controller flip ONE object between
            # bursts), role_config derivation, the router's shared
            # page-size/bucket view, and FleetBackend.request_cost's
            # replica-0 pricing — assumes every decode/mixed replica
            # reads the SAME ContinuousConfig instance. A per-replica
            # list would serve, silently, until the first live flip
            # reached only replica 0.
            raise ValueError(
                "ReplicaSet takes ONE shared ContinuousConfig, not "
                f"per-replica configs (got {type(config).__name__} of "
                f"{len(config)}): every decode/mixed replica aliases "
                "the same live instance so a knob flip (spec_decode, "
                "decode_rounds, ...) reaches the whole fleet at once. "
                "For heterogeneous engines, build a serving.modelset."
                "ModelSet of single-model members instead."
            )
        self.config = config or ContinuousConfig()
        self.fleet_config = fleet or FleetConfig()
        if self.fleet_config.replicas < 1:
            raise ValueError(
                f"need >= 1 replica, got {self.fleet_config.replicas}"
            )
        if self.fleet_config.policy not in ("prefix", "random"):
            raise ValueError(
                f"unknown routing policy {self.fleet_config.policy!r}"
            )
        self.tokenizer = tokenizer or ByteTokenizer()
        k = self.fleet_config.replicas
        if meshes is not None and len(meshes) != k:
            raise ValueError(
                f"meshes has {len(meshes)} entries for {k} replicas"
            )
        replica_meshes = meshes if meshes is not None else [mesh] * k
        c = self.config
        # Roles/states are LISTS (PR 19): elastic spawn appends, and
        # the router aliases both in place — replica indices stay
        # stable for metric labels, routed counters, and hints across
        # the whole lifecycle (a retired slot is never reused).
        self.roles = list(resolve_roles(self.fleet_config.role, k))
        self.states: list[str] = ["serving"] * k
        tier_on = (
            c.host_cache_bytes > 0 and c.share_prefix and c.prefill_chunk > 0
        )
        self.store: HostPageStore | None = None
        if host_store is not None:
            # EXTERNAL store (PR 16): typically a RemotePageStore over
            # the authoritative tier in another process — the same
            # interface, so everything below (preempt, export,
            # restore, stats) takes it transparently.
            if not tier_on:
                raise ValueError(
                    "a shared host_store needs the offload tier "
                    "engaged: host_cache_bytes > 0, share_prefix, "
                    "prefill_chunk > 0"
                )
            self.store = host_store
        elif tier_on:
            # ONE store, fleet-wide budget: any replica restores any
            # chain (store keys carry each replica's config/weights
            # scope, so a heterogeneous fleet can never cross-restore).
            self.store = HostPageStore(c.host_cache_bytes)
        self.batchers: list[ContinuousBatcher] = []
        scope: tuple | None = None
        for i in range(k):
            # Adaptive control (PR 15): ``control`` is a ControlConfig
            # — each replica gets ITS OWN AdaptiveController (the
            # acceptance/overhead/MBU signals are per-replica streams;
            # one shared controller would average incomparable
            # workloads). None = every knob static, the pre-PR-15
            # fleet.
            ctrl = None
            if control is not None:
                from llm_consensus_tpu.serving.control import (
                    AdaptiveController,
                )

                ctrl = AdaptiveController(control)
            b = ContinuousBatcher(
                cfg,
                params,
                tokenizer=self.tokenizer,
                # Decode/mixed replicas share the fleet's live config
                # instance; a prefill replica gets role_config's copy
                # with the decode-phase machinery pinned off. None of
                # the replaced fields enter the store-key scope, so
                # roled replicas still restore each other's pages.
                config=role_config(c, self.roles[i]),
                mesh=replica_meshes[i],
                draft=draft,
                draft_map=draft_map,
                host_store=self.store,
                # Replica 0 computes the store-key scope (a walk over
                # every param leaf); its siblings share the identical
                # cfg/params, so they reuse it instead of re-walking.
                host_store_scope=scope,
                controller=ctrl,
            )
            if self.store is not None and scope is None:
                scope = b._store_scope
            self.batchers.append(b)
        # Elastic spawn materials (PR 19): references only — jax
        # arrays are immutable and a spawned replica re-shards the
        # SAME parameter tree exactly like the construction loop above.
        self._params = params
        self._draft = draft
        self._draft_map = draft_map
        self._control_cfg = control
        self._spawn_mesh = replica_meshes[-1]
        self._store_scope = scope
        # Shared-config audit (PR 18): role_config must hand every
        # decode/mixed replica the SAME live instance (prefill copies
        # are the one sanctioned divergence — their decode machinery is
        # pinned off and none of the replaced fields enter the store
        # scope). A drift here means a live knob flip would reach only
        # part of the fleet — fail loudly at construction, not at the
        # first flip.
        for i, b in enumerate(self.batchers):
            if self.roles[i] != "prefill" and b.config is not c:
                raise RuntimeError(
                    f"replica {i} (role {self.roles[i]!r}) holds a "
                    "private ContinuousConfig copy — the live-knob-flip "
                    "contract requires every decode/mixed replica to "
                    "alias the fleet's one shared instance"
                )
        self.router = PrefixRouter(
            self.batchers,
            self.fleet_config,
            c.page_size,
            roles=self.roles,
            states=self.states,
        )
        # Prefill→decode handoffs engage only when a prefill-role
        # replica exists AND the page transport is live (a roled fleet
        # without a store could never move the chain).
        self.handoff: HandoffCoordinator | None = None
        if "prefill" in self.roles:
            if self.store is not None:
                self.handoff = HandoffCoordinator(self)
            else:
                log.warning(
                    "prefill-role replicas configured without a page "
                    "transport (host_cache_bytes == 0 or sharing off): "
                    "no chain can ever hand off — the prefill replicas "
                    "will idle while decode replicas prefill everything"
                )
        # stats() mirrors of the routed/preempt Prometheus counters
        # (lockstep tested).
        self._lock = threading.Lock()
        self._routed = [
            {r: 0 for r in ROUTE_REASONS} for _ in range(k)
        ]
        self._preempt_requests = [0] * k
        # Elastic lifecycle mirrors of gateway_fleet_scale_total
        # (lockstep tested) + a guard serializing spawn/retire.
        self._scale = {"spawn": 0, "drain": 0, "retire": 0}
        self._scale_lock = threading.Lock()
        self._refresh_state_gauge()

    # -- serving --------------------------------------------------------

    def _route_ids(self, prompt: str):
        """The prompt's token ids AS THE BATCHER WILL SEE THEM (the
        same largest-bucket left-truncation submit applies) — routing
        on the untruncated prompt could affine on a prefix the
        admission then cuts off."""
        ids = self.tokenizer.encode(prompt)
        return ids[-self.config.seq_buckets[-1] :]

    def submit(self, prompt: str, **kw):
        """Route + submit; returns the replica batcher's Future.
        Keyword args pass through to
        :meth:`ContinuousBatcher.submit`. The prompt is tokenized
        ONCE — the FULL encoding is handed to the batcher (so its own
        over-long-prompt policy still applies: reject under
        ``truncate_prompts=False``, warn+left-truncate otherwise)
        while routing sees the truncated view the admission will
        actually serve."""
        full_ids = self.tokenizer.encode(prompt)
        ids = full_ids[-self.config.seq_buckets[-1] :]
        chain = prefix_chain_key(ids, self.config.page_size)
        if self.handoff is not None:
            # Role split (PR 16): a cold chain warms on a prefill
            # replica and lands in the shared store before (off-loop)
            # or while (on the gateway loop) the real request decodes.
            # The submit path runs under the request's trace (PR 20):
            # hand it through so the claim→export→restore window and
            # the store ops inside it attribute to THIS request.
            self.handoff.ensure_prefilled(
                prompt, ids, chain, trace=_tracing.current_trace()
            )
        idx, reason = self.router.route(ids, chain=chain)
        self._count_route(idx, reason, chain)
        if self.fleet_config.prefetch and self.store is not None:
            # Route-driven restore prefetch (PR 17): the destination
            # is known NOW, admission happens later on the replica's
            # worker — stage the chain's store pages on a side thread
            # in between so the restore plan starts from staged planes
            # (one remote round trip saved per restorable page run).
            # Non-blocking and advisory; registry-resident pages are
            # skipped by the prefetcher's own probe.
            self.batchers[idx].prefetch_chain(ids)
        return self.batchers[idx].submit(
            prompt, prompt_ids=full_ids, **kw
        )

    def submit_to(self, idx: int, prompt: str, **kw):
        """Bypass the router (tests, pinned traffic)."""
        return self.batchers[idx].submit(prompt, **kw)

    def _count_route(self, idx: int, reason: str, chain) -> None:
        _M_ROUTED.labels(replica=str(idx), reason=reason).inc()
        with self._lock:
            self._routed[idx][reason] += 1
        b = self.batchers[idx]
        _M_PROGRAMS.labels(replica=str(idx)).set(b.device_programs_total())
        _M_HIT_RATE.labels(replica=str(idx)).set(b.prefix_hit_rate())
        if self.store is not None:
            _M_STORE_BYTES.set(self.store.bytes_used)
        _flight.flight_recorder().record(
            "route",
            time.perf_counter(),
            trace_id=_tracing.trace_id_of(_tracing.current_trace()),
            replica=idx,
            reason=reason,
            chain_pages=len(chain),
        )

    # -- overload: preempt instead of shed ------------------------------

    def preempt_for_admission(self) -> bool:
        """The gateway admission controller's overflow hook: called at
        a queue-full moment, returns True to ADMIT past the bound
        instead of shedding 429.

        Preemption is possible while (a) the shared tier can absorb
        another page (a full tier would evict other requests'
        preserved work — real loss) AND (b) the fleet shows ANY
        preserved or preservable chain work: registry-resident chains
        (pinned-by-live-slots included — a transient all-pinned
        moment still admits; chains demote as slots retire) OR
        entries already in the shared store. The store clause matters
        right after a preemption: the demoted chains have LEFT the
        registries and the storm's own chains have not registered
        yet, but the preserved work is sitting in the tier — shedding
        in that window would 429 the exact storm preemption exists to
        absorb. Traffic that registers NOTHING shareable ever
        (sub-page prompts, a sharing-off fleet) populates neither
        surface and keeps the classic 429 backpressure — admitting it
        past the bound would grow the queue without bound with
        nothing to preempt. When some replica holds demotable chains
        right now, the one with the most (the victim) is asked to
        demote ``FleetConfig.preempt_pages`` of its lowest-priority
        chains, freeing device pool pages for the storm. Cheap on the
        happy path (node-count reads — no registry tree walks on the
        event loop; the demotion itself runs on the victim's worker
        thread), but it MAY briefly synchronize with an in-flight
        spill's device_get through the victim's lock — that
        synchronization is deliberate, see ORDER MATTERS below."""
        store = self.store
        if store is None:
            return False
        page_bytes = max(b.host_page_bytes for b in self.batchers)
        if store.headroom_bytes < page_bytes:
            return False
        # Victim selection by CACHED node counts (O(1) per replica),
        # not by the reclaimable-pages tree walk — this runs on the
        # gateway event loop once per overflowing submit. A victim
        # whose chains are all pinned right now makes the preempt
        # request a worker-side no-op; the pages demote as slots
        # retire either way.
        victim, pages = None, 0
        for i, b in enumerate(self.batchers):
            r = b.cached_chain_pages()
            if r > pages:
                victim, pages = i, r
        # ORDER MATTERS: the registry probe above synchronizes on each
        # batcher's lock, so while a preempt's evict+demote is
        # mid-flight this call blocks until the victim's store puts
        # have landed, and the store read BELOW sees them. Reading the
        # store first can pair a pre-demote store (empty) with a
        # post-demote registry (empty) and shed spuriously in the one
        # window preemption exists to cover (observed: 1/12 storm
        # requests 429'd under the reversed order).
        if victim is None and len(store) == 0:
            return False
        if victim is not None:
            vb = self.batchers[victim]
            grant = min(pages, self.fleet_config.preempt_pages)
            if vb.controller is not None and not vb.controller.restore_pacing_ok(
                grant, vb.host_page_bytes
            ):
                # Restore pacing (PR 15): the modeled restore debt —
                # bytes preemption demoted that the one-page-per-
                # iteration restore path has not repaid — is past its
                # cap. Demoting more chains now just thrashes the
                # tier (everything demoted is about to be restored),
                # so classic 429 backpressure resumes until the debt
                # drains. Controller-less fleets keep the PR-14
                # behavior unchanged.
                return False
            vb.request_preempt(grant)
            _M_PREEMPTIONS.labels(replica=str(victim)).inc()
            with self._lock:
                self._preempt_requests[victim] += 1
        return True

    # -- rebalance (explicit) -------------------------------------------

    def rebalance_chain(
        self, prompt: str, wait_s: float | None = 30.0
    ) -> int | None:
        """Export ``prompt``'s resident chain from its owning replica
        into the shared store (spill, not eviction), so ANY replica's
        next same-prefix admission restores it remotely. Returns the
        owner's index (None when no replica holds the chain). The
        router does this automatically under owner congestion; this is
        the explicit lever (tests, operational drain)."""
        ids = self._route_ids(prompt)
        owner, best = None, 0
        for i, b in enumerate(self.batchers):
            t = b.prefix_probe(ids)["registry_tokens"]
            if t > best:
                owner, best = i, t
        if owner is None:
            return None
        ev = self.batchers[owner].request_export(ids)
        if wait_s is not None and not ev.wait(wait_s):
            raise TimeoutError(
                f"replica {owner} did not run the chain export "
                f"within {wait_s}s"
            )
        return owner

    # -- elastic replicas (PR 19) ---------------------------------------

    def _refresh_state_gauge(self) -> None:
        for state in ("serving", "draining", "retired"):
            _M_FLEET_REPLICAS.labels(state=state).set(
                sum(1 for s in self.states if s == state)
            )

    def _note_scale(self, action: str, idx: int, **meta) -> None:
        """One transition = counter + mirror + flight event + gauge
        refresh (the PR-15 _decide discipline at fleet altitude)."""
        _M_FLEET_SCALE.labels(action=action).inc()
        with self._lock:
            self._scale[action] += 1
        self._refresh_state_gauge()
        _flight.flight_recorder().record(
            "scale", time.perf_counter(), action=action, replica=idx, **meta
        )

    def serving_indices(self) -> list[int]:
        return [i for i, s in enumerate(self.states) if s == "serving"]

    def spawn_replica(self) -> int:
        """Add one mixed-role batcher replica and put it in service.

        The new replica is built exactly like the construction loop —
        same shared ContinuousConfig instance (the live-knob-flip
        contract extends to it), same shared parameter tree, same
        shared store (reusing the cached store-key scope, no
        param-tree re-walk) — and appended so every existing replica's
        index, metric labels, and routing hints stay valid. The router
        sees it on its next ``healthy()`` probe; cold pools make it
        the least-loaded target, so new work drains toward it
        immediately. Returns the new replica's index."""
        from llm_consensus_tpu.serving.control import AdaptiveController
        from llm_consensus_tpu.serving.disagg import role_config

        with self._scale_lock:
            ctrl = (
                AdaptiveController(self._control_cfg)
                if self._control_cfg is not None
                else None
            )
            b = ContinuousBatcher(
                self.cfg,
                self._params,
                tokenizer=self.tokenizer,
                config=role_config(self.config, "mixed"),
                mesh=self._spawn_mesh,
                draft=self._draft,
                draft_map=self._draft_map,
                host_store=self.store,
                host_store_scope=self._store_scope,
                controller=ctrl,
            )
            idx = len(self.batchers)
            with self._lock:
                self._routed.append({r: 0 for r in ROUTE_REASONS})
                self._preempt_requests.append(0)
            # Append order: batcher first, then role/state — a router
            # probe between the two sees a shorter states list and
            # simply skips the newcomer for one decision.
            self.batchers.append(b)
            self.roles.append("mixed")
            self.states.append("serving")
            self._note_scale("spawn", idx)
            return idx

    def retire_replica(
        self, idx: int, wait_s: float = 60.0, poll_s: float = 0.05
    ) -> dict:
        """Drain and retire replica ``idx`` with ZERO lost requests.

        The sequence is the PR-14 rebalance discipline pointed at a
        whole replica: (1) mark ``draining`` — the router immediately
        stops handing it NEW work while its loop keeps running; (2)
        wait for its admitted requests (waiting + slotted) to finish —
        their futures resolve normally; (3) demote its resident
        registry chains to the shared HostPageStore (the preempt/evict
        path — after the drain nothing is pinned, so the chains
        re-home: any surviving replica's next same-prefix admission
        restores them at device_put latency instead of re-prefilling);
        (4) stop the loop and mark ``retired``. The slot stays in
        ``batchers`` so indices never shift.

        Raises TimeoutError if in-flight work outlives ``wait_s`` —
        the replica is left DRAINING (never killed with live work;
        call again to finish the retire)."""
        if not 0 <= idx < len(self.batchers):
            raise ValueError(f"no replica {idx}")
        if self.states[idx] not in ("serving", "draining"):
            raise ValueError(
                f"replica {idx} is {self.states[idx]}, not retirable"
            )
        if self.roles[idx] == "prefill":
            raise ValueError(
                "prefill-role replicas anchor the handoff tier; "
                "elastic retire covers decode-capable replicas only"
            )
        with self._scale_lock:
            survivors = [
                i for i in self.serving_indices() if i != idx
            ]
            if not survivors:
                raise ValueError(
                    "cannot retire the last serving replica"
                )
            b = self.batchers[idx]
            if self.states[idx] == "serving":
                self.states[idx] = "draining"
                self._note_scale(
                    "drain", idx, active=b.active_requests()
                )
            deadline = time.monotonic() + wait_s
            while b.active_requests() > 0:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"replica {idx} still has "
                        f"{b.active_requests()} in-flight requests "
                        f"after {wait_s}s; left draining"
                    )
                time.sleep(poll_s)
            # Chains re-home through the shared store: demote every
            # reclaimable registry page (nothing is pinned post-drain)
            # so survivors restore instead of re-prefilling.
            demoted = 0
            if self.store is not None:
                pages = b.cached_chain_pages()
                if pages:
                    b.request_preempt(pages)
                    while (
                        b.cached_chain_pages() > 0
                        and time.monotonic() <= deadline
                    ):
                        time.sleep(poll_s)
                    demoted = pages - b.cached_chain_pages()
            b.close()
            self.states[idx] = "retired"
            self._note_scale("retire", idx, demoted_pages=demoted)
            return {
                "replica": idx,
                "demoted_pages": demoted,
                "serving": len(self.serving_indices()),
            }

    # -- observability / lifecycle --------------------------------------

    def prefix_probe(self, ids) -> dict:
        """The fleet's best resident-chain view for these token ids —
        the max over every replica's read-only
        :meth:`ContinuousBatcher.prefix_probe` (registry pages first,
        host-tier extension breaks ties: the router's own comparison).
        The ``/debug/chains`` probe surface a front gateway's
        peer-routing reads (PR 16)."""
        best = (0, 0)
        for b in self.batchers:
            p = b.prefix_probe(ids)
            best = max(best, (p["registry_tokens"], p["host_tokens"]))
        # One scope for the whole answer (PR 18): the fleet is
        # homogeneous by the shared-config contract, so replica 0's
        # model/weights identity names every chain counted above.
        return {
            "registry_tokens": best[0],
            "host_tokens": best[1],
            "scope": self.batchers[0].chain_scope(),
        }

    def heartbeat(self) -> dict:
        """Aggregate serving-loop liveness: ``alive`` only when EVERY
        in-service replica's loop is alive (a degraded fleet must flip
        /readyz — one wedged replica is a capacity loss the balancer
        upstream should see), ``last_tick_age_s`` is the stalest such
        replica's, and ``replicas`` carries each loop's own heartbeat
        so the gateway can name the wedged index. Draining/retired
        replicas (PR 19) report their lifecycle state in their entry
        but are EXCLUDED from the aggregate — a deliberate drain or a
        stopped retired loop is not an outage."""
        hbs = [b.heartbeat() for b in self.batchers]
        for h, s in zip(hbs, self.states):
            if s != "serving":
                h["state"] = s
        act = [
            h for h, s in zip(hbs, self.states) if s == "serving"
        ] or hbs
        return {
            "alive": all(h["alive"] for h in act),
            "last_tick_age_s": max(h["last_tick_age_s"] for h in act),
            "last_step_age_s": max(
                (
                    h["last_step_age_s"]
                    for h in act
                    if h["last_step_age_s"] is not None
                ),
                default=None,
            ),
            "replicas": hbs,
        }

    def stats(self) -> dict:
        """Fleet snapshot: per-replica batcher stats plus aggregates.
        Shared-store counters are taken from the STORE once — each
        replica's own ``offload_demoted/dropped/host_bytes`` keys read
        the same shared store, so summing them would multiply-count.
        Pulling stats also refreshes the per-replica gauges
        (``gateway_replica_programs`` / ``_prefix_hit_rate`` /
        ``_shared_store_bytes``), so a scrape following a stats pull
        is current."""
        per = [b.stats() for b in self.batchers]
        for i, role in enumerate(self.roles):
            # The per-ROLE split of the process-global (last-writer-
            # wins) autotune families: each replica's stats carry its
            # role, the PR-14/15 per-replica convention.
            per[i]["role"] = role
            per[i]["state"] = self.states[i]
        for i, b in enumerate(self.batchers):
            # The same accessors the route-time refresh uses — ONE
            # definition of each gauge's value (a second copy keyed on
            # the program-kind list would drift the moment a kind is
            # added).
            _M_PROGRAMS.labels(replica=str(i)).set(
                b.device_programs_total()
            )
            _M_HIT_RATE.labels(replica=str(i)).set(b.prefix_hit_rate())
        if self.store is not None:
            _M_STORE_BYTES.set(self.store.bytes_used)
        with self._lock:
            routed = [dict(r) for r in self._routed]
            preempts = list(self._preempt_requests)
            scale = dict(self._scale)
        agg_lookups = sum(s["prefix_lookups"] for s in per)
        return {
            "replicas": len(self.batchers),
            "serving_replicas": len(self.serving_indices()),
            "states": list(self.states),
            "router_weights": self.router.weights(),
            "scale_events": scale,
            "policy": self.fleet_config.policy,
            "roles": list(self.roles),
            "role_handoffs": (
                self.handoff.handoffs if self.handoff is not None else 0
            ),
            # Claim-to-exported handoff latency (PR 17) — the stats()
            # mirror of gateway_handoff_seconds (lockstep tested).
            "handoff_seconds_sum": (
                self.handoff.handoff_seconds_sum
                if self.handoff is not None
                else 0.0
            ),
            "handoff_seconds_count": (
                self.handoff.handoff_seconds_count
                if self.handoff is not None
                else 0
            ),
            "per_replica": per,
            "routed": routed,
            "routed_total": sum(sum(r.values()) for r in routed),
            "routed_prefix": sum(r["prefix"] for r in routed),
            "preempt_requests": preempts,
            "completed_requests": sum(
                s["completed_requests"] for s in per
            ),
            "generated_tokens": sum(s["generated_tokens"] for s in per),
            "prefill_chunks": sum(s["prefill_chunks"] for s in per),
            "prefix_lookups": agg_lookups,
            "prefix_hits": sum(s["prefix_hits"] for s in per),
            "prefix_hit_rate": (
                sum(s["prefix_hits"] for s in per) / max(1, agg_lookups)
            ),
            "prefix_pages_shared": sum(
                s["prefix_pages_shared"] for s in per
            ),
            "preempted_pages": sum(s["preempted_pages"] for s in per),
            "exported_pages": sum(s["exported_pages"] for s in per),
            "offload_restored_pages": sum(
                s["offload_restored_pages"] for s in per
            ),
            "offload_demoted_pages": (
                self.store.demoted_pages if self.store else 0
            ),
            "offload_dropped_pages": (
                self.store.dropped_pages if self.store else 0
            ),
            "shared_store_bytes": (
                self.store.bytes_used if self.store else 0
            ),
            "shared_store_pages": len(self.store) if self.store else 0,
        }

    def close(self) -> None:
        for b, s in zip(self.batchers, self.states):
            if s != "retired":  # retired loops already stopped
                b.close()


class FleetBackend(_backend_base.Backend):
    """Backend seam over a :class:`ReplicaSet` — the fleet counterpart
    of :class:`~llm_consensus_tpu.serving.continuous.
    ContinuousBackend`. The Coordinator's panel fan-out submits each
    member through the router, so panel mates affine to the replica
    whose registry holds their shared header; ``health()`` exposes the
    aggregate heartbeat (per-replica entries included) for the
    gateway's /readyz, and ``preempt_for_admission`` is the overflow
    hook the gateway wires into its admission controller."""

    def __init__(self, replicas: ReplicaSet):
        self.replicas = replicas

    async def generate_batch(self, requests):
        import asyncio

        BackendError = _backend_base.BackendError
        GenerationResult = _backend_base.GenerationResult

        futs = []
        try:
            for r in requests:
                futs.append(
                    self.replicas.submit(
                        r.prompt,
                        max_new_tokens=r.params.max_new_tokens,
                        temperature=r.params.temperature,
                        seed=r.params.seed,
                        top_k=r.params.top_k,
                        top_p=r.params.top_p,
                        stop=r.params.stop,
                    )
                )
        except (RuntimeError, ValueError) as e:
            # Mirror ContinuousBackend: a mid-batch submit failure must
            # not orphan earlier members' device work silently.
            for f in futs:
                f.cancel()
            raise BackendError(f"fleet submit failed: {e}") from e
        outs = await asyncio.gather(*(asyncio.wrap_future(f) for f in futs))
        return [
            GenerationResult(
                text=o.text, num_tokens=o.num_tokens, meta=o.timing
            )
            for o in outs
        ]

    def health(self) -> dict:
        return self.replicas.heartbeat()

    @property
    def tokenizer(self):
        """The fleet tokenizer — the gateway's ``/debug/chains``
        handler encodes ``?prompt=`` probes with it."""
        return self.replicas.tokenizer

    def prefix_probe(self, ids) -> dict:
        """``/debug/chains`` probe surface: the fleet-wide best
        resident-chain view (PR 16)."""
        return self.replicas.prefix_probe(ids)

    def request_cost(self, prompt: str, max_new_tokens: int) -> float:
        """Modeled bytes for the gateway's cost-budget admission
        (PR 15) — replica 0's pricing: the fleet is homogeneous in
        config terms (one shared ContinuousConfig), so any replica's
        modeled_request_cost is THE fleet price."""
        b = self.replicas.batchers[0]
        return b.modeled_request_cost(
            len(self.replicas.tokenizer.encode(prompt)), max_new_tokens
        )

    def preempt_for_admission(self) -> bool:
        return self.replicas.preempt_for_admission()

    async def close(self) -> None:
        self.replicas.close()
