"""Fleet control plane: telemetry -> fleet-level decisions (PR 19).

Everything the repo learned to measure and tune so far stops at the
replica boundary: the PR-15 :class:`~llm_consensus_tpu.serving.control.
AdaptiveController` closes its loop per replica, the PR-10 TTFT/TBT
histograms are telemetry-only, and the PR-14 :class:`~llm_consensus_tpu.
serving.fleet.PrefixRouter` never sees autotune/MBU/queue-cost signals.
This module is the layer above all of it — ONE controller per
:class:`~llm_consensus_tpu.serving.fleet.ReplicaSet` that turns the
existing per-replica telemetry into four coupled fleet-level decisions:

- **SLO-aware admission** (configured here, enforced in
  :mod:`llm_consensus_tpu.server.admission`): requests carry an
  optional SLO class (``/v1/generate`` ``"slo":`` field); admission
  predicts each request's queue wait from modeled cost ahead of it and
  the live dispatch rate, and at a full queue sheds the request that
  *will miss its SLO* — never simply the newest.
  :meth:`FleetControlConfig.admission_kwargs` is the one bridge: the
  CLI splats it into :class:`~llm_consensus_tpu.server.admission.
  AdmissionConfig` so the gateway and the fleet agree on classes.
- **Tenant fair-share** (same split): weighted fair queueing across
  the ``"tenant"`` payload field plus an admitted-cost share cap under
  contention, in the same modeled-byte unit as PR-15 cost-budget
  admission — one tenant's storm cannot starve panel traffic.
- **Router weight steering**: each tick folds per-replica modeled
  queue cost into :meth:`PrefixRouter.set_weights` load weights (a
  loaded replica's cost is inflated, repelling new work), and sizes
  two previously-static knobs from the same signals — the shared-
  prefix group-formation cap (``GroupTracker.max_groups``, via the
  worker-applied :meth:`ContinuousBatcher.request_group_cap`) and the
  host-tier restore-batch ceiling (:meth:`AdaptiveController.
  steer_restore_cap`).
- **Elastic replicas**: spawn batcher replicas against sustained
  queue-depth demand and retire them when the fleet idles, draining
  the retiring replica through the shared HostPageStore exactly like
  PR-14 rebalancing — zero lost requests, chains re-homed
  (:meth:`ReplicaSet.spawn_replica` / :meth:`ReplicaSet.
  retire_replica` do the mechanics; this controller decides WHEN).

Decision discipline mirrors PR-15 autotune: gauges refresh every tick,
``gateway_fleet_decisions_total{decision=}`` moves only when a
setpoint CHANGES, and every change lands a ``fleet`` flight-recorder
event — so a decision storm is visible as a counter slope and
replayable from the ring. All stats() mirrors are lockstep with the
Prometheus families (tested).

Threading: one daemon tick thread per controller (``interval_s``
cadence). Every signal read is a cheap lock-guarded accessor
(waiting_depth / load_cost / active_requests / restore_debt_bytes);
every actuation is either an enqueued worker request (group cap,
preempt) or a trivially-locked setter (router weights, restore cap) —
the tick thread never touches device state. Elastic retire blocks the
tick thread through the drain (bounded by ``retire_wait_s``); routing
and serving continue on their own threads throughout.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

from llm_consensus_tpu.server.metrics import (
    FLEET_DECISIONS as _M_DECISIONS,
)
from llm_consensus_tpu.serving import flight as _flight

log = logging.getLogger(__name__)

__all__ = ["FleetControlConfig", "FleetController", "DECISIONS"]

#: Decision kinds (the ``decision`` label of
#: ``gateway_fleet_decisions_total`` and the stats() mirror keys).
DECISIONS = ("router_weights", "group_cap", "restore_cap", "spawn", "retire")


@dataclass
class FleetControlConfig:
    #: Tick cadence of the control thread.
    interval_s: float = 0.5

    # -- SLO classes (enforced by server/admission.py) ------------------
    #: Class name -> queue-wait target seconds. The defaults give
    #: interactive traffic a tight TTFT budget and batch traffic a
    #: loose one; ``serve --slo-target class=seconds`` overrides.
    slo_classes: dict = field(
        default_factory=lambda: {"interactive": 2.0, "batch": 30.0}
    )
    #: Class applied to requests without an ``"slo"`` payload field;
    #: None = untagged requests stay SLO-blind.
    default_slo_class: str | None = "interactive"

    # -- tenant fair-share (enforced by server/admission.py) ------------
    #: Weighted fair queueing across the ``"tenant"`` payload field.
    fair_share: bool = True
    #: Tenant -> weight (absent tenants weigh 1.0 — equal shares).
    tenant_weights: dict = field(default_factory=dict)
    #: Shed a tenant only past fair_weight * slack (the ±10% band).
    fair_share_slack: float = 1.1
    #: Half-life of the decayed admitted-cost window the cap reads.
    fair_window_s: float = 30.0

    # -- router weight steering -----------------------------------------
    steer_router: bool = True
    #: Weight clamp: a replica's weight is its modeled load relative
    #: to the fleet mean, bounded to keep one hot replica from being
    #: starved forever (it must keep receiving SOME work to drain).
    weight_min: float = 0.25
    weight_max: float = 4.0

    # -- group-formation / restore-batch sizing -------------------------
    steer_sizing: bool = True
    #: Fleet queue pressure = total waiting / (serving x max_slots).
    #: Above ``pressure_high`` the group cap widens to max_slots (batch
    #: every shareable group per dispatch) and restore batches narrow
    #: (bound the stall injected into saturated decode lanes); below
    #: ``pressure_low`` both return to their defaults. The gap is
    #: hysteresis — each group-cap change re-traces the grouped decode
    #: program, so flapping would thrash the jit cache.
    pressure_high: float = 1.0
    pressure_low: float = 0.25
    #: Restore-debt fraction (fleet debt / host-tier budget) above
    #: which any narrowed restore cap is cleared — repaying demoted
    #: chains takes priority over stall bounding.
    restore_debt_high: float = 0.25
    restore_debt_low: float = 0.05
    #: The narrowed restore-batch ceiling under queue pressure.
    restore_cap_narrow: int = 2

    # -- elastic replicas -----------------------------------------------
    #: Replica-count band. ``elastic_max = 0`` disables elastic
    #: scaling entirely (the controller still steers weights/sizing).
    elastic_min: int = 1
    elastic_max: int = 0
    #: Spawn once mean waiting depth per serving replica has sat at or
    #: above this for ``spawn_sustain_ticks`` consecutive ticks — a
    #: single burst must not spawn a replica it will not need.
    spawn_depth: float = 2.0
    spawn_sustain_ticks: int = 3
    #: Retire (down to elastic_min) after this many consecutive ticks
    #: with zero waiting AND zero active requests fleet-wide.
    retire_idle_ticks: int = 20
    #: Drain bound handed to ReplicaSet.retire_replica.
    retire_wait_s: float = 60.0
    #: SLO burn-rate spawn pressure (PR 20): when an attached
    #: admission controller reports any class's decayed miss fraction
    #: (``gateway_slo_burn_rate{class=}``) at or above this, the tick
    #: counts as spawn pressure even if queue depth looks calm —
    #: misses can burn while depth oscillates under the spawn_depth
    #: threshold. 1.0 < never (burn is a fraction).
    burn_spawn_threshold: float = 0.5

    def admission_kwargs(self) -> dict:
        """The AdmissionConfig field overrides this fleet config
        implies — the ONE bridge between ``serve --fleet-control`` and
        the gateway's admission controller, so SLO classes and tenant
        weights cannot drift between the two layers."""
        return {
            "slo_classes": dict(self.slo_classes),
            "default_slo_class": self.default_slo_class,
            "tenant_fair_share": self.fair_share,
            "tenant_weights": dict(self.tenant_weights),
            "fair_share_slack": self.fair_share_slack,
            "fair_window_s": self.fair_window_s,
        }


class FleetController:
    """Fleet-scoped decision loop over one :class:`ReplicaSet`."""

    def __init__(self, replicas, config: FleetControlConfig | None = None):
        self.replicas = replicas
        self.config = config or FleetControlConfig()
        if self.config.elastic_max:
            if self.config.elastic_min < 1:
                raise ValueError("elastic_min must be >= 1")
            if self.config.elastic_max < self.config.elastic_min:
                raise ValueError(
                    "elastic_max must be >= elastic_min "
                    f"({self.config.elastic_max} < "
                    f"{self.config.elastic_min})"
                )
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._decisions = {d: 0 for d in DECISIONS}
        self._ticks = 0
        self._last_weights: list[float] | None = None
        self._group_cap: int | None = None
        self._restore_cap: int | None = None
        self._spawn_streak = 0
        self._idle_streak = 0
        #: The gateway admission controller this fleet serves behind
        #: (PR 20): attached by the CLI after the gateway is built, it
        #: feeds the per-class SLO burn rates into elastic decisions.
        self.admission = None
        # Discoverability: stats/bench surfaces reach the controller
        # through the fleet they already hold.
        replicas.fleet_controller = self

    def attach_admission(self, admission) -> None:
        """Wire the gateway's admission controller in (PR 20) so each
        tick can read its decayed per-class SLO burn rates
        (:meth:`~llm_consensus_tpu.server.admission.
        AdmissionController.burn_rates`, the
        ``gateway_slo_burn_rate{class=}`` mirror) as spawn pressure."""
        self.admission = admission

    def burn_rates(self) -> dict:
        """Per-class decayed SLO miss fractions from the attached
        admission controller; empty when none is attached (the
        pre-PR-20 shape — every decision then falls back to
        depth-only signals)."""
        adm = self.admission
        if adm is None:
            return {}
        try:
            return dict(adm.burn_rates())
        except Exception:  # noqa: BLE001 - telemetry must not kill ticks
            log.exception("burn-rate read failed")
            return {}

    # -- lifecycle ------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="fleet-control", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - the loop must survive
                log.exception("fleet control tick failed")

    # -- decision recording ---------------------------------------------

    def _decide(self, decision: str, **meta) -> None:
        """One setpoint CHANGE = counter + mirror + flight event (the
        PR-15 autotune discipline at fleet altitude). Steady-state
        ticks touch gauges only."""
        _M_DECISIONS.labels(decision=decision).inc()
        with self._lock:
            self._decisions[decision] += 1
        _flight.flight_recorder().record(
            "fleet", time.perf_counter(), decision=decision, **meta
        )

    # -- the loop body (public: tests/bench tick synchronously) ---------

    def tick(self) -> None:
        cfg = self.config
        rs = self.replicas
        serving = rs.serving_indices()
        if not serving:
            return
        with self._lock:
            self._ticks += 1
        bs = [rs.batchers[i] for i in serving]
        depths = [b.waiting_depth() for b in bs]
        actives = [b.active_requests() for b in bs]
        loads = [b.load_cost() for b in bs]
        max_slots = rs.config.max_slots

        if cfg.steer_router:
            self._steer_weights(rs, serving, loads)
        if cfg.steer_sizing:
            pressure = sum(depths) / max(1, len(serving) * max_slots)
            self._steer_group_cap(bs, max_slots, pressure)
            self._steer_restore_cap(rs, bs, pressure)
        if cfg.elastic_max > 0:
            self._steer_elastic(
                rs, serving, depths, actives, self.burn_rates()
            )

    def _steer_weights(self, rs, serving, loads) -> None:
        cfg = self.config
        mean = sum(loads) / len(loads)
        weights = [1.0] * len(rs.batchers)
        if mean > 0:
            for i, cost in zip(serving, loads):
                w = min(max(cost / mean, cfg.weight_min), cfg.weight_max)
                weights[i] = round(w, 3)
        # Gauges refresh every tick (set_weights exports them); the
        # decision counter moves only when the vector changes.
        rs.router.set_weights(weights)
        if weights != self._last_weights:
            self._last_weights = list(weights)
            self._decide("router_weights", weights=tuple(weights))

    def _steer_group_cap(self, bs, max_slots: int, pressure: float) -> None:
        cfg = self.config
        target = self._group_cap
        if pressure >= cfg.pressure_high:
            # Saturated admission queues: widen grouping so every
            # shareable prefix group batches into one dispatch.
            target = max_slots
        elif pressure <= cfg.pressure_low:
            # The GroupTracker construction default.
            target = max(1, max_slots // 2)
        if target is not None and target != self._group_cap:
            for b in bs:
                b.request_group_cap(target)
            self._group_cap = target
            self._decide(
                "group_cap", cap=target, pressure=round(pressure, 3)
            )

    def _steer_restore_cap(self, rs, bs, pressure: float) -> None:
        cfg = self.config
        budget = rs.config.host_cache_bytes
        if rs.store is None or budget <= 0:
            return
        debt = sum(
            b.controller.restore_debt_bytes
            for b in bs
            if b.controller is not None
        )
        frac = debt / budget
        want = self._restore_cap
        if frac >= cfg.restore_debt_high:
            # Heavy restore debt: clear any narrowing — repaying the
            # demoted chains beats bounding per-iteration stalls.
            want = None
        elif pressure >= cfg.pressure_high and frac <= cfg.restore_debt_low:
            # Busy queues, little debt: narrow restore batches so the
            # host tier's promotions inject bounded stalls into the
            # saturated decode lanes.
            want = cfg.restore_cap_narrow
        elif pressure <= cfg.pressure_low:
            want = None
        if want != self._restore_cap:
            for b in bs:
                if b.controller is not None:
                    b.controller.steer_restore_cap(want)
            self._restore_cap = want
            self._decide(
                "restore_cap",
                cap=want if want is not None else -1,
                debt_frac=round(frac, 3),
            )

    def _steer_elastic(self, rs, serving, depths, actives, burn) -> None:
        cfg = self.config
        mean_depth = sum(depths) / len(serving)
        # Burn-rate pressure (PR 20): a class burning SLO misses is
        # demand the depth signal can miss (depth oscillates under
        # spawn_depth while would-miss sheds keep it artificially
        # low) — count it toward the same sustain streak.
        burning = (
            max(burn.values(), default=0.0) >= cfg.burn_spawn_threshold
        )
        if (
            mean_depth >= cfg.spawn_depth or burning
        ) and len(serving) < cfg.elastic_max:
            self._spawn_streak += 1
            if self._spawn_streak >= cfg.spawn_sustain_ticks:
                self._spawn_streak = 0
                idx = rs.spawn_replica()
                self._decide(
                    "spawn",
                    replica=idx,
                    mean_depth=round(mean_depth, 2),
                    burning=burning,
                )
        else:
            self._spawn_streak = 0
        if (
            sum(depths) + sum(actives) == 0
            and len(serving) > cfg.elastic_min
        ):
            self._idle_streak += 1
            if self._idle_streak >= cfg.retire_idle_ticks:
                self._idle_streak = 0
                victims = [
                    i for i in serving if rs.roles[i] != "prefill"
                ]
                if len(victims) > 0 and len(serving) > cfg.elastic_min:
                    victim = max(victims)
                    try:
                        rs.retire_replica(
                            victim, wait_s=cfg.retire_wait_s
                        )
                    except (TimeoutError, ValueError) as e:
                        log.warning(
                            "elastic retire of replica %d skipped: %s",
                            victim,
                            e,
                        )
                        return
                    self._decide("retire", replica=victim)
        else:
            self._idle_streak = 0

    # -- observability --------------------------------------------------

    def stats(self) -> dict:
        """Mirror of gateway_fleet_decisions_total plus the current
        setpoints (lockstep tested)."""
        with self._lock:
            out = {
                f"fleet_decisions_{d}": self._decisions[d]
                for d in DECISIONS
            }
            out["fleet_ticks"] = self._ticks
        out["fleet_router_weights"] = (
            list(self._last_weights) if self._last_weights else []
        )
        out["fleet_group_cap"] = (
            self._group_cap if self._group_cap is not None else -1
        )
        out["fleet_restore_cap"] = (
            self._restore_cap if self._restore_cap is not None else -1
        )
        out["fleet_burn_rate"] = self.burn_rates()
        return out
