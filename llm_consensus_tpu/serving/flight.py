"""Serving flight recorder: the attribution plane over the batcher (PR 10).

Every perf PR since 5 was found by telemetry — the host-gap histogram
motivated pipelined dispatch (PR 6), ``gateway_device_programs_total``
motivated the ragged fusion (PR 8) — but histograms aggregate away the
*sequence* of events. With five interacting subsystems (pipelined
dispatch, ragged fusion, speculative decode, the host KV tier, prefix
groups) the question is no longer "how long is a step" but "what did
THIS request's journey through all of them look like". This module is
the answer's substrate:

- :class:`FlightRecorder` — a bounded, evict-oldest ring of typed
  scheduler events (program dispatch/fetch windows, admissions/sheds,
  chunk scheduling, spec flips and catch-up replays, stream-plan donor
  changes, demote/restore, pipeline flushes, CoW copies, PR 15's
  ``autotune`` knob decisions from the adaptive controller (recorded
  on value changes), and — PR 16 — ``handoff`` (a prefill→decode
  chain handoff completed: source replica + chain pages) and
  ``remote_store`` (the remote page store's circuit breaker flipped
  ``state=down``/``up`` — one event per outage TRANSITION, not per
  failed op, so a dead peer cannot flood the ring)), each stamped
  with monotonic time and the PR-5 trace id. Evictions are counted and
  mirrored into ``gateway_flight_dropped_total`` so a truncated export
  is detectable. Recording is a bool check when disabled and one
  lock+append when enabled — the ``bench.py --serve-flight-overhead``
  A/B leg holds it to the PR-5 < 2% tok/s gate.
- :class:`RequestLog` — a bounded ring of per-request serving
  summaries (TTFT, inter-token-gap percentiles, spec tokens accepted
  per round, restored-vs-prefilled header pages), fed at retirement,
  served at ``GET /debug/requests`` and in the response meta.
- :func:`to_chrome` — Chrome trace-event JSON (Perfetto-loadable) built
  from the ring: a device track reconstructed from dispatch→fetch
  windows (one slice per device program — exactly the programs
  ``gateway_device_programs_total`` counted, asserted in tests), a host
  track for un-overlapped scheduler work, a scheduler-event track, and
  one track per request.

Process-global singletons (:func:`flight_recorder`, :func:`request_log`)
follow :func:`llm_consensus_tpu.utils.tracing.trace_store`'s pattern:
the batcher writes, the gateway reads, tests isolate by ``clear()``.
"""

from __future__ import annotations

import itertools
import threading
from collections import OrderedDict, deque
from dataclasses import dataclass, field

from llm_consensus_tpu.server.metrics import FLIGHT_DROPPED as _M_DROPPED

__all__ = [
    "FlightEvent",
    "FlightRecorder",
    "RequestLog",
    "flight_recorder",
    "request_log",
    "set_enabled",
    "enabled",
    "percentile",
    "to_chrome",
    "merge_fleet",
    "to_chrome_fleet",
]


@dataclass
class FlightEvent:
    """One typed scheduler event.

    ``t0`` is a ``time.perf_counter`` stamp (the batcher's monotonic
    timebase — the same clock every dispatch/fetch stamp already uses);
    ``dur`` is 0 for instantaneous events and for device programs whose
    fetch has not landed yet (the fetch fills the window in place).
    """

    seq: int
    kind: str
    t0: float
    dur: float = 0.0
    trace_id: str | None = None
    meta: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "seq": self.seq,
            "kind": self.kind,
            "t0": self.t0,
            "dur_s": self.dur,
            **(
                {"trace_id": self.trace_id}
                if self.trace_id is not None
                else {}
            ),
            **({"meta": self.meta} if self.meta else {}),
        }


# Process-wide enable switch (the bench A/B lever). Disabled =>
# record() returns None before touching the lock; instrumentation
# sites stay branch-free.
_ENABLED = True


def set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


def enabled() -> bool:
    return _ENABLED


class FlightRecorder:
    """Bounded evict-oldest ring of :class:`FlightEvent`; thread-safe.

    The worker thread records; the gateway thread reads. ``record``
    returns the event object so the one writer may fill a device
    program's (t0, dur) window in place once its fetch lands — count
    parity with ``gateway_device_programs_total`` holds by construction
    because the event is recorded AT the counting site, window known or
    not.
    """

    def __init__(self, capacity: int = 8192):
        self.capacity = max(1, capacity)
        self._events: deque[FlightEvent] = deque()
        self._seq = itertools.count()
        self._dropped = 0
        self._lock = threading.Lock()

    def configure(self, capacity: int | None = None) -> None:
        """Adjust the ring bound (serve CLI knob); an over-full ring
        sheds down to the new cap immediately (counted)."""
        with self._lock:
            if capacity is not None:
                self.capacity = max(1, capacity)
            self._evict_locked()

    def _evict_locked(self) -> None:
        n = 0
        while len(self._events) > self.capacity:
            self._events.popleft()
            n += 1
        if n:
            self._dropped += n
            _M_DROPPED.inc(n)

    def record(
        self,
        kind: str,
        t0: float,
        dur: float = 0.0,
        trace_id: str | None = None,
        meta: dict | None = None,
        **extra,
    ) -> FlightEvent | None:
        """Append one event (evicting the oldest past capacity);
        ``None`` when recording is disabled. Metadata rides as keyword
        arguments (or an explicit ``meta`` dict for keys that collide
        with the positional parameters, e.g. a program's ``kind``)."""
        if not _ENABLED:
            return None
        with self._lock:
            ev = FlightEvent(
                seq=next(self._seq),
                kind=kind,
                t0=t0,
                dur=dur,
                trace_id=trace_id,
                meta={**(meta or {}), **extra},
            )
            self._events.append(ev)
            if len(self._events) > self.capacity:
                self._events.popleft()
                self._dropped += 1
                _M_DROPPED.inc()
        return ev

    def events(self) -> list[FlightEvent]:
        """Oldest-first snapshot."""
        with self._lock:
            return list(self._events)

    @property
    def dropped(self) -> int:
        """Events evicted from the ring (lockstep-mirrored into
        ``gateway_flight_dropped_total``)."""
        return self._dropped

    def __len__(self) -> int:
        return len(self._events)

    def clear(self) -> None:
        """Forget retained events (test isolation; not a drop)."""
        with self._lock:
            self._events.clear()


class RequestLog:
    """Bounded evict-oldest ring of per-request serving summaries.

    Keyed by the batcher's request id; a summary carrying a
    ``trace_id`` is reachable under that key too (the PR-5 id a client
    already holds from ``X-Trace-Id``). Eviction is retention policy,
    not data loss — summaries also ride the response meta — so it is
    not drop-counted.
    """

    def __init__(self, max_requests: int = 512):
        self.max_requests = max(1, max_requests)
        self._by_id: OrderedDict[str, dict] = OrderedDict()
        # trace id -> [request ids]: one trace can cover SEVERAL
        # generations (a /v1/consensus panel fan-out submits every
        # member under the request's one trace).
        self._trace_to_ids: dict[str, list[str]] = {}
        self._lock = threading.Lock()

    def add(self, summary: dict) -> None:
        rid = summary["id"]
        with self._lock:
            self._by_id[rid] = summary
            self._by_id.move_to_end(rid)
            tid = summary.get("trace_id")
            if tid:
                self._trace_to_ids.setdefault(tid, []).append(rid)
            while len(self._by_id) > self.max_requests:
                old_rid, old = self._by_id.popitem(last=False)
                old_tid = old.get("trace_id")
                ids = self._trace_to_ids.get(old_tid)
                if ids:
                    try:
                        ids.remove(old_rid)
                    except ValueError:
                        pass
                    if not ids:
                        del self._trace_to_ids[old_tid]

    def get_all(self, key: str) -> list[dict]:
        """Every retained summary for ``key`` — a request id (at most
        one) or a trace id (every generation that ran under that
        trace, newest first: a consensus panel is N of them)."""
        with self._lock:
            doc = self._by_id.get(key)
            if doc is not None:
                return [doc]
            return [
                self._by_id[rid]
                for rid in reversed(self._trace_to_ids.get(key, []))
                if rid in self._by_id
            ]

    def get(self, key: str) -> dict | None:
        """Lookup by request id OR trace id; for a trace shared by
        several generations, the most recently retired one."""
        docs = self.get_all(key)
        return docs[0] if docs else None

    def recent(self, limit: int = 50) -> list[dict]:
        """Newest-first."""
        with self._lock:
            items = list(self._by_id.values())
        return items[::-1][: max(0, limit)]

    def __len__(self) -> int:
        return len(self._by_id)

    def clear(self) -> None:
        with self._lock:
            self._by_id.clear()
            self._trace_to_ids.clear()


_RECORDER = FlightRecorder()
_REQUESTS = RequestLog()


def flight_recorder() -> FlightRecorder:
    return _RECORDER


def request_log() -> RequestLog:
    return _REQUESTS


def percentile(values: list[float], q: float) -> float:
    """Nearest-rank percentile of an unsorted list (0 for empty) — the
    per-request tbt_p50/p99 summary helper; nearest-rank keeps every
    reported number an actually-observed gap."""
    if not values:
        return 0.0
    vs = sorted(values)
    idx = min(len(vs) - 1, max(0, int(-(-q / 100.0 * len(vs) // 1)) - 1))
    return vs[idx]


# ---------------------------------------------------------------------------
# Chrome trace-event export (Perfetto / chrome://tracing loadable)
# ---------------------------------------------------------------------------

#: pid/tid layout of the export. Device programs land on ONE device
#: track (they are serialized on one device stream — overlap in this
#: track means the window correction is wrong, which is itself visible
#: evidence); un-overlapped host gaps on the host track; the remaining
#: typed events on the scheduler track; each request gets its own tid
#: under the requests pid.
_PID_SERVING = 1
_TID_DEVICE = 1
_TID_HOST = 2
_TID_SCHED = 3
_PID_REQUESTS = 2


def _emit_process_meta(
    out: list[dict],
    pid_serving: int,
    pid_requests: int,
    serving_name: str,
    requests_name: str,
) -> None:
    """Process/thread metadata rows for one host's pid pair."""
    out.append(
        {
            "ph": "M",
            "ts": 0,
            "pid": pid_serving,
            "tid": 0,
            "name": "process_name",
            "args": {"name": serving_name},
        }
    )
    out.append(
        {
            "ph": "M",
            "ts": 0,
            "pid": pid_requests,
            "tid": 0,
            "name": "process_name",
            "args": {"name": requests_name},
        }
    )
    for tid, name in (
        (_TID_DEVICE, "device programs"),
        (_TID_HOST, "host (un-overlapped)"),
        (_TID_SCHED, "scheduler events"),
    ):
        out.append(
            {
                "ph": "M",
                "ts": 0,
                "pid": pid_serving,
                "tid": tid,
                "name": "thread_name",
                "args": {"name": name},
            }
        )


def _emit_events(
    out: list[dict],
    events: list[FlightEvent],
    base: float,
    pid_serving: int,
    pid_requests: int,
) -> None:
    """Emit one host's flight events against a shared time base."""

    def us(t: float) -> float:
        return round((t - base) * 1e6, 3)

    req_tids: dict[str, int] = {}
    for e in events:
        args = dict(e.meta)
        if e.trace_id is not None:
            args["trace_id"] = e.trace_id
        if e.kind == "program":
            out.append(
                {
                    "name": args.get("kind", "program"),
                    "cat": "device",
                    "ph": "X",
                    "ts": us(e.t0),
                    "dur": round(e.dur * 1e6, 3),
                    "pid": pid_serving,
                    "tid": _TID_DEVICE,
                    "args": args,
                }
            )
        elif e.kind == "host":
            out.append(
                {
                    "name": "sched_host",
                    "cat": "host",
                    "ph": "X",
                    "ts": us(e.t0),
                    "dur": round(e.dur * 1e6, 3),
                    "pid": pid_serving,
                    "tid": _TID_HOST,
                    "args": args,
                }
            )
        elif e.kind == "request":
            rid = str(args.get("id", e.trace_id or e.seq))
            tid = req_tids.setdefault(rid, len(req_tids) + 1)
            out.append(
                {
                    "ph": "M",
                    "ts": 0,
                    "pid": pid_requests,
                    "tid": tid,
                    "name": "thread_name",
                    "args": {"name": rid},
                }
            )
            out.append(
                {
                    "name": rid,
                    "cat": "request",
                    "ph": "X",
                    "ts": us(e.t0),
                    "dur": round(e.dur * 1e6, 3),
                    "pid": pid_requests,
                    "tid": tid,
                    "args": args,
                }
            )
        elif e.dur > 0:
            out.append(
                {
                    "name": e.kind,
                    "cat": "scheduler",
                    "ph": "X",
                    "ts": us(e.t0),
                    "dur": round(e.dur * 1e6, 3),
                    "pid": pid_serving,
                    "tid": _TID_SCHED,
                    "args": args,
                }
            )
        else:
            out.append(
                {
                    "name": e.kind,
                    "cat": "scheduler",
                    "ph": "i",
                    "s": "t",
                    "ts": us(e.t0),
                    "pid": pid_serving,
                    "tid": _TID_SCHED,
                    "args": args,
                }
            )


def to_chrome(events: list[FlightEvent]) -> dict:
    """Chrome trace-event JSON from a flight-ring snapshot.

    Every emitted event carries ``ts``/``ph``/``pid``/``tid`` (the
    schema Perfetto's JSON importer requires); ``ts`` is microseconds
    relative to the snapshot's earliest event. Device-program slices
    (``kind == "program"``) become complete ("X") events on the device
    track — their count equals the ``gateway_device_programs_total``
    delta over the same window (a dispatched-not-yet-fetched program
    appears with its dispatch stamp and zero duration). That count
    parity is R-invariant under multi-round decode (PR 12): a program
    folding R rounds is still ONE slice, carrying ``rounds`` in its
    args (next to ``rows``/``tokens``) so the timeline shows how much
    decoding each dispatch held. Events with a
    duration become "X" slices, instantaneous ones "i" instants.
    Request-span events (``kind == "request"``, recorded at
    retirement) each get their own thread row named by request id.
    """
    out: list[dict] = []
    _emit_process_meta(
        out, _PID_SERVING, _PID_REQUESTS, "serving", "requests"
    )
    if events:
        base = min(e.t0 for e in events)
        _emit_events(out, events, base, _PID_SERVING, _PID_REQUESTS)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def merge_fleet(
    events_by_host: dict[str, tuple[list[FlightEvent], float]],
) -> list[FlightEvent]:
    """Merge per-host flight rings onto ONE timebase (PR 20).

    ``events_by_host`` maps a host label to ``(events, offset_s)``
    where ``offset_s`` translates that host's ``perf_counter`` stamps
    into the caller's (the front tier's) clock:
    ``t_front ≈ t_host + offset_s`` — the midpoint estimate from the
    RTT-halving probe piggybacked on peer ``/debug/chains`` and store
    stats replies. Returns new events (inputs untouched) with
    corrected ``t0`` and a ``host`` meta key, sorted by corrected
    ``t0`` so a joined trace reads monotonically across processes.
    """
    merged: list[FlightEvent] = []
    for host, (events, offset) in events_by_host.items():
        for e in events:
            merged.append(
                FlightEvent(
                    seq=e.seq,
                    kind=e.kind,
                    t0=e.t0 + offset,
                    dur=e.dur,
                    trace_id=e.trace_id,
                    meta={**e.meta, "host": host},
                )
            )
    merged.sort(key=lambda e: (e.t0, e.meta.get("host", ""), e.seq))
    return merged


def to_chrome_fleet(
    events_by_host: dict[str, tuple[list[FlightEvent], float]],
) -> dict:
    """Fleet Chrome export: one ``pid`` pair per host (PR 20).

    Same per-event schema as :func:`to_chrome`, but each host's
    events land under its own serving/requests process pair (named
    ``"<host> serving"`` / ``"<host> requests"``) against ONE global
    time base computed over the clock-corrected stamps — so a single
    request forwarded front→prefill→store→decode renders as one
    aligned lane across every process that touched it.
    """
    out: list[dict] = []
    hosts = list(events_by_host)
    corrected = {
        host: [
            FlightEvent(
                seq=e.seq,
                kind=e.kind,
                t0=e.t0 + offset,
                dur=e.dur,
                trace_id=e.trace_id,
                meta=e.meta,
            )
            for e in events
        ]
        for host, (events, offset) in events_by_host.items()
    }
    for i, host in enumerate(hosts):
        _emit_process_meta(
            out,
            10 * i + 1,
            10 * i + 2,
            f"{host} serving",
            f"{host} requests",
        )
    all_events = [e for evs in corrected.values() for e in evs]
    if all_events:
        base = min(e.t0 for e in all_events)
        for i, host in enumerate(hosts):
            _emit_events(
                out, corrected[host], base, 10 * i + 1, 10 * i + 2
            )
    return {"traceEvents": out, "displayTimeUnit": "ms"}
