"""Multi-model serving plane (PR 18): N independent engines, one gateway.

The paper's consensus protocol is a HETEROGENEOUS panel — distinct
personas, ideally distinct models — yet until this PR every panel
member decoded on one engine. :class:`ModelSet` owns N members, each a
complete engine (its own :class:`~llm_consensus_tpu.serving.continuous.
ContinuousBatcher` or :class:`~llm_consensus_tpu.serving.fleet.
ReplicaSet`, config, params, mesh), behind ONE gateway with one shared
metrics/trace plane. Three things make it more than a dict of engines:

- **Cross-model speculation**: a member may name another member as its
  ``draft_from`` donor. The donor's (cfg, params) mount as the PR-9
  draft, with a :mod:`~llm_consensus_tpu.serving.vocab_align` remap
  bridging the tokenizer boundary — the small proposer literally
  accelerates the large judge through the existing Leviathan verify,
  mirrored draft pool, 4-plane host-tier entries, and PR-15 adaptive
  ``spec_k``, all unchanged. Below-threshold vocab coverage disengages
  the pairing with a construction warning (never silently).
- **Per-model admission lanes**: :meth:`ModelSet.admission_lanes`
  yields one ``model:<name>`` priority lane per member for the
  gateway's :class:`~llm_consensus_tpu.server.admission.
  AdmissionConfig`; the gateway defaults a request's priority to its
  model's lane so one member's burst queues behind its own bound, not
  the panel's.
- **Consensus phase routing**: :meth:`phase_models` maps
  propose → the draft-donor members (small, cheap, diverse) and
  evaluate/refine → the default member (large), which the Coordinator
  consumes via ``CoordinatorConfig.phase_models`` — "move the query,
  not the cache".

:class:`ModelSetBackend` is the Backend seam: requests dispatch on
``GenerationRequest.model`` (None = default member), batches split per
member and fan out concurrently, and the fleet surfaces the gateway
relies on (health, prefix_probe with per-model chain scopes,
request_cost, prefetch, preempt hooks) aggregate across members.
"""

from __future__ import annotations

import asyncio
import logging
import threading
from dataclasses import dataclass, field

from llm_consensus_tpu.backends import base as _backend_base
from llm_consensus_tpu.engine.tokenizer import ByteTokenizer, Tokenizer
from llm_consensus_tpu.serving.vocab_align import VocabMap, align_vocabs
from llm_consensus_tpu.server.metrics import (
    MODEL_REQUESTS as _M_MODEL_REQUESTS,
)
from llm_consensus_tpu.server.metrics import (
    MODEL_TOKENS as _M_MODEL_TOKENS,
)
from llm_consensus_tpu.server.metrics import (
    SPEC_XMODEL_COVERAGE as _M_XMODEL_COVERAGE,
)

__all__ = ["ModelSpec", "ModelSet", "ModelSetBackend"]

log = logging.getLogger(__name__)


@dataclass
class ModelSpec:
    """One ModelSet member: a complete engine description.

    ``draft_from`` names ANOTHER member whose (cfg, params) should
    mount as this member's speculative draft — the cross-model pairing.
    ``fleet`` (a FleetConfig with replicas > 1) puts a ReplicaSet
    behind this member instead of a single batcher; ``control`` (a
    ControlConfig) engages PR-15 adaptive control. ``config`` defaults
    to a fresh ContinuousConfig — members NEVER share config instances
    (each member's live knobs are its own; sharing across models is
    exactly the aliasing the ReplicaSet contract reserves for
    same-model replicas).
    """

    name: str
    cfg: object
    params: dict
    tokenizer: Tokenizer | None = None
    config: object = None
    mesh: object = None
    fleet: object = None
    draft_from: str | None = None
    control: object = None
    # Precomputed draft->target alignment for the ``draft_from``
    # pairing, already sized to MODEL vocabs (see VocabMap.sized_to).
    # None = derive from the two tokenizers via align_vocabs. Callers
    # with structural knowledge the tokenizers can't express (e.g. a
    # shared padded-tail convention between related checkpoints) pass
    # their own.
    vocab_map: VocabMap | None = None


@dataclass
class _Member:
    spec: ModelSpec
    engine: object  # ContinuousBatcher | ReplicaSet
    backend: object  # ContinuousBackend | FleetBackend
    draft_pair: str | None = None  # engaged donor name, None = no draft
    vocab_map: VocabMap | None = None
    requests: int = 0
    tokens: int = 0
    lock: object = field(default_factory=threading.Lock)


class ModelSet:
    """N independent engines behind one gateway — see module doc."""

    def __init__(
        self,
        specs: list[ModelSpec],
        *,
        default: str | None = None,
        host_store=None,
        min_draft_coverage: float = 0.5,
    ):
        if not specs:
            raise ValueError("a ModelSet needs at least one member")
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate member names: {names}")
        by_name = {s.name: s for s in specs}
        self.default = default or names[0]
        if self.default not in by_name:
            raise ValueError(
                f"default model {self.default!r} is not a member "
                f"(have {names})"
            )
        self.members: dict[str, _Member] = {}
        for spec in specs:
            if spec.tokenizer is None:
                spec.tokenizer = ByteTokenizer()
            draft = None
            dmap = None
            pair = None
            if spec.draft_from is not None:
                donor = by_name.get(spec.draft_from)
                if donor is None:
                    raise ValueError(
                        f"member {spec.name!r} names draft_from="
                        f"{spec.draft_from!r}, which is not a member "
                        f"(have {names})"
                    )
                if donor is spec:
                    raise ValueError(
                        f"member {spec.name!r} cannot draft from itself"
                    )
                if spec.vocab_map is not None:
                    # Caller-supplied alignment: trusted as-is (the
                    # engine still shape-checks it against both cfgs).
                    dmap = spec.vocab_map
                else:
                    dmap = align_vocabs(
                        spec.tokenizer,
                        donor.tokenizer or ByteTokenizer(),
                        min_coverage=min_draft_coverage,
                    )
                if dmap is None:
                    # align_vocabs already warned with the coverage
                    # numbers; name the pairing so the operator knows
                    # WHICH member lost its draft.
                    log.warning(
                        "member %r: cross-model draft pairing with %r "
                        "disengaged (vocab coverage below %.0f%%) — "
                        "serving without speculation",
                        spec.name,
                        spec.draft_from,
                        100.0 * min_draft_coverage,
                    )
                else:
                    cconf = spec.config
                    if cconf is not None and cconf.spec_k <= 0:
                        raise ValueError(
                            f"member {spec.name!r} pairs draft_from="
                            f"{spec.draft_from!r} but its config has "
                            f"spec_k={cconf.spec_k}: the pairing needs "
                            "spec_k > 0 to size the verify program"
                        )
                    # Alignment runs in tokenizer space; the batcher
                    # gathers with MODEL ids, so size the tables to the
                    # (possibly padded) model vocabs before handoff.
                    donor_tok = donor.tokenizer or ByteTokenizer()
                    dmap = dmap.sized_to(
                        spec.cfg.vocab_size,
                        donor.cfg.vocab_size,
                        target_pad=spec.tokenizer.pad_id,
                        draft_pad=donor_tok.pad_id,
                    )
                    draft = (donor.cfg, donor.params)
                    pair = donor.name
                    _M_XMODEL_COVERAGE.set(dmap.coverage)
            engine, backend = self._build_engine(
                spec, draft, dmap, host_store
            )
            self.members[spec.name] = _Member(
                spec=spec,
                engine=engine,
                backend=backend,
                draft_pair=pair,
                vocab_map=dmap,
            )
        self._audit_engage()

    @staticmethod
    def _build_engine(spec: ModelSpec, draft, dmap, host_store):
        from llm_consensus_tpu.serving.continuous import (
            ContinuousBackend,
            ContinuousBatcher,
            ContinuousConfig,
        )

        config = spec.config if spec.config is not None else (
            ContinuousConfig()
        )
        spec.config = config
        fleet = spec.fleet
        if fleet is not None and getattr(fleet, "replicas", 1) > 1:
            from llm_consensus_tpu.serving.fleet import (
                FleetBackend,
                ReplicaSet,
            )

            rs = ReplicaSet(
                spec.cfg,
                spec.params,
                tokenizer=spec.tokenizer,
                config=config,
                fleet=fleet,
                mesh=spec.mesh,
                draft=draft,
                draft_map=dmap,
                control=spec.control,
                host_store=host_store,
            )
            return rs, FleetBackend(rs)
        controller = None
        if spec.control is not None:
            from llm_consensus_tpu.serving.control import (
                AdaptiveController,
            )

            controller = AdaptiveController(spec.control)
        b = ContinuousBatcher(
            spec.cfg,
            spec.params,
            tokenizer=spec.tokenizer,
            config=config,
            mesh=spec.mesh,
            draft=draft,
            draft_map=dmap,
            host_store=host_store,
            controller=controller,
        )
        return b, ContinuousBackend(b)

    # -- engage audit ---------------------------------------------------

    def engage_matrix(self) -> dict[str, dict]:
        """Per-member engage state of every serving feature — the
        construction audit's data, and the bench/README "engage matrix
        row per model". Each value is True (engaged), False (not
        configured), or a string naming WHY a configured feature will
        not engage (the batcher's own warnings fire for the same
        conditions; this is the queryable mirror)."""
        out: dict[str, dict] = {}
        for name, m in self.members.items():
            c = m.spec.config
            spec_state: object = False
            if m.draft_pair is not None:
                if c.spec_k <= 0:
                    spec_state = "spec_k == 0"
                elif c.steps_per_sync > 1:
                    spec_state = "steps_per_sync > 1"
                elif not c.spec_decode:
                    spec_state = "spec_decode flipped off"
                else:
                    spec_state = True
            rounds_state: object = False
            if c.decode_rounds > 1:
                rounds_state = (
                    True
                    if c.steps_per_sync == 1
                    else "steps_per_sync > 1"
                )
            tier_state: object = False
            if c.host_cache_bytes > 0:
                if c.share_prefix and c.prefill_chunk > 0:
                    tier_state = True
                else:
                    tier_state = "needs share_prefix + prefill_chunk > 0"
            out[name] = {
                "default": name == self.default,
                "cross_model_spec": spec_state,
                "draft_from": m.draft_pair,
                "vocab_coverage": (
                    round(m.vocab_map.coverage, 4)
                    if m.vocab_map is not None
                    else None
                ),
                "decode_rounds": rounds_state,
                "host_tier": tier_state,
                "adaptive_control": m.spec.control is not None,
                "replicas": getattr(m.spec.fleet, "replicas", 1),
            }
        return out

    def _audit_engage(self) -> None:
        """No-silent-disengage (PR 18 acceptance): every configured
        feature either engages or gets named in a warning, per member,
        at construction."""
        for name, row in self.engage_matrix().items():
            for feature in ("cross_model_spec", "decode_rounds",
                            "host_tier"):
                state = row[feature]
                if isinstance(state, str):
                    log.warning(
                        "member %r: %s configured but will not engage "
                        "(%s)", name, feature, state,
                    )
            log.info("modelset member %r engage: %s", name, row)

    # -- consensus routing ----------------------------------------------

    def phase_models(self) -> dict[str, str] | None:
        """Default consensus phase routing: propose on the draft-donor
        members (small, cheap — their caches already hold the panel
        header via the cross-model draft pairing), evaluate/refine on
        the default member (large). None when no member pairs a donor
        — a homogeneous set routes nothing."""
        donors = {
            m.draft_pair
            for m in self.members.values()
            if m.draft_pair is not None
        }
        if not donors:
            return None
        # Deterministic pick: the first donor in member order.
        propose = next(
            n for n in self.members if n in donors
        )
        return {
            "propose": propose,
            "evaluate": self.default,
            "refine": self.default,
        }

    def admission_lanes(self) -> tuple[str, ...]:
        """One ``model:<name>`` admission lane per member (gateway
        priorities beyond the base interactive/batch pair)."""
        return tuple(f"model:{n}" for n in self.members)

    # -- aggregate fleet surface ----------------------------------------

    def stats(self) -> dict:
        """Shared-plane snapshot: per-member engine stats plus the
        dispatch split (the ``gateway_model_*`` families' stats()
        mirror, lockstep by construction — both are fed from
        ModelSetBackend's one dispatch site)."""
        per = {}
        for name, m in self.members.items():
            with m.lock:
                doc = {"requests": m.requests, "tokens": m.tokens}
            doc["engine"] = m.engine.stats()
            doc["draft_from"] = m.draft_pair
            per[name] = doc
        return {
            "members": list(self.members),
            "default": self.default,
            "per_model": per,
            "engage": self.engage_matrix(),
        }

    def close(self) -> None:
        for m in self.members.values():
            m.engine.close()


class ModelSetBackend(_backend_base.Backend):
    """Backend seam over a :class:`ModelSet`: requests dispatch on
    ``GenerationRequest.model`` (None = the set's default member), a
    batch splits per member and fans out concurrently — one panel
    fan-out drives N engines at once."""

    def __init__(self, modelset: ModelSet):
        self.modelset = modelset

    def member_backend(self, model: str | None):
        """Resolve a request's model tag to a member backend. Unknown
        tags raise — a typo'd model must 400 at the gateway, not
        silently serve from the default weights."""
        ms = self.modelset
        if model is None:
            model = ms.default
        m = ms.members.get(model)
        if m is None:
            raise _backend_base.BackendError(
                f"unknown model {model!r} (have {list(ms.members)})"
            )
        return m

    async def generate_batch(self, requests):
        ms = self.modelset
        groups: dict[str, list[int]] = {}
        for i, r in enumerate(requests):
            name = r.model if r.model is not None else ms.default
            if name not in ms.members:
                raise _backend_base.BackendError(
                    f"unknown model {name!r} (have {list(ms.members)})"
                )
            groups.setdefault(name, []).append(i)
        results: list = [None] * len(requests)

        async def run(name: str, idxs: list[int]):
            m = ms.members[name]
            outs = await m.backend.generate_batch(
                [requests[i] for i in idxs]
            )
            toks = sum(o.num_tokens for o in outs)
            _M_MODEL_REQUESTS.labels(model=name).inc(len(idxs))
            _M_MODEL_TOKENS.labels(model=name).inc(toks)
            with m.lock:
                m.requests += len(idxs)
                m.tokens += toks
            for i, o in zip(idxs, outs):
                results[i] = o

        await asyncio.gather(
            *(run(name, idxs) for name, idxs in groups.items())
        )
        return results

    # -- gateway surfaces ------------------------------------------------

    def health(self) -> dict:
        """Aggregate /readyz heartbeat: alive only when EVERY member's
        engine is (one wedged model degrades the whole panel — the
        consensus protocol needs all phases servable); per-member
        entries name the wedged one."""
        docs = {
            name: m.engine.heartbeat()
            for name, m in self.modelset.members.items()
        }
        ages = [d["last_tick_age_s"] for d in docs.values()]
        steps = [
            d["last_step_age_s"]
            for d in docs.values()
            if d.get("last_step_age_s") is not None
        ]
        return {
            "alive": all(d["alive"] for d in docs.values()),
            "last_tick_age_s": max(ages),
            "last_step_age_s": max(steps) if steps else None,
            "models": docs,
        }

    @property
    def tokenizer(self):
        """The DEFAULT member's tokenizer (``/debug/chains``'s
        ``?prompt=`` encoding; per-member probes re-encode below)."""
        ms = self.modelset
        return ms.members[ms.default].spec.tokenizer

    def prefix_probe(self, ids) -> dict:
        """``/debug/chains`` across the whole set: the top-level
        registry/host numbers keep the single-engine shape (the
        DEFAULT member's view — peer routing compares those), and
        ``models`` carries every member's own scoped probe so a
        heterogeneous front tier can tell whose chains it is counting
        (the ids land verbatim on members sharing the default's
        tokenizer; others re-encode through their own)."""
        ms = self.modelset
        default_tok = ms.members[ms.default].spec.tokenizer
        text = None
        per = {}
        for name, m in ms.members.items():
            mids = ids
            tok = m.spec.tokenizer
            if name != ms.default and tok is not default_tok:
                if text is None:
                    text = default_tok.decode(ids)
                mids = tok.encode(text)
            per[name] = m.engine.prefix_probe(mids)
        top = per[ms.default]
        return {
            "registry_tokens": top["registry_tokens"],
            "host_tokens": top["host_tokens"],
            "scope": top.get("scope"),
            "models": per,
        }

    def request_cost(self, prompt: str, max_new_tokens: int) -> float:
        """Cost-budget admission pricing (PR 15): the DEFAULT member's
        modeled bytes — the gateway prices before it knows the model
        split, and the default (large) member is the conservative
        anchor."""
        ms = self.modelset
        m = ms.members[ms.default]
        batcher = getattr(m.engine, "batchers", None)
        b = batcher[0] if batcher else m.engine
        return b.modeled_request_cost(
            len(m.spec.tokenizer.encode(prompt)), max_new_tokens
        )

    def prefetch(self, prompt: str) -> bool:
        """Enqueue-time restore prefetch (PR 17) on the default member
        (the one whose host tier most likely holds the chain)."""
        ms = self.modelset
        m = ms.members[ms.default]
        pf = getattr(m.backend, "prefetch", None)
        if callable(pf):
            return bool(pf(prompt))
        return False

    def preempt_for_admission(self) -> bool:
        """Overflow hook: let ANY member free pool pages — the gateway
        queue is shared, so whichever engine can demote helps."""
        did = False
        for m in self.modelset.members.values():
            hook = getattr(m.engine, "preempt_for_admission", None)
            if callable(hook):
                try:
                    did = bool(hook()) or did
                except Exception:  # noqa: BLE001 - advisory hook
                    log.exception("member preempt hook failed")
        return did

    async def close(self) -> None:
        self.modelset.close()
