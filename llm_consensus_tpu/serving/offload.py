"""Host-RAM offload tier under the paged prefix registry (PR 4).

PRs 2-3 made the consensus panel's shared prompt prefix free in HBM
capacity (CoW page sharing) and decode bandwidth (group-aware
attention) — but only while the pages stay resident:
:meth:`~llm_consensus_tpu.models.paged_cache.PrefixRegistry.evict`
permanently dropped registry-only pages under pool pressure, so the
protocol's multi-round traffic (propose → evaluate → refine, each round
re-sending the same huge header) re-prefilled prefixes the chip
computed minutes ago. This module turns that eviction into DEMOTION:

- **Demote** — the registry's ``on_evict`` hook hands each ready victim
  page to the batcher, which ``jax.device_get``s its K/V planes into
  this byte-budgeted :class:`HostPageStore`. Pages spill VERBATIM in
  the pool's own dtype (an int8-KV pool's quantized pages travel with
  whatever scale planes the caller passes) — no recompression, so a
  restored page is bit-identical to the one that left.
- **Restore** — admission falls through registry-miss → host-hit: the
  matched chain extends through host-resident pages, which are
  allocated fresh device pages, re-registered (ready=False), and
  promoted back via async ``device_put`` + ``install_page`` scheduled
  BETWEEN decode steps, exactly like chunked prefill. The per-page
  readiness gates PR 2 built make a same-prefix burst dedup against an
  in-flight *restore* the same way it dedups against an in-flight
  prefill.

Keys are full token CHAINS (every token from the prefix root through
the page's end), not per-page runs: a page's K/V content is a function
of its whole context, so the chain is the only sound identity. The
store is a plain LRU over ``budget_bytes`` — overflow drops the
least-recently-used page cleanly (the tier below host RAM is
recompute, which is always correct).

Host-side only and jax-free on the hot paths (plain numpy + an
OrderedDict); the batcher owns the device transfers.

**Fleet-scoped since PR 14** (:mod:`llm_consensus_tpu.serving.fleet`):
one store can back N batcher replicas, so any replica can restore a
chain any other replica demoted — the page transport behind both
preempt-to-host-tier and chain rebalancing. Two consequences:

- The store is now THREAD-SAFE: every method holds one internal lock,
  and the check-then-act demote race ("is the chain resident? then
  refresh, else fetch") is closed by :meth:`touch` returning whether
  the key was still resident — a concurrent LRU drop between a
  caller's probe and its ``touch`` degrades to a fresh ``put``, never
  a silent recency update of a ghost entry.
- Callers that share a store MUST namespace their keys by model/config
  identity (the batcher prepends its
  :attr:`~llm_consensus_tpu.serving.continuous.ContinuousBatcher`
  store scope — config dims, page size, pool dtype, and a weights
  fingerprint): a page's bytes are a function of the weights that
  wrote it, so heterogeneous replicas must never cross-restore. The
  store itself stays key-agnostic (tests use bare chains on private
  stores).

Mesh-native since PR 13: on a dp×mp mesh the demote ``device_get``
assembles a page's sharded plane slices into one host buffer and the
restore ``install_page`` scatters it back through the pool's
NamedSharding — the round trip stays bit-identical (tested on
dp2×mp2), and the store itself is topology-blind (it only ever sees
host numpy planes). Per-shard streaming of the slices is a
chip-transport optimization the correctness contract doesn't depend
on.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Sequence

import numpy as np

__all__ = ["HostPageStore", "page_planes"]

#: A host-resident page: one numpy array per cache plane (k, v, and for
#: quantized pools their scale planes), stored verbatim.
Planes = tuple


class HostPageStore:
    """Byte-budgeted LRU store of demoted KV pages, keyed by token chain.

    ``put`` accepts a tuple of numpy planes and accounts their exact
    ``nbytes``; when the budget overflows, least-recently-used entries
    drop (counted in :attr:`dropped_pages` — the tier below host RAM is
    recompute). ``get`` returns the planes verbatim and refreshes
    recency; entries SURVIVE a restore, so a prefix that round-trips
    HBM → host → HBM → evicted again re-demotes without a second
    device fetch (:meth:`touch` lets the demote hook skip the
    ``device_get``).

    Thread-safe (PR 14): one lock serializes every mutation, so N
    fleet replicas can demote/restore concurrently — counters, the
    LRU order, and the byte accounting stay exact under interleaving.
    """

    def __init__(self, budget_bytes: int):
        if budget_bytes <= 0:
            raise ValueError(f"budget_bytes must be > 0, got {budget_bytes}")
        self.budget_bytes = int(budget_bytes)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, Planes]" = OrderedDict()
        self._bytes = 0
        # Monotonic counters (the serving layer exports these).
        self.demoted_pages = 0
        self.dropped_pages = 0
        self.lookups = 0
        self.hits = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key: tuple) -> bool:
        with self._lock:
            return key in self._entries

    @property
    def bytes_used(self) -> int:
        return self._bytes

    @property
    def headroom_bytes(self) -> int:
        """Budget not yet occupied — the fleet router's "can the tier
        absorb a preempted page without dropping someone else's work"
        signal (:meth:`ReplicaSet.preempt_for_admission`)."""
        with self._lock:
            return max(0, self.budget_bytes - self._bytes)

    @staticmethod
    def _nbytes(planes: Planes) -> int:
        return sum(int(p.nbytes) for p in planes)

    def put(self, key: tuple, planes: Sequence[np.ndarray]) -> bool:
        """Demote one page's planes. Returns True when resident after
        the call (a page bigger than the whole budget is refused — it
        could only live by evicting everything for one entry)."""
        resident, _, _ = self.put_counted(key, planes)
        return resident

    def put_counted(
        self, key: tuple, planes: Sequence[np.ndarray]
    ) -> tuple[bool, int, int]:
        """:meth:`put` returning ``(resident, demoted, dropped)`` —
        THIS call's own counter deltas, computed under the lock. On a
        fleet-shared store a caller must not reconstruct its deltas
        from the global counters around a call: a concurrent replica's
        puts interleave and would be double-counted into both
        replicas' Prometheus increments."""
        planes = tuple(np.asarray(p) for p in planes)
        nbytes = self._nbytes(planes)
        with self._lock:
            if key in self._entries:
                # Same chain => same content (KV is a deterministic
                # function of the chain — scoped keys pin the weights
                # too); refresh recency, keep the original bytes. Two
                # replicas racing the same demote land here: the second
                # put degrades to a refresh, never double-accounting
                # bytes.
                self._entries.move_to_end(key)
                self.demoted_pages += 1
                return True, 1, 0
            if nbytes > self.budget_bytes:
                self.dropped_pages += 1
                return False, 0, 1
            self._entries[key] = planes
            self._bytes += nbytes
            self.demoted_pages += 1
            dropped = 0
            while self._bytes > self.budget_bytes:
                _, victim = self._entries.popitem(last=False)
                self._bytes -= self._nbytes(victim)
                self.dropped_pages += 1
                dropped += 1
            return True, 1, dropped

    def touch(self, key: tuple) -> bool:
        """Re-demotion of a chain already resident: same chain => same
        content, so only recency moves — no second device fetch, no
        byte-accounting change. Returns False when the key is GONE (a
        concurrent LRU drop won the race between the caller's probe
        and this call) — the caller must then fetch + :meth:`put` like
        a fresh demotion instead of assuming residency."""
        with self._lock:
            if key not in self._entries:
                return False
            self._entries.move_to_end(key)
            self.demoted_pages += 1
            return True

    def get(self, key: tuple) -> Planes | None:
        """Planes for ``key`` (verbatim), refreshing recency; None on
        miss. The entry stays resident — restore does not consume it."""
        with self._lock:
            self.lookups += 1
            planes = self._entries.get(key)
            if planes is None:
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return planes

    # -- batched surface (PR 17) ----------------------------------------
    # One call per PLAN instead of one per page: over the remote
    # transport each method below is a single round trip, and the
    # in-process implementations here keep the interface identical so
    # the batcher never branches on store locality. Each loops the
    # per-key primitive (one lock acquisition per key) — exactness of
    # the LRU/byte accounting matters more than shaving lock hops in
    # a host-RAM tier whose unit of work is a megabyte-scale memcpy.

    def put_many(
        self, items: Sequence[tuple[tuple, Sequence[np.ndarray]]]
    ) -> list[tuple[bool, int, int]]:
        """:meth:`put_counted` for a batch; one delta triple per item,
        in order."""
        return [self.put_counted(key, planes) for key, planes in items]

    def touch_many(self, keys: Sequence[tuple]) -> list[bool]:
        """:meth:`touch` for a batch; one residency flag per key."""
        return [self.touch(k) for k in keys]

    def get_run(self, keys: Sequence[tuple]) -> list[Planes]:
        """Planes for the longest contiguous PREFIX of ``keys`` that is
        resident, stopping at the first miss. Chain keys are prefix-
        nested (page k+1's chain extends page k's), so a restore plan
        only ever wants a prefix run — a hit after a gap could not be
        installed anyway. Recency refreshes exactly like :meth:`get`."""
        out: list[Planes] = []
        for k in keys:
            planes = self.get(k)
            if planes is None:
                break
            out.append(planes)
        return out

    def run_len(self, keys: Sequence[tuple]) -> int:
        """Length of the contiguous resident prefix of ``keys`` WITHOUT
        moving plane bytes or recency (pure probe — the router's
        prefix_probe extension walk)."""
        n = 0
        with self._lock:
            for k in keys:
                if k not in self._entries:
                    break
                n += 1
        return n

    def stats_snapshot(self) -> dict:
        """Every counter plus occupancy, read under ONE lock hold — the
        consistent view the remote page-store server piggybacks on each
        response frame and the fleet stats() reads once per pull (N
        separate property reads could interleave with a concurrent
        demote and report hits > lookups)."""
        with self._lock:
            return {
                "pages": len(self._entries),
                "bytes_used": self._bytes,
                "budget_bytes": self.budget_bytes,
                "headroom_bytes": max(0, self.budget_bytes - self._bytes),
                "demoted_pages": self.demoted_pages,
                "dropped_pages": self.dropped_pages,
                "lookups": self.lookups,
                "hits": self.hits,
            }


def page_planes(cache, page: int) -> tuple[np.ndarray, np.ndarray]:
    """Fetch one page's (k, v) planes to host, verbatim dtype.

    One blocking ``device_get`` ([L, page, Hkv, Dh] each — a 1B-class
    config at page 64 is ~1.5 MiB bf16). The single-page primitive for
    tests and tools; the batcher's demote hook batches an evict walk's
    victims into ONE device_get instead of calling this per page.
    """
    import jax

    return jax.device_get((cache.k[:, page], cache.v[:, page]))
