"""Remote host page store: the fleet KV tier across processes (PR 16).

PR 14 made :class:`~llm_consensus_tpu.serving.offload.HostPageStore`
the fleet's page transport — thread-safe, chain-keyed, scoped by each
batcher's config dims + weights fingerprint so heterogeneous replicas
can never cross-restore. But it is in-memory, which confines the fleet
to one process. This module lifts the SAME interface onto a socket:

- :class:`PageStoreServer` wraps ONE authoritative ``HostPageStore``
  behind a TCP or Unix-domain transport. There is no negotiation in
  the protocol because none is needed: the PR-14 ``(scope, chain)``
  keys already carry config dims and the weights fingerprint, so a
  process whose scope differs simply never hits.
- :class:`RemotePageStore` is a client implementing the full
  ``HostPageStore`` surface (``put_counted`` / ``touch`` / ``get`` /
  ``__contains__`` / ``headroom_bytes`` / the counters, plus the PR-17
  batched ``put_many`` / ``get_run`` / ``touch_many`` / ``run_len``),
  so ``ReplicaSet`` / ``ContinuousBatcher(host_store=)`` take a local
  store or a remote one transparently — 4-plane target+draft entries
  included (the store layer is plane-count agnostic).

**Failure contract — degrade, never wedge.** Every client failure
(connect refused, peer disconnect mid-``put``, a slow peer hitting the
client timeout) degrades to a local MISS: ``get`` returns None,
``touch``/``__contains__`` return False, ``put_counted`` reports the
page dropped — so the worker loop recomputes via chunked prefill
(always correct) instead of stalling. Each failure increments
``gateway_remote_store_errors_total``, logs ONE warning per outage
(not per op), records a ``remote_store`` flight event on the
transition, and opens the circuit for ``retry_s`` seconds — ops during
the open window miss immediately with no socket attempt, so a dead
peer costs the worker loop nothing per iteration (heartbeat stays
fresh; tested).

**Cheap reads by piggyback.** Every server response frame carries the
authoritative store's :meth:`stats_snapshot`, which the client caches;
``headroom_bytes`` / ``bytes_used`` / ``len`` / the counters read the
cache and NEVER touch the network — the admission overflow hook reads
headroom on the asyncio event loop, where a blocking RTT would freeze
the gateway under exactly the overload the hook exists to absorb.
``gateway_remote_store_bytes`` mirrors the cached occupancy;
``gateway_remote_store_rtt_seconds`` observes each successful
exchange; ``gateway_transfer_bytes_total{dir}`` counts plane payload
bytes crossing the wire either way.

**Wire format v2 (PR 17) — zero-copy scatter-gather.** A frame is::

    prelude(20B) || pickled header || raw plane bytes

with prelude ``>2sBxIIQ`` = magic ``b"KV"``, version, pad, a u32
sequence tag, header length, body length. Plane arrays are NOT
pickled: the header carries ``(dtype_name, shape, nbytes)`` descriptor
groups and the body is the concatenated raw bytes, written with ONE
``sendmsg`` scatter-gather pass over memoryviews (no ``tobytes()``
staging copy) and read with ``recv_into`` straight into preallocated
numpy buffers (no pickle reassembly copy). The sequence tag makes the
connection PIPELINED: many ops fly in-flight concurrently over one
socket (a dedicated receiver thread dispatches replies by tag), so K
replicas stop serializing through one lock-held round trip. Batched
ops (``put_many``, ``get_run``) make a whole export batch or restore
plan a single round trip. Dtypes travel by NAME so ml_dtypes
extension dtypes (bfloat16 et al.) survive the trip.

**Wire format v1 (PR 16)** — ``4-byte big-endian length || pickle
payload`` with planes as ``(dtype, shape, bytes)`` triples — is still
spoken by the server (it sniffs the first two bytes per frame: v2
frames open with ``b"KV"``, which as a v1 length prefix would mean a
>1 GiB frame, far past ``_MAX_FRAME``) and by
``RemotePageStore(wire="v1")``, which keeps the one-lock synchronous
client as the measured baseline for the transport A/B bench leg.

Pickle headers are a FLEET-INTERNAL trust boundary (bind
localhost/UDS, same deployment): the transport authenticates nothing,
exactly like the in-process store it replaces.
"""

from __future__ import annotations

import logging
import pickle
import socket
import struct
import threading
import time
from typing import Sequence

import numpy as np

from llm_consensus_tpu.server.metrics import (
    REMOTE_STORE_BYTES as _M_BYTES,
)
from llm_consensus_tpu.server.metrics import (
    REMOTE_STORE_ERRORS as _M_ERRORS,
)
from llm_consensus_tpu.server.metrics import (
    REMOTE_STORE_RTT as _M_RTT,
)
from llm_consensus_tpu.server.metrics import (
    TRANSFER_BYTES as _M_XFER,
)
from llm_consensus_tpu.serving.offload import HostPageStore
from llm_consensus_tpu.utils import tracing as _tracing

log = logging.getLogger(__name__)

#: v2 ops that move plane bytes (PR 20): the only ones worth a
#: ``store_op`` span/flight event — control ops (touch/contains/stats)
#: would flood the bounded ring from the worker loop for no
#: attribution value.
_DATA_OPS = frozenset({"put_counted", "put_many", "get", "get_run"})

__all__ = ["PageStoreServer", "RemotePageStore", "parse_endpoint"]

_LEN = struct.Struct(">I")
#: Refuse frames past this (a corrupt length prefix must not allocate
#: gigabytes): generous for any real page payload (a 1B-class bf16
#: page is ~1.5 MiB; 4-plane int8+scales entries are smaller).
_MAX_FRAME = 256 << 20

#: v2 frame prelude: magic, version, pad, sequence tag, header length,
#: body (raw plane bytes) length.
_PRELUDE = struct.Struct(">2sBxIIQ")
_MAGIC = b"KV"
#: Scatter-gather buffers per ``sendmsg`` call — conservatively under
#: Linux's UIO_MAXIOV (1024); longer vectors chunk across calls.
_IOV_MAX = 512


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> bytes:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > _MAX_FRAME:
        raise ConnectionError(f"frame length {n} exceeds cap {_MAX_FRAME}")
    return _recv_exact(sock, n)


def _recv_exact_into(sock: socket.socket, mv: memoryview) -> None:
    """Fill ``mv`` completely from the socket — the zero-copy receive
    half (bytes land straight in the caller's preallocated buffer)."""
    got = 0
    while got < len(mv):
        n = sock.recv_into(mv[got:])
        if n == 0:
            raise ConnectionError("peer closed mid-frame")
        got += n


def _send_vec(sock: socket.socket, views: list) -> None:
    """Scatter-gather send: one ``sendmsg`` pass over the frame's
    memoryviews (prelude+header, then each plane's buffer) instead of
    concatenating into a staging bytes object. Handles partial sends
    and chunks vectors longer than the iovec limit."""
    views = [memoryview(v) for v in views]
    views = [v for v in views if len(v)]
    if not hasattr(sock, "sendmsg"):  # pragma: no cover - non-POSIX
        sock.sendall(b"".join(views))
        return
    i = 0
    while i < len(views):
        sent = sock.sendmsg(views[i : i + _IOV_MAX])
        while sent > 0:
            v = views[i]
            if sent >= len(v):
                sent -= len(v)
                i += 1
            else:
                views[i] = v[sent:]
                sent = 0


def _plane_view(a: np.ndarray) -> memoryview:
    # uint8 view rather than memoryview(a) directly: ml_dtypes
    # extension dtypes don't export a buffer format numpy will cast.
    return memoryview(a.view(np.uint8).reshape(-1))


def _pack_frame(seq: int, payload, groups: Sequence) -> tuple[list, int]:
    """Build a v2 frame as a list of buffers for :func:`_send_vec`.

    ``groups`` is a sequence of plane tuples; each plane contributes a
    ``(dtype_name, shape, nbytes)`` descriptor to the pickled header
    and its raw buffer to the frame tail — the arrays themselves are
    never copied or pickled. Returns ``(buffers, body_bytes)``."""
    descs = []
    views: list = []
    body = 0
    for planes in groups:
        gd = []
        for p in planes:
            a = np.ascontiguousarray(p)
            n = int(a.nbytes)
            gd.append((a.dtype.name, a.shape, n))
            if n:
                views.append(_plane_view(a))
            body += n
        descs.append(gd)
    hdr = pickle.dumps((payload, descs), protocol=4)
    prelude = _PRELUDE.pack(_MAGIC, 2, seq & 0xFFFFFFFF, len(hdr), body)
    return [prelude + hdr] + views, body


def _finish_v2(sock: socket.socket, prelude: bytes) -> tuple:
    """Read the rest of a v2 frame whose prelude bytes are in hand.

    Returns ``(seq, payload, groups)`` with every plane received by
    ``recv_into`` directly into its final numpy buffer. Descriptor
    sizes are validated against the body length BEFORE any allocation,
    so a fuzzed frame can't make the receiver allocate past
    ``_MAX_FRAME``."""
    magic, ver, seq, hdr_len, body_len = _PRELUDE.unpack(prelude)
    if magic != _MAGIC or ver != 2:
        raise ConnectionError(f"bad v2 prelude (magic={magic!r} ver={ver})")
    if hdr_len > _MAX_FRAME or body_len > _MAX_FRAME:
        raise ConnectionError(
            f"v2 frame exceeds cap (hdr={hdr_len} body={body_len})"
        )
    payload, descs = pickle.loads(_recv_exact(sock, hdr_len))
    groups = []
    got = 0
    for gd in descs:
        planes = []
        for dt_name, shape, nbytes in gd:
            dt = _np_dtype(dt_name)
            want = int(nbytes)
            count = 1
            for d in shape:
                count *= int(d)
            if want < 0 or count * dt.itemsize != want or got + want > body_len:
                raise ConnectionError("v2 plane descriptor/body mismatch")
            a = np.empty(shape, dtype=dt)
            if want:
                _recv_exact_into(sock, _plane_view(a))
            got += want
            planes.append(a)
        groups.append(tuple(planes))
    if got != body_len:
        raise ConnectionError("v2 body length mismatch")
    return seq, payload, groups


def _enc_planes(planes: Sequence[np.ndarray]) -> list:
    """Planes -> ``(dtype, shape, bytes)`` triples (the raw-bytes half
    of the v1 wire format; plane COUNT rides along, so 2-plane bf16 and
    4-plane target+draft / int8+scale entries all pass through).

    Dtypes travel by NAME, not ``.str``: the extension dtypes the KV
    pool actually uses (ml_dtypes bfloat16 et al.) stringify as opaque
    void codes (``|V2``) under ``.str``, which would decode to planes
    jax rejects at restore time."""
    out = []
    for p in planes:
        a = np.ascontiguousarray(p)
        out.append((a.dtype.name, a.shape, a.tobytes()))
    return out


def _np_dtype(name: str) -> np.dtype:
    """Dtype from its wire name, resolving extension dtypes (bfloat16,
    float8 variants) through ml_dtypes when numpy alone can't."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _dec_planes(enc: list) -> tuple:
    return tuple(
        np.frombuffer(raw, dtype=_np_dtype(dt)).reshape(shape)
        for dt, shape, raw in enc
    )


def _nodelay(sock: socket.socket) -> None:
    """Disable Nagle on TCP sockets: page-store RPCs interleave small
    header frames with bulk plane bytes, and a delayed-ACK/Nagle stall
    on the header half adds ~40ms per op on cross-host links. No-op
    for UDS."""
    if sock.family == socket.AF_INET:
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:  # pragma: no cover - platform quirk
            pass


def parse_endpoint(spec) -> tuple[str, object]:
    """``"tcp://host:port"`` / ``"uds:///path"`` / ``(host, port)`` /
    a bare filesystem path -> ``("tcp", (host, port))`` or
    ``("uds", path)``."""
    if isinstance(spec, tuple):
        return "tcp", (spec[0], int(spec[1]))
    s = str(spec)
    if s.startswith("tcp://"):
        host, _, port = s[len("tcp://"):].rpartition(":")
        return "tcp", (host or "127.0.0.1", int(port))
    if s.startswith("uds://"):
        return "uds", s[len("uds://"):]
    if "/" in s or not s:
        return "uds", s
    host, _, port = s.rpartition(":")
    return "tcp", (host or "127.0.0.1", int(port))


class PageStoreServer:
    """Page-transport server over ONE authoritative
    :class:`HostPageStore`, speaking both wire formats per frame.

    One accept thread + one daemon thread per connection (a fleet has
    a handful of clients, each holding one long-lived socket). A
    connection's requests are handled in arrival order and replies
    carry the request's sequence tag, which is all the pipelined
    client needs — server-side concurrency stays per-connection. All
    mutation funnels through the wrapped store's own lock, so a local
    in-process user and remote clients can share it. A malformed or
    truncated frame drops THAT connection only (the client reconnects
    or degrades); the listener and other connections are unaffected.
    """

    def __init__(
        self,
        store: HostPageStore,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        path: str | None = None,
    ):
        self.store = store
        self._path = path
        if path is not None:
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.bind(path)
            self.endpoint = f"uds://{path}"
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind((host, port))
            self.endpoint = "tcp://{}:{}".format(*self._sock.getsockname())
        self._sock.listen(16)
        self._closed = threading.Event()
        self._accept_thread: threading.Thread | None = None
        self._conns_lock = threading.Lock()
        self._conns: set[socket.socket] = set()

    def start(self) -> "PageStoreServer":
        t = threading.Thread(
            target=self._accept_loop, name="page-store-accept", daemon=True
        )
        self._accept_thread = t
        t.start()
        return self

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed
            _nodelay(conn)
            threading.Thread(
                target=self._serve_conn,
                args=(conn,),
                name="page-store-conn",
                daemon=True,
            ).start()

    def _read_request(self, conn: socket.socket) -> tuple:
        """One request frame, either wire: ``(ver, seq, payload,
        groups)``. Sniffs the first two bytes — ``b"KV"`` opens a v2
        prelude; as a v1 length prefix those bytes would mean a >1 GiB
        frame, far past ``_MAX_FRAME``, so the formats can't collide."""
        head = _recv_exact(conn, 2)
        if head == _MAGIC:
            rest = _recv_exact(conn, _PRELUDE.size - 2)
            return (2,) + _finish_v2(conn, head + rest)
        rest = _recv_exact(conn, 2)
        (n,) = _LEN.unpack(head + rest)
        if n > _MAX_FRAME:
            raise ConnectionError(f"frame length {n} exceeds cap {_MAX_FRAME}")
        return 1, 0, pickle.loads(_recv_exact(conn, n)), []

    def _serve_conn(self, conn: socket.socket) -> None:
        with self._conns_lock:
            if self._closed.is_set():
                try:
                    conn.close()
                except OSError:
                    pass
                return
            self._conns.add(conn)
        try:
            while not self._closed.is_set():
                try:
                    ver, seq, payload, groups = self._read_request(conn)
                except (
                    ConnectionError,
                    OSError,
                    EOFError,
                    struct.error,
                    pickle.PickleError,
                    ValueError,
                    TypeError,
                    MemoryError,
                ):
                    return  # garbage or gone: drop this connection only
                if ver == 1:
                    try:
                        reply = self._handle_v1(payload)
                    except Exception as e:  # noqa: BLE001 - malformed op
                        reply = ("err", repr(e), self._stats_stamped())
                    try:
                        _send_frame(conn, pickle.dumps(reply, protocol=4))
                    except OSError:
                        return
                else:
                    # Optional third header element (PR 20): the owning
                    # request's trace id. Length-tolerant both ways —
                    # an old client sends 2 elements, an old server
                    # ignores the third.
                    tid = payload[2] if len(payload) > 2 else None
                    t_op = time.perf_counter()
                    try:
                        result, out_groups = self._handle_v2(
                            payload[0], payload[1], groups
                        )
                        status = "ok"
                    except Exception as e:  # noqa: BLE001 - malformed op
                        status, result, out_groups = "err", repr(e), []
                    self._flight_op(
                        payload[0], tid, groups, out_groups, t_op
                    )
                    views, _ = _pack_frame(
                        seq,
                        (status, result, self._stats_stamped()),
                        out_groups,
                    )
                    try:
                        _send_vec(conn, views)
                    except OSError:
                        return
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _stats_stamped(self) -> dict:
        """Store stats + clock-probe stamp (PR 20): the client halves
        the op's RTT around ``now_pc`` to place this store process's
        perf_counter timebase on its own — the store-connection leg of
        the fleet clock-offset estimator."""
        return {
            **self.store.stats_snapshot(),
            "now_pc": time.perf_counter(),
        }

    def _flight_op(
        self, op, tid, groups: list, out_groups: list, t_op: float
    ) -> None:
        """Record one data-plane op in THIS process's flight ring
        (PR 20), tagged with the owning trace id — the store-side lane
        of the merged fleet timeline. Control ops are skipped (the
        worker loop's touch/contains churn would flood the ring)."""
        if op not in _DATA_OPS:
            return
        try:
            from llm_consensus_tpu.serving import flight as _flight

            _flight.flight_recorder().record(
                "store_op",
                t_op,
                time.perf_counter() - t_op,
                trace_id=tid if isinstance(tid, str) else None,
                op=op,
                rx_bytes=sum(
                    int(p.nbytes) for g in groups for p in g
                ),
                tx_bytes=sum(
                    int(p.nbytes) for g in out_groups for p in g
                ),
            )
        except Exception:  # noqa: BLE001 - telemetry must not fail ops
            pass

    def _handle_v1(self, req: tuple) -> tuple:
        """PR-16 ops with pickled plane triples — kept verbatim so a
        ``wire="v1"`` client (the bench baseline) exercises the exact
        old path."""
        op, args = req[0], req[1:]
        store = self.store
        if op == "put_counted":
            key, enc = args
            result = store.put_counted(key, _dec_planes(enc))
        elif op == "touch":
            result = store.touch(args[0])
        elif op == "get":
            planes = store.get(args[0])
            result = None if planes is None else _enc_planes(planes)
        elif op == "contains":
            result = args[0] in store
        elif op == "stats":
            result = None
        else:
            raise ValueError(f"unknown op {op!r}")
        return "ok", result, self._stats_stamped()

    def _handle_v2(self, op: str, args: tuple, groups: list) -> tuple:
        """v2 ops: planes arrive/depart as raw frame groups, never
        through pickle. Returns ``(result, out_groups)``."""
        store = self.store
        if op == "put_counted":
            return store.put_counted(args[0], groups[0]), []
        if op == "put_many":
            keys = args[0]
            if len(keys) != len(groups):
                raise ValueError("put_many keys/groups mismatch")
            return store.put_many(list(zip(keys, groups))), []
        if op == "touch":
            return store.touch(args[0]), []
        if op == "touch_many":
            return store.touch_many(args[0]), []
        if op == "get":
            planes = store.get(args[0])
            return (False, []) if planes is None else (True, [planes])
        if op == "get_run":
            runs = store.get_run(args[0])
            return len(runs), runs
        if op == "run_len":
            return store.run_len(args[0]), []
        if op == "contains":
            return args[0] in store, []
        if op == "stats":
            return None, []
        raise ValueError(f"unknown op {op!r}")

    def close(self) -> None:
        """Stop the listener AND hang up every live connection (a
        shutdown unblocks the per-connection threads parked in recv,
        so a close is a hard mid-stream kill from the clients' view —
        their in-flight ops fail to misses, exactly the degrade path
        the circuit breaker covers)."""
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        if self._path is not None:
            import os

            try:
                os.unlink(self._path)
            except OSError:
                pass


class _Pending:
    """One in-flight v2 op: the waiter blocks on ``ev``; the receiver
    thread fills ``reply``/``groups`` (or marks ``failed``) and sets
    it."""

    __slots__ = ("ev", "reply", "groups", "failed", "t0")

    def __init__(self):
        self.ev = threading.Event()
        self.reply = None
        self.groups: list = []
        self.failed = False
        self.t0 = time.perf_counter()


class RemotePageStore:
    """Client half: the ``HostPageStore`` interface over a socket.

    Drop-in for the places a fleet passes a store —
    ``ReplicaSet(host_store=)`` / ``ContinuousBatcher(host_store=)`` —
    with the degrade-to-miss failure contract described in the module
    docstring. Construction NEVER raises on a dead server: the first
    exchange fails, the circuit opens, and the batcher recomputes
    until the peer answers.

    ``wire="v2"`` (default) speaks the zero-copy scatter-gather
    format with PIPELINED sequence-tagged ops: the socket write is the
    only serialized section, a dedicated receiver thread dispatches
    replies by tag, and any number of worker/prefetch/export threads
    keep ops in flight concurrently. An op that times out poisons the
    connection (frames can't be resynced mid-stream), failing all
    in-flight ops to misses and opening the circuit — the same degrade
    contract as v1, just batched. ``wire="v1"`` keeps the PR-16
    one-lock synchronous client, byte-for-byte the old frames: the
    measured baseline for the transport A/B leg.
    """

    def __init__(
        self,
        endpoint,
        *,
        timeout_s: float = 2.0,
        retry_s: float = 1.0,
        wire: str = "v2",
    ):
        if wire not in ("v1", "v2"):
            raise ValueError(f"wire must be 'v1' or 'v2', got {wire!r}")
        self.wire = wire
        self.kind, self.address = parse_endpoint(endpoint)
        self.endpoint = (
            f"{self.kind}://{self.address}"
            if self.kind == "uds"
            else "tcp://{}:{}".format(*self.address)
        )
        self.timeout_s = float(timeout_s)
        self.retry_s = float(retry_s)
        self._lock = threading.Lock()
        self._send_lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._seq = 0
        self._pending: dict[int, _Pending] = {}
        self._down_until = 0.0
        self._warned_down = False
        #: Local failure count (mirrors gateway_remote_store_errors_total
        #: for this client; the Prometheus family is process-global).
        self.errors = 0
        #: Plane payload bytes this client moved, by direction — the
        #: stats mirrors of ``gateway_transfer_bytes_total{dir=...}``.
        self.tx_bytes = 0
        self.rx_bytes = 0
        #: Clock-offset estimate for the store host (PR 20):
        #: ``t_local ≈ t_store + clock_offset``, from halving each v2
        #: op's RTT around the ``now_pc`` stamp the server piggybacks
        #: on every reply; the min-RTT observation wins (the tightest
        #: round trip bounds the midpoint error). None until a reply
        #: carrying the stamp lands.
        self.clock_offset: float | None = None
        self.clock_rtt: float | None = None
        # Last piggybacked authoritative-store snapshot: the cache
        # behind every read property (no network on the read path).
        self._stats: dict = {}
        # Best-effort warm-up: populates the stats cache when the
        # server is up; opens the circuit (no raise) when it is not.
        self._call_simple("stats")

    # -- transport ------------------------------------------------------

    def _connect(self) -> socket.socket:
        if self.kind == "uds":
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        else:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.settimeout(self.timeout_s)
        s.connect(self.address)
        _nodelay(s)
        return s

    def _drop_socket(self) -> None:
        """shutdown+close under the send lock: shutdown is what
        reliably unblocks a receiver thread parked in ``recv`` (a bare
        close can leave it blocked on Linux)."""
        with self._send_lock:
            s = self._sock
            self._sock = None
        if s is not None:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    def _fail(self, exc: Exception) -> None:
        """One failure: count, open the circuit, warn on the DOWN
        transition only (a dead peer must not log per worker-loop op),
        and drop the socket so the next attempt reconnects."""
        self.errors += 1
        _M_ERRORS.inc()
        self._down_until = time.monotonic() + self.retry_s
        self._drop_socket()
        if not self._warned_down:
            self._warned_down = True
            log.warning(
                "remote page store %s unavailable (%r): degrading to "
                "local miss/recompute until it answers",
                self.endpoint,
                exc,
            )
            self._flight("down", error=repr(exc))

    def _flight(self, state: str, **extra) -> None:
        # Lazy import mirrors control.py: consumers of this module may
        # not want the flight module (and its deps) at import time.
        try:
            from llm_consensus_tpu.serving import flight as _flight

            _flight.flight_recorder().record(
                "remote_store",
                time.perf_counter(),
                endpoint=self.endpoint,
                state=state,
                **extra,
            )
        except Exception:  # noqa: BLE001 - telemetry must not fail ops
            pass

    def _flight_op(
        self, op: str, tid, tx: int, rx: int, dur: float
    ) -> None:
        """One data-plane op in this process's flight ring (PR 20),
        tagged with the owning trace id and the bytes it moved — the
        per-request attribution of the
        ``gateway_transfer_bytes_total`` increments the same op made
        (the counter itself stays label-bounded; the flight event
        carries the join key)."""
        try:
            from llm_consensus_tpu.serving import flight as _flight

            _flight.flight_recorder().record(
                "store_op",
                time.perf_counter() - dur,
                dur,
                trace_id=tid,
                op=op,
                endpoint=self.endpoint,
                tx_bytes=tx,
                rx_bytes=rx,
            )
        except Exception:  # noqa: BLE001 - telemetry must not fail ops
            pass

    def _count_xfer(self, direction: str, n: int) -> None:
        if not n:
            return
        if direction == "tx":
            self.tx_bytes += n
        else:
            self.rx_bytes += n
        _M_XFER.labels(dir=direction).inc(n)

    def _recovered(self) -> None:
        if self._warned_down:
            self._warned_down = False
            log.info("remote page store %s recovered", self.endpoint)
            self._flight("up")

    # -- v1 synchronous exchange ----------------------------------------

    def _call_v1(self, op: str, *args):
        """One lock-held request/response exchange (the PR-16 client,
        byte-for-byte). Returns ``(True, result)``, or None after ANY
        failure (the degrade-to-miss contract). Never raises."""
        with self._lock:
            if time.monotonic() < self._down_until:
                self.errors += 1
                _M_ERRORS.inc()
                return None
            t0 = time.perf_counter()
            try:
                if self._sock is None:
                    self._sock = self._connect()
                payload = pickle.dumps((op, *args), protocol=4)
                _send_frame(self._sock, payload)
                status, result, stats = pickle.loads(_recv_frame(self._sock))
            except (OSError, ConnectionError, EOFError, pickle.PickleError) as e:
                self._fail(e)
                return None
            if status != "ok":
                # The server rejected the op (malformed key): a miss,
                # but the connection is healthy — no circuit.
                self.errors += 1
                _M_ERRORS.inc()
                log.warning(
                    "remote page store %s rejected %s: %s",
                    self.endpoint,
                    op,
                    result,
                )
                return None
            self._stats = stats
            _M_RTT.observe(time.perf_counter() - t0)
            _M_BYTES.set(stats.get("bytes_used", 0))
            self._recovered()
            return (True, result)  # wrap: distinguish None-result hits

    # -- v2 pipelined exchange ------------------------------------------

    def _start_rx(self, sock: socket.socket) -> None:
        threading.Thread(
            target=self._rx_loop, args=(sock,), name="page-store-rx", daemon=True
        ).start()

    def _rx_loop(self, sock: socket.socket) -> None:
        """Receiver half of the pipelined connection: reads reply
        frames forever, dispatching each to its waiter by sequence
        tag. An idle-timeout on the FIRST byte of a frame is benign
        (op deadlines are enforced by the waiters, who poison the
        socket on expiry); a timeout or error mid-frame is fatal —
        the stream can't be resynced — and fails every in-flight op
        to a miss."""
        one = bytearray(1)
        try:
            while True:
                try:
                    n = sock.recv_into(one)
                except socket.timeout:
                    continue
                if n == 0:
                    raise ConnectionError("server closed connection")
                rest = _recv_exact(sock, _PRELUDE.size - 1)
                seq, payload, groups = _finish_v2(sock, bytes(one) + rest)
                self._count_xfer(
                    "rx", sum(int(p.nbytes) for g in groups for p in g)
                )
                with self._lock:
                    pend = self._pending.pop(seq, None)
                if pend is not None:
                    pend.reply = payload
                    pend.groups = groups
                    pend.ev.set()
        except (
            OSError,
            ConnectionError,
            EOFError,
            struct.error,
            pickle.PickleError,
            ValueError,
            TypeError,
            MemoryError,
        ) as e:
            with self._lock:
                current = self._sock is sock
            if current:
                # This thread detected the failure first: open the
                # circuit once. (If a waiter's timeout got here first,
                # the socket is already swapped out and counted.)
                self._fail(e)
            self._abort_pending()

    def _abort_pending(self) -> None:
        with self._lock:
            pend = list(self._pending.values())
            self._pending.clear()
        for p in pend:
            p.failed = True
            p.ev.set()

    def _call_v2(self, op: str, args: tuple = (), groups: Sequence = ()):
        """One pipelined op. Returns ``(True, result, plane_groups)``
        or None after ANY failure. The send is the only serialized
        section; the reply is awaited without holding any lock, so
        concurrent callers keep the wire full. Never raises.

        Trace join (PR 20): the owning request's trace id (the
        contextvar the handoff worker propagated) rides the v2 header
        as an optional third element — the server tags its own flight
        ring with it, and this side lands a ``store_op`` span on the
        trace plus a flight event carrying the moved bytes, so wire
        transfers attribute to the request that caused them."""
        trace = _tracing.current_trace()
        tid = trace.trace_id if trace is not None else None
        with self._lock:
            if time.monotonic() < self._down_until:
                self.errors += 1
                _M_ERRORS.inc()
                return None
        pend = _Pending()
        seq = None
        try:
            with self._send_lock:
                sock = self._sock
                if sock is None:
                    sock = self._connect()
                    self._sock = sock
                    self._start_rx(sock)
                with self._lock:
                    self._seq = seq = (self._seq + 1) & 0xFFFFFFFF
                    self._pending[seq] = pend
                views, tx = _pack_frame(seq, (op, args, tid), groups)
                _send_vec(sock, views)
            self._count_xfer("tx", tx)
        except (
            OSError,
            ConnectionError,
            EOFError,
            pickle.PickleError,
            struct.error,
        ) as e:
            with self._lock:
                self._pending.pop(seq, None)
            self._fail(e)
            return None
        if not pend.ev.wait(self.timeout_s):
            with self._lock:
                self._pending.pop(seq, None)
            self._fail(
                socket.timeout(f"no reply to {op} within {self.timeout_s}s")
            )
            return None
        if pend.failed:
            # The connection died while we waited; whoever detected it
            # already opened the circuit — count THIS op's miss only.
            self.errors += 1
            _M_ERRORS.inc()
            return None
        status, result, stats = pend.reply
        with self._lock:
            self._stats = stats
        t1 = time.perf_counter()
        dur = t1 - pend.t0
        _M_RTT.observe(dur)
        _M_BYTES.set(stats.get("bytes_used", 0))
        # Clock-offset piggyback (PR 20): every reply carrying the
        # server's ``now_pc`` stamp refines the estimate; min-RTT wins.
        now = stats.get("now_pc")
        if isinstance(now, (int, float)) and (
            self.clock_rtt is None or dur <= self.clock_rtt
        ):
            self.clock_offset = (pend.t0 + t1) / 2.0 - float(now)
            self.clock_rtt = dur
        if op in _DATA_OPS:
            rx = sum(int(p.nbytes) for g in pend.groups for p in g)
            if trace is not None:
                trace.add_span(
                    "store_op",
                    pend.t0,
                    dur,
                    op=op,
                    tx_bytes=tx,
                    rx_bytes=rx,
                )
            self._flight_op(op, tid, tx, rx, dur)
        if status != "ok":
            self.errors += 1
            _M_ERRORS.inc()
            log.warning(
                "remote page store %s rejected %s: %s",
                self.endpoint,
                op,
                result,
            )
            return None
        self._recovered()
        return (True, result, pend.groups)

    def _call_simple(self, op: str, *args):
        """Planeless op over whichever wire is active; ``(True,
        result)`` or None."""
        if self.wire == "v1":
            return self._call_v1(op, *args)
        hit = self._call_v2(op, args)
        return None if hit is None else (True, hit[1])

    # -- HostPageStore surface ------------------------------------------

    @staticmethod
    def _as_planes(planes: Sequence[np.ndarray]) -> tuple:
        return tuple(np.ascontiguousarray(p) for p in planes)

    def put(self, key: tuple, planes: Sequence[np.ndarray]) -> bool:
        resident, _, _ = self.put_counted(key, planes)
        return resident

    def put_counted(
        self, key: tuple, planes: Sequence[np.ndarray]
    ) -> tuple[bool, int, int]:
        planes = self._as_planes(planes)
        if self.wire == "v1":
            hit = self._call_v1("put_counted", key, _enc_planes(planes))
            if hit is not None:
                self._count_xfer(
                    "tx", sum(int(p.nbytes) for p in planes)
                )
        else:
            hit = self._call_v2("put_counted", (key,), (planes,))
        if hit is None:
            # The page never left the process: not resident, not
            # demoted anywhere — report it dropped so the caller's
            # accounting reflects a real loss, not a silent no-op.
            return False, 0, 1
        return tuple(hit[1])

    def put_many(
        self, items: Sequence[tuple[tuple, Sequence[np.ndarray]]]
    ) -> list[tuple[bool, int, int]]:
        """Batched :meth:`put_counted`: ONE round trip on v2 (keys in
        the header, every page's planes scatter-gathered into one
        frame); a per-key loop on v1. Degrades to all-dropped."""
        items = [(k, self._as_planes(p)) for k, p in items]
        if not items:
            return []
        if self.wire == "v1":
            return [self.put_counted(k, p) for k, p in items]
        hit = self._call_v2(
            "put_many",
            (tuple(k for k, _ in items),),
            tuple(p for _, p in items),
        )
        if hit is None:
            return [(False, 0, 1)] * len(items)
        return [tuple(t) for t in hit[1]]

    def touch(self, key: tuple) -> bool:
        hit = self._call_simple("touch", key)
        return bool(hit[1]) if hit is not None else False

    def touch_many(self, keys: Sequence[tuple]) -> list[bool]:
        """Batched :meth:`touch`: one round trip on v2, a loop on v1
        (the v1 server predates the op). Degrades to all-False, which
        the demote hook maps to fresh puts — correct, just heavier."""
        keys = list(keys)
        if not keys:
            return []
        if self.wire == "v1":
            return [self.touch(k) for k in keys]
        hit = self._call_v2("touch_many", (keys,))
        if hit is None:
            return [False] * len(keys)
        return [bool(b) for b in hit[1]]

    def get(self, key: tuple):
        if self.wire == "v1":
            hit = self._call_v1("get", key)
            if hit is None or hit[1] is None:
                return None
            planes = _dec_planes(hit[1])
            self._count_xfer("rx", sum(int(p.nbytes) for p in planes))
            return planes
        hit = self._call_v2("get", (key,))
        if hit is None or not hit[1]:
            return None
        return hit[2][0]

    def get_run(self, keys: Sequence[tuple]) -> list:
        """Planes for the longest resident prefix of ``keys``: ONE
        round trip on v2 (a whole restore plan in one frame), a
        get-until-miss loop on v1. Degrades to an empty run —
        admission recomputes the tail."""
        keys = list(keys)
        if not keys:
            return []
        if self.wire == "v1":
            out = []
            for k in keys:
                planes = self.get(k)
                if planes is None:
                    break
                out.append(planes)
            return out
        hit = self._call_v2("get_run", (keys,))
        if hit is None:
            return []
        return list(hit[2])

    def run_len(self, keys: Sequence[tuple]) -> int:
        """Resident-prefix length without plane movement (the probe
        behind prefix_probe's host extension): one round trip on v2,
        a contains loop on v1. Degrades to 0."""
        keys = list(keys)
        if not keys:
            return 0
        if self.wire == "v1":
            n = 0
            for k in keys:
                if k not in self:
                    break
                n += 1
            return n
        hit = self._call_v2("run_len", (keys,))
        return int(hit[1]) if hit is not None else 0

    def __contains__(self, key: tuple) -> bool:
        hit = self._call_simple("contains", key)
        return bool(hit[1]) if hit is not None else False

    def refresh_stats(self) -> dict:
        """One explicit stats exchange (tests + periodic refresh);
        returns the cached snapshot either way."""
        self._call_simple("stats")
        return dict(self._stats)

    # Read properties serve the piggybacked cache — NEVER the network
    # (the admission overflow hook reads headroom on the event loop).

    def __len__(self) -> int:
        return int(self._stats.get("pages", 0))

    @property
    def bytes_used(self) -> int:
        return int(self._stats.get("bytes_used", 0))

    @property
    def budget_bytes(self) -> int:
        return int(self._stats.get("budget_bytes", 0))

    @property
    def headroom_bytes(self) -> int:
        return int(self._stats.get("headroom_bytes", 0))

    @property
    def demoted_pages(self) -> int:
        return int(self._stats.get("demoted_pages", 0))

    @property
    def dropped_pages(self) -> int:
        return int(self._stats.get("dropped_pages", 0))

    @property
    def lookups(self) -> int:
        return int(self._stats.get("lookups", 0))

    @property
    def hits(self) -> int:
        return int(self._stats.get("hits", 0))

    def stats_snapshot(self) -> dict:
        return dict(self._stats)

    def close(self) -> None:
        self._drop_socket()
        self._abort_pending()


def main(argv: list[str] | None = None) -> int:
    """Standalone authoritative store process:
    ``python -m llm_consensus_tpu.serving.remote_store --budget-mb 256``
    prints one JSON line ``{"endpoint": ...}`` then serves until
    SIGTERM/SIGINT — the cross-process half of the --serve-disagg
    bench leg and of a real multi-host deployment."""
    import argparse
    import json
    import signal
    import sys

    p = argparse.ArgumentParser(prog="remote_store")
    p.add_argument("--budget-mb", type=int, default=256)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--uds", default=None, help="serve a unix socket path")
    args = p.parse_args(argv)
    server = PageStoreServer(
        HostPageStore(args.budget_mb << 20),
        host=args.host,
        port=args.port,
        path=args.uds,
    ).start()
    print(json.dumps({"endpoint": server.endpoint}), flush=True)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    try:
        while not stop.wait(0.5):
            pass
    finally:
        server.close()
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
