"""Remote host page store: the fleet KV tier across processes (PR 16).

PR 14 made :class:`~llm_consensus_tpu.serving.offload.HostPageStore`
the fleet's page transport — thread-safe, chain-keyed, scoped by each
batcher's config dims + weights fingerprint so heterogeneous replicas
can never cross-restore. But it is in-memory, which confines the fleet
to one process. This module lifts the SAME interface onto a socket:

- :class:`PageStoreServer` wraps ONE authoritative ``HostPageStore``
  behind a length-prefixed TCP or Unix-domain transport (one frame per
  request/response; payload = op + key + raw plane bytes). There is no
  negotiation in the protocol because none is needed: the PR-14
  ``(scope, chain)`` keys already carry config dims and the weights
  fingerprint, so a process whose scope differs simply never hits.
- :class:`RemotePageStore` is a client implementing the full
  ``HostPageStore`` surface (``put_counted`` / ``touch`` / ``get`` /
  ``__contains__`` / ``headroom_bytes`` / the counters), so
  ``ReplicaSet`` / ``ContinuousBatcher(host_store=)`` take a local
  store or a remote one transparently — 4-plane target+draft entries
  included (the store layer is plane-count agnostic).

**Failure contract — degrade, never wedge.** Every client failure
(connect refused, peer disconnect mid-``put``, a slow peer hitting the
client timeout) degrades to a local MISS: ``get`` returns None,
``touch``/``__contains__`` return False, ``put_counted`` reports the
page dropped — so the worker loop recomputes via chunked prefill
(always correct) instead of stalling. Each failure increments
``gateway_remote_store_errors_total``, logs ONE warning per outage
(not per op), records a ``remote_store`` flight event on the
transition, and opens the circuit for ``retry_s`` seconds — ops during
the open window miss immediately with no socket attempt, so a dead
peer costs the worker loop nothing per iteration (heartbeat stays
fresh; tested).

**Cheap reads by piggyback.** Every server response frame carries the
authoritative store's :meth:`stats_snapshot`, which the client caches;
``headroom_bytes`` / ``bytes_used`` / ``len`` / the counters read the
cache and NEVER touch the network — the admission overflow hook reads
headroom on the asyncio event loop, where a blocking RTT would freeze
the gateway under exactly the overload the hook exists to absorb.
``gateway_remote_store_bytes`` mirrors the cached occupancy;
``gateway_remote_store_rtt_seconds`` observes each successful
exchange.

Wire format: ``4-byte big-endian length || pickle payload``, with
plane arrays serialized explicitly as ``(dtype_str, shape, bytes)``
triples — keys + raw bytes, nothing else. Pickle is a FLEET-INTERNAL
trust boundary (bind localhost/UDS, same deployment): the transport
authenticates nothing, exactly like the in-process store it replaces.
"""

from __future__ import annotations

import logging
import pickle
import socket
import struct
import threading
import time
from typing import Sequence

import numpy as np

from llm_consensus_tpu.server.metrics import (
    REMOTE_STORE_BYTES as _M_BYTES,
)
from llm_consensus_tpu.server.metrics import (
    REMOTE_STORE_ERRORS as _M_ERRORS,
)
from llm_consensus_tpu.server.metrics import (
    REMOTE_STORE_RTT as _M_RTT,
)
from llm_consensus_tpu.serving.offload import HostPageStore

log = logging.getLogger(__name__)

__all__ = ["PageStoreServer", "RemotePageStore", "parse_endpoint"]

_LEN = struct.Struct(">I")
#: Refuse frames past this (a corrupt length prefix must not allocate
#: gigabytes): generous for any real page payload (a 1B-class bf16
#: page is ~1.5 MiB; 4-plane int8+scales entries are smaller).
_MAX_FRAME = 256 << 20


def _send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> bytes:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if n > _MAX_FRAME:
        raise ConnectionError(f"frame length {n} exceeds cap {_MAX_FRAME}")
    return _recv_exact(sock, n)


def _enc_planes(planes: Sequence[np.ndarray]) -> list:
    """Planes -> ``(dtype, shape, bytes)`` triples (the raw-bytes half
    of the wire format; plane COUNT rides along, so 2-plane bf16 and
    4-plane target+draft / int8+scale entries all pass through).

    Dtypes travel by NAME, not ``.str``: the extension dtypes the KV
    pool actually uses (ml_dtypes bfloat16 et al.) stringify as opaque
    void codes (``|V2``) under ``.str``, which would decode to planes
    jax rejects at restore time."""
    out = []
    for p in planes:
        a = np.ascontiguousarray(p)
        out.append((a.dtype.name, a.shape, a.tobytes()))
    return out


def _np_dtype(name: str) -> np.dtype:
    """Dtype from its wire name, resolving extension dtypes (bfloat16,
    float8 variants) through ml_dtypes when numpy alone can't."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _dec_planes(enc: list) -> tuple:
    return tuple(
        np.frombuffer(raw, dtype=_np_dtype(dt)).reshape(shape)
        for dt, shape, raw in enc
    )


def parse_endpoint(spec) -> tuple[str, object]:
    """``"tcp://host:port"`` / ``"uds:///path"`` / ``(host, port)`` /
    a bare filesystem path -> ``("tcp", (host, port))`` or
    ``("uds", path)``."""
    if isinstance(spec, tuple):
        return "tcp", (spec[0], int(spec[1]))
    s = str(spec)
    if s.startswith("tcp://"):
        host, _, port = s[len("tcp://"):].rpartition(":")
        return "tcp", (host or "127.0.0.1", int(port))
    if s.startswith("uds://"):
        return "uds", s[len("uds://"):]
    if "/" in s or not s:
        return "uds", s
    host, _, port = s.rpartition(":")
    return "tcp", (host or "127.0.0.1", int(port))


class PageStoreServer:
    """Length-prefixed page-transport server over ONE authoritative
    :class:`HostPageStore`.

    One accept thread + one daemon thread per connection (a fleet has
    a handful of clients, each holding one long-lived socket). All
    mutation funnels through the wrapped store's own lock, so a local
    in-process user and remote clients can share it.
    """

    def __init__(
        self,
        store: HostPageStore,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        path: str | None = None,
    ):
        self.store = store
        self._path = path
        if path is not None:
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.bind(path)
            self.endpoint = f"uds://{path}"
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind((host, port))
            self.endpoint = "tcp://{}:{}".format(*self._sock.getsockname())
        self._sock.listen(16)
        self._closed = threading.Event()
        self._accept_thread: threading.Thread | None = None

    def start(self) -> "PageStoreServer":
        t = threading.Thread(
            target=self._accept_loop, name="page-store-accept", daemon=True
        )
        self._accept_thread = t
        t.start()
        return self

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # listener closed
            threading.Thread(
                target=self._serve_conn,
                args=(conn,),
                name="page-store-conn",
                daemon=True,
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._closed.is_set():
                try:
                    req = pickle.loads(_recv_frame(conn))
                    reply = self._handle(req)
                except (ConnectionError, OSError, EOFError):
                    return
                except Exception as e:  # noqa: BLE001 - malformed op
                    reply = ("err", repr(e), self.store.stats_snapshot())
                try:
                    _send_frame(conn, pickle.dumps(reply, protocol=4))
                except OSError:
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, req: tuple) -> tuple:
        op, args = req[0], req[1:]
        store = self.store
        if op == "put_counted":
            key, enc = args
            result = store.put_counted(key, _dec_planes(enc))
        elif op == "touch":
            result = store.touch(args[0])
        elif op == "get":
            planes = store.get(args[0])
            result = None if planes is None else _enc_planes(planes)
        elif op == "contains":
            result = args[0] in store
        elif op == "stats":
            result = None
        else:
            raise ValueError(f"unknown op {op!r}")
        return "ok", result, store.stats_snapshot()

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass
        if self._path is not None:
            import os

            try:
                os.unlink(self._path)
            except OSError:
                pass


class RemotePageStore:
    """Client half: the ``HostPageStore`` interface over a socket.

    Drop-in for the places a fleet passes a store —
    ``ReplicaSet(host_store=)`` / ``ContinuousBatcher(host_store=)`` —
    with the degrade-to-miss failure contract described in the module
    docstring. Construction NEVER raises on a dead server: the first
    exchange fails, the circuit opens, and the batcher recomputes
    until the peer answers.
    """

    def __init__(self, endpoint, *, timeout_s: float = 2.0, retry_s: float = 1.0):
        self.kind, self.address = parse_endpoint(endpoint)
        self.endpoint = (
            f"{self.kind}://{self.address}"
            if self.kind == "uds"
            else "tcp://{}:{}".format(*self.address)
        )
        self.timeout_s = float(timeout_s)
        self.retry_s = float(retry_s)
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None
        self._down_until = 0.0
        self._warned_down = False
        #: Local failure count (mirrors gateway_remote_store_errors_total
        #: for this client; the Prometheus family is process-global).
        self.errors = 0
        # Last piggybacked authoritative-store snapshot: the cache
        # behind every read property (no network on the read path).
        self._stats: dict = {}
        # Best-effort warm-up: populates the stats cache when the
        # server is up; opens the circuit (no raise) when it is not.
        self._call("stats")

    # -- transport ------------------------------------------------------

    def _connect(self) -> socket.socket:
        if self.kind == "uds":
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        else:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.settimeout(self.timeout_s)
        s.connect(self.address)
        return s

    def _fail(self, exc: Exception) -> None:
        """One failure: count, open the circuit, warn on the DOWN
        transition only (a dead peer must not log per worker-loop op),
        and drop the socket so the next attempt reconnects."""
        self.errors += 1
        _M_ERRORS.inc()
        self._down_until = time.monotonic() + self.retry_s
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        if not self._warned_down:
            self._warned_down = True
            log.warning(
                "remote page store %s unavailable (%r): degrading to "
                "local miss/recompute until it answers",
                self.endpoint,
                exc,
            )
            self._flight("down", error=repr(exc))

    def _flight(self, state: str, **extra) -> None:
        # Lazy import mirrors control.py: consumers of this module may
        # not want the flight module (and its deps) at import time.
        try:
            from llm_consensus_tpu.serving import flight as _flight

            _flight.flight_recorder().record(
                "remote_store",
                time.perf_counter(),
                endpoint=self.endpoint,
                state=state,
                **extra,
            )
        except Exception:  # noqa: BLE001 - telemetry must not fail ops
            pass

    def _call(self, op: str, *args):
        """One request/response exchange. Returns the result, or None
        after ANY failure (the degrade-to-miss contract; callers map
        None to their own miss value). Never raises."""
        with self._lock:
            if time.monotonic() < self._down_until:
                self.errors += 1
                _M_ERRORS.inc()
                return None
            t0 = time.perf_counter()
            try:
                if self._sock is None:
                    self._sock = self._connect()
                payload = pickle.dumps((op, *args), protocol=4)
                _send_frame(self._sock, payload)
                status, result, stats = pickle.loads(_recv_frame(self._sock))
            except (OSError, ConnectionError, EOFError, pickle.PickleError) as e:
                self._fail(e)
                return None
            if status != "ok":
                # The server rejected the op (malformed key): a miss,
                # but the connection is healthy — no circuit.
                self.errors += 1
                _M_ERRORS.inc()
                log.warning(
                    "remote page store %s rejected %s: %s",
                    self.endpoint,
                    op,
                    result,
                )
                return None
            self._stats = stats
            _M_RTT.observe(time.perf_counter() - t0)
            _M_BYTES.set(stats.get("bytes_used", 0))
            if self._warned_down:
                self._warned_down = False
                log.info("remote page store %s recovered", self.endpoint)
                self._flight("up")
            return (True, result)  # wrap: distinguish None-result hits

    # -- HostPageStore surface ------------------------------------------

    def put(self, key: tuple, planes: Sequence[np.ndarray]) -> bool:
        resident, _, _ = self.put_counted(key, planes)
        return resident

    def put_counted(
        self, key: tuple, planes: Sequence[np.ndarray]
    ) -> tuple[bool, int, int]:
        hit = self._call("put_counted", key, _enc_planes(planes))
        if hit is None:
            # The page never left the process: not resident, not
            # demoted anywhere — report it dropped so the caller's
            # accounting reflects a real loss, not a silent no-op.
            return False, 0, 1
        return tuple(hit[1])

    def touch(self, key: tuple) -> bool:
        hit = self._call("touch", key)
        return bool(hit[1]) if hit is not None else False

    def get(self, key: tuple):
        hit = self._call("get", key)
        if hit is None or hit[1] is None:
            return None
        return _dec_planes(hit[1])

    def __contains__(self, key: tuple) -> bool:
        hit = self._call("contains", key)
        return bool(hit[1]) if hit is not None else False

    def refresh_stats(self) -> dict:
        """One explicit stats exchange (tests + periodic refresh);
        returns the cached snapshot either way."""
        self._call("stats")
        return dict(self._stats)

    # Read properties serve the piggybacked cache — NEVER the network
    # (the admission overflow hook reads headroom on the event loop).

    def __len__(self) -> int:
        return int(self._stats.get("pages", 0))

    @property
    def bytes_used(self) -> int:
        return int(self._stats.get("bytes_used", 0))

    @property
    def budget_bytes(self) -> int:
        return int(self._stats.get("budget_bytes", 0))

    @property
    def headroom_bytes(self) -> int:
        return int(self._stats.get("headroom_bytes", 0))

    @property
    def demoted_pages(self) -> int:
        return int(self._stats.get("demoted_pages", 0))

    @property
    def dropped_pages(self) -> int:
        return int(self._stats.get("dropped_pages", 0))

    @property
    def lookups(self) -> int:
        return int(self._stats.get("lookups", 0))

    @property
    def hits(self) -> int:
        return int(self._stats.get("hits", 0))

    def stats_snapshot(self) -> dict:
        return dict(self._stats)

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None


def main(argv: list[str] | None = None) -> int:
    """Standalone authoritative store process:
    ``python -m llm_consensus_tpu.serving.remote_store --budget-mb 256``
    prints one JSON line ``{"endpoint": ...}`` then serves until
    SIGTERM/SIGINT — the cross-process half of the --serve-disagg
    bench leg and of a real multi-host deployment."""
    import argparse
    import json
    import signal
    import sys

    p = argparse.ArgumentParser(prog="remote_store")
    p.add_argument("--budget-mb", type=int, default=256)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--uds", default=None, help="serve a unix socket path")
    args = p.parse_args(argv)
    server = PageStoreServer(
        HostPageStore(args.budget_mb << 20),
        host=args.host,
        port=args.port,
        path=args.uds,
    ).start()
    print(json.dumps({"endpoint": server.endpoint}), flush=True)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    try:
        while not stop.wait(0.5):
            pass
    finally:
        server.close()
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
