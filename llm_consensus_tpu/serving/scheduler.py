"""Batch scheduler: many concurrent requests -> few device programs.

Design (TPU-first): the expensive resource is a compiled decode program
over static shapes, so the scheduler's job is to pack concurrent
requests into shape buckets and keep the chip busy with full batches.

- Producers call :meth:`BatchScheduler.submit` (thread-safe, returns a
  ``concurrent.futures.Future``).
- Request metadata rides the native MPMC ring
  (:class:`llm_consensus_tpu.native.NativeRing`) when libconsensus_rt is
  built, else a ``queue.Queue`` — same semantics, pure-Python fallback.
- One scheduler thread drains up to ``max_batch`` requests per cycle
  (with a short linger so near-simultaneous panel fan-outs coalesce into
  one program), groups them by sampling config, runs
  ``InferenceEngine.generate_texts`` once per group, and resolves the
  futures.

The reference has no scheduler at all — its concurrency is unbounded
per-request HTTP futures (``src/main.rs:101,156,182``).
"""

from __future__ import annotations

import itertools
import json
import logging
import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field

from llm_consensus_tpu.backends.base import (
    Backend,
    BackendError,
    GenerationRequest,
    GenerationResult,
)
from llm_consensus_tpu.engine.engine import InferenceEngine
from llm_consensus_tpu.engine.sampler import SamplerConfig
from llm_consensus_tpu.server.metrics import (
    SCHED_DEPTH as _M_DEPTH,
)
from llm_consensus_tpu.server.metrics import (
    SCHED_OCCUPANCY as _M_OCCUPANCY,
)
from llm_consensus_tpu.server.metrics import (
    SCHED_SUBMITTED as _M_SUBMITTED,
)
from llm_consensus_tpu.utils import tracing as _tracing

log = logging.getLogger(__name__)


@dataclass
class SchedulerConfig:
    max_batch: int = 64
    # Linger: after the first request arrives, wait this long for more to
    # coalesce (panel fan-outs land together; one program instead of N).
    linger_s: float = 0.004
    ring_capacity: int = 1024
    use_native_ring: bool = True


@dataclass
class _Pending:
    request: GenerationRequest
    future: Future = field(default_factory=Future)
    # Request-scoped trace captured at submit; the scheduler thread
    # attaches its batch-execution span to it explicitly.
    trace: object | None = None


class BatchScheduler:
    """Thread-safe request batcher over one engine."""

    def __init__(
        self, engine: InferenceEngine, config: SchedulerConfig | None = None
    ):
        self.engine = engine
        self.config = config or SchedulerConfig()
        self._pending: dict[int, _Pending] = {}
        self._ids = itertools.count()
        self._lock = threading.Lock()
        self._queue = self._make_queue()
        # Liveness heartbeat: stamped per scheduler-loop iteration (the
        # idle loop polls at 20 Hz) — the gateway readiness probe.
        self._hb_tick = time.monotonic()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="batch-scheduler", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------------

    def _make_queue(self):
        if self.config.use_native_ring:
            try:
                from llm_consensus_tpu.native import NativeRing, available

                if available():
                    return NativeRing(self.config.ring_capacity)
            except Exception:  # noqa: BLE001
                pass
        return queue.Queue(maxsize=self.config.ring_capacity)

    def _q_push(self, item: dict) -> None:
        payload = json.dumps(item).encode()
        if isinstance(self._queue, queue.Queue):
            self._queue.put(payload)
        else:
            self._queue.push(payload)

    def _q_pop(self, timeout: float | None) -> dict | None:
        if isinstance(self._queue, queue.Queue):
            try:
                payload = self._queue.get(timeout=timeout)
            except queue.Empty:
                return None
        else:
            payload = self._queue.pop(timeout=timeout)
            if payload is None:
                return None
        return json.loads(payload)

    # ------------------------------------------------------------------

    def submit(self, request: GenerationRequest) -> Future:
        """Enqueue one request; the Future resolves to GenerationResult."""
        if self._stop.is_set():
            raise RuntimeError("scheduler stopped")
        pend = _Pending(request=request, trace=_tracing.current_trace())
        with self._lock:
            rid = next(self._ids)
            self._pending[rid] = pend
            _M_DEPTH.set(len(self._pending))
        _M_SUBMITTED.inc()
        self._q_push({"id": rid})
        return pend.future

    def close(self) -> None:
        self._stop.set()
        if not isinstance(self._queue, queue.Queue):
            self._queue.close()
        self._thread.join(timeout=5)

    # ------------------------------------------------------------------

    def heartbeat(self) -> dict:
        """Scheduler-loop liveness (see ContinuousBatcher.heartbeat)."""
        alive = self._thread.is_alive() and not self._stop.is_set()
        return {
            "alive": alive,
            # Uniform lifecycle shape with the continuous batcher (PR
            # 19): every heartbeat-bearing backend reports a state so
            # readiness probes can branch on one key.
            "state": "serving" if alive else "stopped",
            "last_tick_age_s": time.monotonic() - self._hb_tick,
            "last_step_age_s": None,
        }

    def _run(self) -> None:
        cfg = self.config
        while not self._stop.is_set():
            self._hb_tick = time.monotonic()
            first = self._q_pop(timeout=0.05)
            if first is None:
                continue
            batch_ids = [first["id"]]
            deadline = time.perf_counter() + cfg.linger_s
            while len(batch_ids) < cfg.max_batch:
                left = deadline - time.perf_counter()
                nxt = self._q_pop(timeout=max(left, 0)) if left > 0 else None
                if nxt is None:
                    break
                batch_ids.append(nxt["id"])
            self._execute(batch_ids)
        # Drain on shutdown: fail any still-pending futures.
        with self._lock:
            for pend in self._pending.values():
                if not pend.future.done():
                    pend.future.set_exception(BackendError("scheduler stopped"))
            self._pending.clear()

    def _execute(self, batch_ids: list[int]) -> None:
        with self._lock:
            pends = [
                (rid, self._pending.pop(rid))
                for rid in batch_ids
                if rid in self._pending
            ]
            _M_DEPTH.set(len(self._pending))
        if not pends:
            return
        _M_OCCUPANCY.observe(len(pends))
        # Group by static sampling config (one compiled program each).
        groups: dict[tuple, list[tuple[int, _Pending]]] = {}
        for rid, pend in pends:
            p = pend.request.params
            groups.setdefault(
                (p.max_new_tokens, p.top_k, p.top_p), []
            ).append((rid, pend))
        for (max_new, top_k, top_p), members in groups.items():
            # Re-stamp per group: a legitimately long whole-batch
            # program must not age the liveness tick like a wedge
            # (the tick still ages DURING one group's device call —
            # size the readiness threshold above the longest batch).
            self._hb_tick = time.monotonic()
            reqs = [pend.request for _, pend in members]
            t0 = time.perf_counter()
            try:
                outs = self.engine.generate_texts(
                    [r.prompt for r in reqs],
                    temperatures=[r.params.temperature for r in reqs],
                    seed=reqs[0].params.seed,
                    max_new_tokens=max_new,
                    sampler=SamplerConfig(top_k=top_k, top_p=top_p),
                )
            except Exception as e:  # noqa: BLE001
                log.error("scheduler batch failed: %s", e)
                for _, pend in members:
                    if not pend.future.done():
                        pend.future.set_exception(
                            BackendError(f"batch execution failed: {e}")
                        )
                continue
            dur = time.perf_counter() - t0
            for (_, pend), out in zip(members, outs):
                if pend.trace is not None:
                    pend.trace.add_span(
                        "scheduler_batch", t0, dur, batch=len(members)
                    )
                pend.future.set_result(
                    GenerationResult(
                        text=out.text,
                        num_tokens=out.num_tokens,
                        logprob=out.logprob,
                    )
                )


class ServingBackend(Backend):
    """Backend seam over a shared :class:`BatchScheduler` — multiple
    coordinators/eval harnesses share one chip efficiently."""

    def __init__(self, scheduler: BatchScheduler):
        self.scheduler = scheduler

    def health(self) -> dict:
        """Gateway readiness probe surface: the scheduler heartbeat."""
        return self.scheduler.heartbeat()

    async def generate_batch(
        self, requests: list[GenerationRequest]
    ) -> list[GenerationResult]:
        import asyncio

        futures = [self.scheduler.submit(r) for r in requests]
        return await asyncio.gather(
            *(asyncio.wrap_future(f) for f in futures)
        )
