"""Cross-model vocab alignment for speculative decoding (PR 18).

A draft model speeds decoding only when its proposals land in the
TARGET model's token space. With one tokenizer the spaces coincide and
the batcher's draft machinery (PR 9) needs no translation; a
heterogeneous panel — the paper's point — pairs a small proposer with a
large judge whose tokenizers may differ. This module builds the
exact-match remap tables that let the small model's greedy stream feed
the large model's Leviathan verify anyway:

- ``d2t`` maps each DRAFT vocab id to the target id whose single-token
  round trip matches it byte-for-byte (decode under the draft
  tokenizer, re-encode under the target's; accept only if that encodes
  back to exactly one token which decodes to the same string).
- ``t2d`` is the inverse view — the id the DRAFT model should be fed
  when the target commits a token. Target ids without a single-token
  draft equivalent fall back to the draft's pad id: the draft model
  sees a blind spot, acceptance drops for that context, correctness
  does not (the accept rule in :mod:`llm_consensus_tpu.engine.accept`
  is exact for ANY draft proposal, including a garbage one).

Because the batcher drafts greedily (one-hot q), a remapped draft is
still just "some proposal" to the verify program — alignment quality
moves the ACCEPTANCE RATE, never the emitted bytes. That invariant is
what the cross-model byte-parity test pins.

Coverage below ``min_coverage`` means the pairing would burn a full
draft plane for near-zero acceptance, so :func:`align_vocabs` returns
None with a construction warning — the documented disengage, mirroring
the batcher's other no-silent-disengage warnings.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

import numpy as np

from llm_consensus_tpu.engine.tokenizer import Tokenizer

__all__ = ["VocabMap", "align_vocabs"]

log = logging.getLogger(__name__)

# Ids above this are never scanned: exact-match alignment decodes and
# re-encodes every candidate id on the host at construction, and real
# tokenizers run 32k-256k ids. The cap bounds startup cost; ids past it
# simply stay unmapped (they lower coverage, which the threshold then
# judges). Callers with known-small vocabs (bytes: 259) never hit it.
_DEFAULT_SCAN_LIMIT = 65536


@dataclass(frozen=True)
class VocabMap:
    """Exact-match token remap between a draft and a target vocab.

    ``d2t``: int32 [draft_vocab] — target id per draft id (target pad
    where unmapped). ``t2d``: int32 [target_vocab] — draft id per
    target id (draft pad where unmapped). ``coverage``: mapped fraction
    of the scanned draft vocab. ``identity``: the tokenizers agree on
    every scanned id AND the vocab sizes match — the batcher skips the
    gather entirely and behaves exactly as the one-tokenizer PR-9 path.
    """

    d2t: np.ndarray
    t2d: np.ndarray
    coverage: float
    identity: bool
    n_mapped: int

    def scope_key(self) -> tuple:
        """Cheap content digest for store-key scoping: two maps that
        hash differently must never share host-tier entries (the draft
        planes a restore installs were written through this map)."""
        if self.identity:
            return ("vocab_map", "identity", len(self.d2t), len(self.t2d))
        import hashlib

        h = hashlib.sha1(self.d2t.tobytes())
        h.update(self.t2d.tobytes())
        return ("vocab_map", h.hexdigest()[:16], self.n_mapped)

    def sized_to(
        self,
        target_vocab: int,
        draft_vocab: int,
        *,
        target_pad: int = 0,
        draft_pad: int = 0,
    ) -> "VocabMap":
        """Copy extended to MODEL-config vocab sizes. Alignment runs in
        tokenizer space, but model embeddings are commonly padded past
        the tokenizer (lane tiling), and the batcher gathers with model
        token ids — so the tables must span the model vocabs. Padded-
        tail ids are unmapped (-> pad): a random-weight argmax landing
        there drafts pad and gets rejected, never out-indexes. Identity
        survives only when both model vocabs already match the tables
        (equal-size pass-through skips the gather, which is only safe
        when every representable id means the same thing on both
        sides)."""
        if target_vocab < len(self.t2d) or draft_vocab < len(self.d2t):
            raise ValueError(
                f"model vocab ({target_vocab} target / {draft_vocab} "
                f"draft) smaller than the tokenizer tables "
                f"({len(self.t2d)} / {len(self.d2t)}) — the tokenizer "
                "emits ids the model cannot embed"
            )
        if target_vocab == len(self.t2d) and draft_vocab == len(self.d2t):
            return self
        d2t = np.full(draft_vocab, target_pad, dtype=np.int32)
        t2d = np.full(target_vocab, draft_pad, dtype=np.int32)
        d2t[: len(self.d2t)] = self.d2t
        t2d[: len(self.t2d)] = self.t2d
        identity = self.identity and target_vocab == draft_vocab
        if identity:
            # Same tokenizer layout, equal padded vocabs: the tail maps
            # to itself, matching the PR-9 single-tokenizer pass-through.
            tail = np.arange(len(self.d2t), draft_vocab, dtype=np.int32)
            d2t[len(self.d2t) :] = tail
            t2d[len(self.t2d) :] = tail
        return VocabMap(
            d2t=d2t,
            t2d=t2d,
            coverage=self.coverage,
            identity=identity,
            n_mapped=self.n_mapped,
        )


def _single_token_match(src: Tokenizer, dst: Tokenizer, tid: int):
    """Target-side id for ``tid`` iff the round trip is exact: decode
    under ``src``, re-encode under ``dst`` to exactly one id whose own
    decode reproduces the string. Returns None otherwise."""
    try:
        s = src.decode([tid])
    except Exception:  # noqa: BLE001 - undecodable id = unmapped
        return None
    if not s:
        return None
    try:
        out = dst.encode(s, add_bos=False)
    except Exception:  # noqa: BLE001 - unencodable text = unmapped
        return None
    if len(out) != 1:
        return None
    try:
        if dst.decode(out) != s:
            return None
    except Exception:  # noqa: BLE001
        return None
    return int(out[0])


def align_vocabs(
    target_tok: Tokenizer,
    draft_tok: Tokenizer,
    *,
    min_coverage: float = 0.5,
    scan_limit: int = _DEFAULT_SCAN_LIMIT,
) -> VocabMap | None:
    """Build the exact-match :class:`VocabMap` draft→target, or None
    (with a warning) when shared-subset coverage is below
    ``min_coverage`` — the construction-time disengage.

    Special ids (pad/bos/eos) are pinned to their counterparts without
    a round trip: their decode is typically empty/unstable, but the
    correspondence is structural. The same tokenizer object (or two
    byte tokenizers — a closed class with one fixed id layout) short-
    circuits to the identity map.
    """
    vt = int(target_tok.vocab_size)
    vd = int(draft_tok.vocab_size)
    d2t = np.full(vd, target_tok.pad_id, dtype=np.int32)
    t2d = np.full(vt, draft_tok.pad_id, dtype=np.int32)

    same_object = target_tok is draft_tok
    from llm_consensus_tpu.engine.tokenizer import ByteTokenizer

    both_bytes = isinstance(target_tok, ByteTokenizer) and isinstance(
        draft_tok, ByteTokenizer
    )
    if same_object or both_bytes:
        n = min(vt, vd)
        ids = np.arange(n, dtype=np.int32)
        d2t[:n] = ids
        t2d[:n] = ids
        return VocabMap(
            d2t=d2t,
            t2d=t2d,
            coverage=n / max(vd, 1),
            identity=(vt == vd),
            n_mapped=n,
        )

    # Structural specials first — they anchor the map even when their
    # decode round trip is degenerate.
    for did, tid in (
        (draft_tok.pad_id, target_tok.pad_id),
        (draft_tok.bos_id, target_tok.bos_id),
        (draft_tok.eos_id, target_tok.eos_id),
    ):
        if 0 <= did < vd and 0 <= tid < vt:
            d2t[did] = tid
            t2d[tid] = did

    specials_d = {draft_tok.pad_id, draft_tok.bos_id, draft_tok.eos_id}
    scanned = 0
    mapped = 0
    identity = vt == vd
    limit = min(vd, scan_limit)
    for did in range(limit):
        if did in specials_d:
            continue
        scanned += 1
        tid = _single_token_match(draft_tok, target_tok, did)
        if tid is None:
            identity = False
            continue
        d2t[did] = tid
        mapped += 1
        if tid != did:
            identity = False
        # First writer wins on the inverse: two draft ids round-
        # tripping to one target id is a draft-side aliasing quirk;
        # the earlier (usually canonical) id keeps the slot.
        if t2d[tid] == draft_tok.pad_id or tid == target_tok.pad_id:
            t2d[tid] = did
    if vd > limit:
        identity = False
        log.warning(
            "vocab alignment scanned %d of %d draft ids (scan_limit): "
            "unscanned ids stay unmapped and count against coverage",
            limit,
            vd,
        )

    coverage = mapped / max(scanned, 1)
    n_mapped = mapped + len({d for d in specials_d if 0 <= d < vd})
    if coverage < min_coverage:
        log.warning(
            "cross-model speculation DISENGAGED: exact-match vocab "
            "coverage %.1f%% (mapped %d of %d scanned draft ids) is "
            "below the %.1f%% threshold — a draft proposing outside "
            "the shared subset would be rejected nearly every round, "
            "paying the full draft planes for no speedup. Serving "
            "continues without a draft for this pairing.",
            100.0 * coverage,
            mapped,
            scanned,
            100.0 * min_coverage,
        )
        return None
    return VocabMap(
        d2t=d2t,
        t2d=t2d,
        coverage=coverage,
        identity=identity,
        n_mapped=n_mapped,
    )
