"""Training: sharded causal-LM train step (persona tuning substrate).

The reference conditions personas purely by prompt ("tuning" strings,
``src/main.rs:359-426``) and trains nothing. The TPU framework supplies a
real training path — fine-tuning persona/panel models is how domain
conditioning scales past prompt engineering — and the same sharded train
step is the multi-chip dry-run surface (``__graft_entry__.dryrun_multichip``).
"""

from llm_consensus_tpu.training.data import SftBatchLoader, TokenBatchLoader
from llm_consensus_tpu.training.loop import (
    LoopConfig,
    TrainReport,
    run_training,
)
from llm_consensus_tpu.training.train import (
    TrainConfig,
    TrainState,
    causal_lm_loss,
    make_optimizer,
    make_sharded_train_step,
    make_train_step,
)

__all__ = [
    "SftBatchLoader",
    "TokenBatchLoader",
    "LoopConfig",
    "TrainConfig",
    "TrainReport",
    "TrainState",
    "causal_lm_loss",
    "make_optimizer",
    "make_sharded_train_step",
    "make_train_step",
    "run_training",
]
