"""Training data pipeline: token shards -> [B, S] device batches.

Feeds :func:`llm_consensus_tpu.training.train.make_train_step`. Uses the
native mmap/prefetch loader (:class:`llm_consensus_tpu.native.NativeLoader`)
when libconsensus_rt is built, else an equivalent pure-numpy sampler.
Shards are raw little-endian int32 token files (see
:func:`write_token_shard`). The reference has no data/training pipeline
at all (SURVEY.md §2).
"""

from __future__ import annotations

import os
from pathlib import Path

import numpy as np


def write_token_shard(path: str | os.PathLike, tokens: np.ndarray) -> None:
    """Write a 1-D int32 token array as a raw shard file."""
    np.ascontiguousarray(tokens, np.int32).tofile(path)


class TokenBatchLoader:
    """Random contiguous [batch, seq] windows from a token shard.

    Iterating yields ``(tokens, loss_mask)`` numpy pairs ready for the
    train step (mask is all-ones; document-boundary masking can be
    layered on by storing EOS tokens in the shard).
    """

    def __init__(
        self,
        path: str | os.PathLike,
        batch: int,
        seq: int,
        seed: int = 0,
        prefer_native: bool = True,
    ):
        self.path = Path(path)
        self.batch, self.seq = batch, seq
        self._seed = seed
        self._drawn = 0
        self._native = None
        if prefer_native:
            try:
                from llm_consensus_tpu.native import NativeLoader, available

                if available():
                    self._native = NativeLoader(self.path, batch, seq, seed)
            except FileNotFoundError:
                raise
            except Exception:  # noqa: BLE001 - build/toolchain issues
                self._native = None
        if self._native is None:
            self._tokens = np.fromfile(self.path, np.int32)
            if self._tokens.size < seq + 1:
                raise ValueError(
                    f"shard {path} has {self._tokens.size} tokens < seq+1"
                )
            self._rng = np.random.default_rng(seed)

    @property
    def native(self) -> bool:
        return self._native is not None

    @property
    def position(self) -> int:
        """Number of batches drawn so far (for exact training resume)."""
        return self._drawn

    def seek(self, position: int) -> None:
        """Reposition the stream so the next batch is batch ``position``
        of a fresh same-seed loader (checkpoint-resume determinism).

        Pure-numpy path fast-forwards the RNG without touching token
        data; the native path redraws (it owns its RNG in C).
        """
        if position < self._drawn:
            # Restart the stream from the beginning.
            if self._native is not None:
                from llm_consensus_tpu.native import NativeLoader

                self._native.close()
                self._native = NativeLoader(
                    self.path, self.batch, self.seq, self._seed
                )
            else:
                self._rng = np.random.default_rng(self._seed)
            self._drawn = 0
        if self._native is not None:
            self._native.skip(position - self._drawn)
            self._drawn = position
        else:
            while self._drawn < position:
                self._rng.integers(
                    0, self._tokens.size - self.seq, size=self.batch
                )
                self._drawn += 1

    @property
    def n_tokens(self) -> int:
        if self._native is not None:
            return self._native.n_tokens
        return int(self._tokens.size)

    def next(self) -> tuple[np.ndarray, np.ndarray]:
        if self._native is not None:
            toks = self._native.next()
        else:
            starts = self._rng.integers(
                0, self._tokens.size - self.seq, size=self.batch
            )
            toks = np.stack(
                [self._tokens[s : s + self.seq] for s in starts]
            )
        self._drawn += 1
        mask = np.ones_like(toks, np.float32)
        return toks, mask

    def __iter__(self):
        while True:
            yield self.next()

    def close(self) -> None:
        if self._native is not None:
            self._native.close()
            self._native = None
