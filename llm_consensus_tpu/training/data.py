"""Training data pipeline: token shards -> [B, S] device batches.

Feeds :func:`llm_consensus_tpu.training.train.make_train_step`. Uses the
native mmap/prefetch loader (:class:`llm_consensus_tpu.native.NativeLoader`)
when libconsensus_rt is built, else an equivalent pure-numpy sampler.
Shards are raw little-endian int32 token files (see
:func:`write_token_shard`). The reference has no data/training pipeline
at all (SURVEY.md §2).
"""

from __future__ import annotations

import os
from pathlib import Path

import numpy as np


def write_token_shard(path: str | os.PathLike, tokens: np.ndarray) -> None:
    """Write a 1-D int32 token array as a raw shard file."""
    np.ascontiguousarray(tokens, np.int32).tofile(path)


class SftBatchLoader:
    """Padded per-example batches with completion-only loss masks.

    Supervised fine-tuning counterpart of :class:`TokenBatchLoader` for
    (prompt, completion) pairs (the arithmetic accuracy loop,
    ``examples/train_arith_em.py``): each ``next()`` draws a seeded
    random batch of examples, right-pads to ``[batch, seq]`` with
    ``pad_id``, and builds the loss mask so only *completion-token
    predictions* count — ``mask[i] = 1`` exactly where ``tokens[i+1]``
    is a completion token, matching ``causal_lm_loss``'s one-position
    shift. Exposes the same ``position``/``seek`` resume contract as
    :class:`TokenBatchLoader`.
    """

    def __init__(
        self,
        examples: list[tuple[list[int], list[int]]],
        batch: int,
        seq: int,
        seed: int = 0,
        pad_id: int = 0,
    ):
        self.batch, self.seq = batch, seq
        self.pad_id = pad_id
        self._seed = seed
        self._drawn = 0
        self._data: list[tuple[np.ndarray, int]] = []
        for p, c in examples:
            ids = np.asarray((list(p) + list(c))[:seq], np.int32)
            if len(p) >= len(ids):
                continue  # completion truncated away entirely: no signal
            if len(ids) < 2:
                continue  # a single token has no next-token target
            self._data.append((ids, len(p)))
        if not self._data:
            raise ValueError("no example fits within seq")
        self._rng = np.random.default_rng(seed)

    @property
    def n_examples(self) -> int:
        return len(self._data)

    @property
    def position(self) -> int:
        return self._drawn

    def seek(self, position: int) -> None:
        if position < self._drawn:
            self._rng = np.random.default_rng(self._seed)
            self._drawn = 0
        while self._drawn < position:
            self._rng.integers(0, len(self._data), size=self.batch)
            self._drawn += 1

    def next(self) -> tuple[np.ndarray, np.ndarray]:
        idx = self._rng.integers(0, len(self._data), size=self.batch)
        toks = np.full((self.batch, self.seq), self.pad_id, np.int32)
        mask = np.zeros((self.batch, self.seq), np.float32)
        for r, j in enumerate(idx):
            ids, p = self._data[j]
            toks[r, : len(ids)] = ids
            # Predictions of tokens p..len-1 (the completion) live at
            # predictor positions p-1..len-2. An empty prompt (p=0)
            # clamps to 0: token 0 itself has no predictor, and the
            # naive p-1 slice would wrap to seq-1 and zero the mask.
            mask[r, max(p - 1, 0) : len(ids) - 1] = 1.0
        self._drawn += 1
        return toks, mask

    def __iter__(self):
        while True:
            yield self.next()

    def close(self) -> None:  # loader-protocol parity
        pass


class TokenBatchLoader:
    """Random contiguous [batch, seq] windows from a token shard.

    Iterating yields ``(tokens, loss_mask)`` numpy pairs ready for the
    train step (mask is all-ones; document-boundary masking can be
    layered on by storing EOS tokens in the shard).
    """

    def __init__(
        self,
        path: str | os.PathLike,
        batch: int,
        seq: int,
        seed: int = 0,
        prefer_native: bool = True,
    ):
        self.path = Path(path)
        self.batch, self.seq = batch, seq
        self._seed = seed
        self._drawn = 0
        self._native = None
        if prefer_native:
            try:
                from llm_consensus_tpu.native import NativeLoader, available

                if available():
                    self._native = NativeLoader(self.path, batch, seq, seed)
            except FileNotFoundError:
                raise
            except Exception:  # noqa: BLE001 - build/toolchain issues
                self._native = None
        if self._native is None:
            self._tokens = np.fromfile(self.path, np.int32)
            if self._tokens.size < seq + 1:
                raise ValueError(
                    f"shard {path} has {self._tokens.size} tokens < seq+1"
                )
            self._rng = np.random.default_rng(seed)

    @property
    def native(self) -> bool:
        return self._native is not None

    @property
    def position(self) -> int:
        """Number of batches drawn so far (for exact training resume)."""
        return self._drawn

    def seek(self, position: int) -> None:
        """Reposition the stream so the next batch is batch ``position``
        of a fresh same-seed loader (checkpoint-resume determinism).

        Pure-numpy path fast-forwards the RNG without touching token
        data; the native path redraws (it owns its RNG in C).
        """
        if position < self._drawn:
            # Restart the stream from the beginning.
            if self._native is not None:
                from llm_consensus_tpu.native import NativeLoader

                self._native.close()
                self._native = NativeLoader(
                    self.path, self.batch, self.seq, self._seed
                )
            else:
                self._rng = np.random.default_rng(self._seed)
            self._drawn = 0
        if self._native is not None:
            self._native.skip(position - self._drawn)
            self._drawn = position
        else:
            while self._drawn < position:
                self._rng.integers(
                    0, self._tokens.size - self.seq, size=self.batch
                )
                self._drawn += 1

    @property
    def n_tokens(self) -> int:
        if self._native is not None:
            return self._native.n_tokens
        return int(self._tokens.size)

    def next(self) -> tuple[np.ndarray, np.ndarray]:
        if self._native is not None:
            toks = self._native.next()
        else:
            starts = self._rng.integers(
                0, self._tokens.size - self.seq, size=self.batch
            )
            toks = np.stack(
                [self._tokens[s : s + self.seq] for s in starts]
            )
        self._drawn += 1
        mask = np.ones_like(toks, np.float32)
        return toks, mask

    def __iter__(self):
        while True:
            yield self.next()

    def close(self) -> None:
        if self._native is not None:
            self._native.close()
            self._native = None
