"""Training loop driver: data -> sharded step -> checkpoint/resume.

The reference has no training at all (SURVEY.md §2); this driver is the
missing "run it for real" layer over
:mod:`llm_consensus_tpu.training.train`:

- builds the right step for the mesh (unsharded / GSPMD-sharded /
  GPipe-pipelined when the mesh has a ``pipe`` axis),
- checkpoints every ``ckpt_every`` steps WITH loader position + step in
  the metadata, and resumes exactly (same step count, same data order)
  if the checkpoint dir already holds state — crash-and-restart yields
  the same training trajectory,
- logs loss + tokens/sec at ``log_every``.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from pathlib import Path

import jax
import numpy as np

from llm_consensus_tpu.models.configs import ModelConfig
from llm_consensus_tpu.models.transformer import init_params
from llm_consensus_tpu.training.train import (
    TrainConfig,
    TrainState,
    init_train_state,
    make_sharded_train_step,
    make_train_step,
)

log = logging.getLogger(__name__)


@dataclass
class LoopConfig:
    total_steps: int = 100
    log_every: int = 10
    ckpt_every: int = 0  # 0 = no checkpointing
    ckpt_dir: str | None = None
    n_microbatches: int = 2  # used only for pipeline meshes
    seed: int = 0


@dataclass
class StepLog:
    step: int
    loss: float
    tokens_per_sec: float


@dataclass
class TrainReport:
    final_step: int
    losses: list[StepLog] = field(default_factory=list)
    resumed_from: int | None = None


_LATEST = "LATEST"


def _latest_checkpoint(ckpt_dir: str) -> Path | None:
    """Resolve the newest COMPLETE checkpoint under ``ckpt_dir``.

    Checkpoints are versioned subdirectories committed by atomically
    updating a LATEST pointer file after the save finishes — a crash
    mid-save leaves a dangling step dir but LATEST still names the last
    complete one, so state and metadata can never mismatch. Falls back
    to ``ckpt_dir`` itself for legacy flat layouts.
    """
    root = Path(ckpt_dir)
    pointer = root / _LATEST
    if pointer.exists():
        candidate = root / pointer.read_text().strip()
        if (candidate / "state").exists():
            return candidate
    if (root / "state").exists():  # legacy flat layout
        return root
    return None


def _save_checkpoint(ckpt_dir: str, state, done: int, loader) -> None:
    from llm_consensus_tpu.checkpoint.io import save_train_state

    root = Path(ckpt_dir)
    step_dir = root / f"step_{done}"
    # State passes through as-is: orbax handles sharded arrays (each
    # host writes its shards); gathering to host would break multi-host
    # and triple host RAM.
    save_train_state(
        step_dir,
        state,
        extra={
            "step": done,
            "loader_position": getattr(loader, "position", 0),
        },
    )
    # Commit: atomic pointer swap. Readers never see a half-written
    # checkpoint as current.
    tmp = root / (_LATEST + ".tmp")
    tmp.write_text(step_dir.name)
    tmp.replace(root / _LATEST)
    # Prune everything older than the two newest complete checkpoints.
    keep = {step_dir.name}
    steps = sorted(
        (
            int(p.name.split("_")[1])
            for p in root.glob("step_*")
            if p.name != step_dir.name and (p / "state").exists()
        ),
        reverse=True,
    )
    keep.update(f"step_{s}" for s in steps[:1])
    import shutil

    for p in root.glob("step_*"):
        if p.name not in keep:
            shutil.rmtree(p, ignore_errors=True)
    log.info("checkpointed step %d -> %s", done, step_dir)


def _make_step(cfg: ModelConfig, tcfg: TrainConfig, mesh, micro: int):
    if mesh is None:
        step = make_train_step(cfg, tcfg)
        return step, lambda s, t, m: (s, t, m)
    if mesh.shape.get("pipe", 1) > 1:
        from llm_consensus_tpu.parallel.pipeline import (
            make_pipeline_train_step,
        )

        return make_pipeline_train_step(cfg, tcfg, mesh, micro)
    return make_sharded_train_step(cfg, tcfg, mesh)


def run_training(
    cfg: ModelConfig,
    tcfg: TrainConfig,
    loader,
    loop: LoopConfig | None = None,
    mesh=None,
    params: dict | None = None,
) -> tuple[TrainState, TrainReport]:
    """Train for ``loop.total_steps`` steps (absolute, resume-aware).

    ``loader`` must yield ``(tokens, loss_mask)`` numpy batches from
    ``next()`` and expose ``position``/``seek(position)`` for exact
    resume (``training.data.TokenBatchLoader`` does).
    """
    loop = loop or LoopConfig()
    # tcfg.total_steps defines the LR schedule and must be identical
    # across checkpoint-resumed legs (loop.total_steps is just "train
    # until step N"), so never mutate it — but training past the
    # schedule end means silently riding the decay floor: say so.
    if loop.total_steps > tcfg.total_steps:
        log.warning(
            "loop.total_steps=%d exceeds the LR schedule length "
            "(TrainConfig.total_steps=%d); steps past it use the decay "
            "floor LR",
            loop.total_steps,
            tcfg.total_steps,
        )
    report = TrainReport(final_step=0)

    resume_dir = _latest_checkpoint(loop.ckpt_dir) if loop.ckpt_dir else None

    start_step = 0
    if resume_dir is not None:
        from llm_consensus_tpu.checkpoint.io import restore_train_state

        # Abstract template: no point materializing a random init (and
        # full optimizer moments) just to describe shapes.
        template = jax.eval_shape(
            lambda: init_train_state(
                cfg,
                params
                if params is not None
                else init_params(
                    cfg, jax.random.PRNGKey(loop.seed), dtype=jax.numpy.float32
                ),
                tcfg,
            )
        )
        state, extra = restore_train_state(resume_dir, template)
        extra = extra or {}
        start_step = int(extra.get("step", state.step))
        if "loader_position" in extra and hasattr(loader, "seek"):
            loader.seek(int(extra["loader_position"]))
        else:
            log.warning(
                "resuming at step %d WITHOUT restoring data position "
                "(meta has loader_position: %s; loader has seek(): %s) — "
                "the data order will differ from an uninterrupted run",
                start_step,
                "loader_position" in extra,
                hasattr(loader, "seek"),
            )
        report.resumed_from = start_step
        log.info("resumed from %s at step %d", resume_dir, start_step)
    else:
        if params is None:
            params = init_params(
                cfg, jax.random.PRNGKey(loop.seed), dtype=jax.numpy.float32
            )
        state = init_train_state(cfg, params, tcfg)

    step_fn, place = _make_step(cfg, tcfg, mesh, loop.n_microbatches)
    batch_shardings = None  # captured from the first placed batch

    t_last = time.perf_counter()
    tokens_since = 0
    for step_i in range(start_step, loop.total_steps):
        tokens, mask = loader.next()
        tokens = np.asarray(tokens)
        mask = np.asarray(mask, np.float32)
        if mesh is None:
            s_tokens, s_mask = tokens, mask
        elif batch_shardings is None:
            # First step: place the full state + batch per the step's
            # sharding rules, then reuse the batch shardings.
            state, s_tokens, s_mask = place(state, tokens, mask)
            batch_shardings = (s_tokens.sharding, s_mask.sharding)
        else:
            s_tokens = jax.device_put(tokens, batch_shardings[0])
            s_mask = jax.device_put(mask, batch_shardings[1])
        state, loss = step_fn(state, s_tokens, s_mask)
        tokens_since += int(tokens.size)

        done = step_i + 1
        if loop.log_every and done % loop.log_every == 0:
            dt = max(time.perf_counter() - t_last, 1e-9)
            entry = StepLog(
                step=done,
                loss=float(loss),
                tokens_per_sec=tokens_since / dt,
            )
            report.losses.append(entry)
            log.info(
                "step %d loss %.4f %.0f tok/s",
                entry.step,
                entry.loss,
                entry.tokens_per_sec,
            )
            t_last = time.perf_counter()
            tokens_since = 0

        if loop.ckpt_every and loop.ckpt_dir and done % loop.ckpt_every == 0:
            _save_checkpoint(loop.ckpt_dir, state, done, loader)

    report.final_step = loop.total_steps
    return state, report
