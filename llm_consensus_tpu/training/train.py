"""Causal-LM training step, sharded over the 4-axis mesh.

TPU-first design:
- one jitted step: loss + grads + optax update, donated state;
- rematerialization (``jax.checkpoint``) over the layer scan trades
  FLOPs for HBM on long sequences;
- sharding is declarative: params follow
  :func:`llm_consensus_tpu.parallel.partitioning.param_pspecs` (TP over
  ``model``, EP over ``expert``), batches shard over ``data``; GSPMD
  inserts the gradient psums — no hand-written collectives (the
  reference has no training or distributed backend at all, SURVEY.md §2).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from llm_consensus_tpu.models.configs import ModelConfig
from llm_consensus_tpu.models.transformer import forward
from llm_consensus_tpu.parallel.partitioning import param_pspecs


@dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 3e-4
    weight_decay: float = 0.01
    b1: float = 0.9
    b2: float = 0.95
    grad_clip: float = 1.0
    warmup_steps: int = 100
    total_steps: int = 10_000
    remat: bool = True
    # Mixed precision: keep fp32 master params in the train state, cast
    # to this dtype inside the loss for MXU-speed matmuls (set
    # "bfloat16" on TPU), with full-precision grads/updates applied to
    # the masters. None = compute in the params' own dtype.
    compute_dtype: str | None = None


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    params: dict
    opt_state: object
    step: jnp.ndarray


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.learning_rate,
        warmup_steps=cfg.warmup_steps,
        decay_steps=max(cfg.total_steps, cfg.warmup_steps + 1),
        end_value=cfg.learning_rate * 0.1,
    )
    return optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip),
        optax.adamw(
            schedule, b1=cfg.b1, b2=cfg.b2, weight_decay=cfg.weight_decay
        ),
    )


def _cast_params(params, dtype: str | None):
    if not dtype:
        return params
    target = jnp.dtype(dtype)
    return jax.tree_util.tree_map(
        lambda x: x.astype(target)
        if jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        params,
    )


def causal_lm_loss(
    cfg: ModelConfig,
    params: dict,
    tokens: jnp.ndarray,
    loss_mask: jnp.ndarray,
    remat: bool = True,
    compute_dtype: str | None = None,
    mesh=None,
) -> jnp.ndarray:
    """Next-token cross-entropy. tokens [B, S]; loss_mask [B, S] with 1.0
    on positions whose *prediction* (of the next token) counts.

    ``compute_dtype``: cast float params to this dtype for the forward
    (mixed precision — the cast sits inside grad, so gradients flow back
    to the original-dtype masters). ``mesh`` routes attention through
    ring attention when ``cfg.use_ring`` and the mesh has ``seq > 1`` —
    true sequence parallelism, not just activation sharding.

    MoE configs add the router auxiliary terms (load-balance + z-loss,
    weighted by ``cfg.moe_aux_loss_weight`` / ``cfg.moe_z_loss_weight``)
    — without them top-k routing collapses onto a few experts during
    training (the Mixtral config, BASELINE.md config[2]).
    """
    params = _cast_params(params, compute_dtype)
    moe_aux = cfg.is_moe and (
        cfg.moe_aux_loss_weight > 0 or cfg.moe_z_loss_weight > 0
    )
    if moe_aux:
        logits, aux = forward(
            cfg, params, tokens, remat=remat, mesh=mesh, return_moe_aux=True
        )
    else:
        logits = forward(cfg, params, tokens, remat=remat, mesh=mesh)
    targets = tokens[:, 1:]
    lp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    nll = -jnp.take_along_axis(lp, targets[..., None], axis=-1)[..., 0]
    mask = loss_mask[:, :-1].astype(jnp.float32)
    loss = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    if moe_aux:
        loss = (
            loss
            + cfg.moe_aux_loss_weight * aux["load_balance"]
            + cfg.moe_z_loss_weight * aux["z_loss"]
        )
    return loss


def init_train_state(
    cfg: ModelConfig, params: dict, tcfg: TrainConfig
) -> TrainState:
    opt = make_optimizer(tcfg)
    return TrainState(
        params=params,
        opt_state=opt.init(params),
        step=jnp.zeros((), jnp.int32),
    )


def make_train_step(cfg: ModelConfig, tcfg: TrainConfig):
    """Unsharded (single-device / auto-sharded) train step."""
    opt = make_optimizer(tcfg)

    @partial(jax.jit, donate_argnums=(0,))
    def step(state: TrainState, tokens, loss_mask):
        loss, grads = jax.value_and_grad(
            lambda p: causal_lm_loss(
                cfg, p, tokens, loss_mask, tcfg.remat, tcfg.compute_dtype
            )
        )(state.params)
        updates, opt_state = opt.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(params=params, opt_state=opt_state, step=state.step + 1),
            loss,
        )

    return step


def make_sharded_train_step(cfg: ModelConfig, tcfg: TrainConfig, mesh: Mesh):
    """Train step jitted with explicit mesh shardings.

    Params/opt-state shard per :func:`param_pspecs` (TP/EP), batches over
    ``data``; the returned ``place`` helper puts a host state/batch onto
    the mesh with those shardings.
    """
    opt = make_optimizer(tcfg)

    def step(state: TrainState, tokens, loss_mask):
        loss, grads = jax.value_and_grad(
            lambda p: causal_lm_loss(
                cfg,
                p,
                tokens,
                loss_mask,
                tcfg.remat,
                tcfg.compute_dtype,
                mesh=mesh if cfg.use_ring else None,
            )
        )(state.params)
        updates, opt_state = opt.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(params=params, opt_state=opt_state, step=state.step + 1),
            loss,
        )

    def place(state: TrainState, tokens, loss_mask):
        # Batch over `data`, sequence over `seq` (activation/sequence
        # parallelism for training; GSPMD inserts the attention gathers).
        return place_train_state(
            state,
            mesh,
            param_pspecs(state.params),
            batch_spec=P("data", "seq"),
            batches=(tokens, loss_mask),
        )

    jitted = jax.jit(step, donate_argnums=(0,))
    return jitted, place


def place_train_state(
    state: TrainState,
    mesh: Mesh,
    pspecs,
    *,
    batch_spec: P,
    batches: tuple,
):
    """Place a host TrainState + batch arrays onto the mesh.

    Params follow ``pspecs``. Optimizer state: optax moment trees (mu/nu)
    mirror the params tree, so an opt-state leaf's key-path *ends with*
    some param's key-path — shard it like that param. Everything else
    (step counts, scalars) replicates. Matching by path, not shape:
    distinct params can share a shape (wq/wo are both [L, D, D]) but need
    different specs.
    """
    param_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        pspecs,
        is_leaf=lambda x: isinstance(x, P),
    )
    params = jax.tree_util.tree_map(jax.device_put, state.params, param_sh)
    param_shardings = {
        tuple(str(k) for k in path): leaf.sharding
        for path, leaf in jax.tree_util.tree_leaves_with_path(params)
    }
    max_depth = max((len(k) for k in param_shardings), default=0)

    def put_opt(path, leaf):
        keys = tuple(str(k) for k in path)
        for start in range(max(0, len(keys) - max_depth), len(keys)):
            sh = param_shardings.get(keys[start:])
            if sh is not None:
                return jax.device_put(leaf, sh)
        return jax.device_put(leaf, NamedSharding(mesh, P()))

    opt_state = jax.tree_util.tree_map_with_path(put_opt, state.opt_state)
    batch_sh = NamedSharding(mesh, batch_spec)
    placed_state = TrainState(
        params=params,
        opt_state=opt_state,
        step=jax.device_put(state.step, NamedSharding(mesh, P())),
    )
    return (placed_state, *(jax.device_put(b, batch_sh) for b in batches))
