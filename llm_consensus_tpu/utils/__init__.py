"""Utilities: logging, tracing/profiling, deterministic RNG streams.

The reference's observability is ``log``+``env_logger`` only, and its
only timing is the REPL poll pacing (SURVEY.md §5). Here: structured
span tracing with wall-clock + optional JAX profiler integration, and
RUST_LOG-convention logging setup.
"""

from llm_consensus_tpu.utils.logging import setup_logging
from llm_consensus_tpu.utils.tracing import (
    Trace,
    Tracer,
    TraceStore,
    current_trace,
    request_span,
    span,
    trace_jax_profile,
    trace_store,
    use_trace,
)

__all__ = [
    "Trace",
    "Tracer",
    "TraceStore",
    "current_trace",
    "request_span",
    "setup_logging",
    "span",
    "trace_jax_profile",
    "trace_store",
    "use_trace",
]
