"""Persistent XLA compilation cache.

First TPU compilation of the decode program costs 20-40 s; a persistent
cache makes repeat CLI/serving launches near-instant. Off by default in
JAX; this turns it on with sane thresholds. (Reference counterpart: none
— it compiles nothing, SURVEY.md §0.)
"""

from __future__ import annotations

import logging
import os
from pathlib import Path

log = logging.getLogger(__name__)

_DEFAULT = "~/.cache/llm_consensus_tpu/xla"


def enable_compilation_cache(path: str | None = None) -> str | None:
    """Enable the persistent compile cache at ``path`` (idempotent).

    Honors ``LLM_CONSENSUS_CACHE_DIR``; returns the directory used, or
    None if enabling failed (old jax, read-only fs) — callers proceed
    either way.
    """
    import jax

    cache_dir = str(
        Path(
            path or os.environ.get("LLM_CONSENSUS_CACHE_DIR", _DEFAULT)
        ).expanduser()
    )
    try:
        Path(cache_dir).mkdir(parents=True, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        return cache_dir
    except Exception as e:  # noqa: BLE001 - cache is best-effort
        log.warning("compilation cache disabled: %s", e)
        return None
