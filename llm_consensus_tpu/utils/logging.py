"""Logging setup — env_logger parity.

The reference initializes ``env_logger`` (``src/main.rs:352``) and
controls verbosity with ``RUST_LOG``; here ``LLM_CONSENSUS_LOG`` plays
that role (same convention: a level name, optionally ``module=level``
pairs separated by commas).
"""

from __future__ import annotations

import logging
import os

_FORMAT = "[%(asctime)s %(levelname)s %(name)s] %(message)s"


def setup_logging(spec: str | None = None) -> None:
    """Configure logging from a RUST_LOG-style spec.

    ``spec`` defaults to ``$LLM_CONSENSUS_LOG`` (then ``info``).
    Examples: ``debug``, ``info,llm_consensus_tpu.consensus=debug``.
    """
    spec = spec if spec is not None else os.environ.get("LLM_CONSENSUS_LOG", "info")
    root_level = logging.INFO
    module_levels: dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            mod, _, lvl = part.partition("=")
            level = getattr(logging, lvl.strip().upper(), None)
            if isinstance(level, int):
                module_levels[mod.strip()] = level
        else:
            level = getattr(logging, part.upper(), None)
            if isinstance(level, int):
                root_level = level
    # force: reconfigure on repeat calls (basicConfig is otherwise a no-op
    # once a handler exists, so level changes would silently not apply).
    logging.basicConfig(level=root_level, format=_FORMAT, force=True)
    for mod, level in module_levels.items():
        logging.getLogger(mod).setLevel(level)
