"""Stop-sequence rules shared by every surface that honors them.

The stop contract (engine batch path, streaming, prefix-cached
generation, and the continuous batcher all promise the same observable
behavior — ``tests/test_paged.py::test_backend_stop_parity_local_vs_
continuous`` asserts it across the Backend seam) lives HERE once:

- :func:`earliest_stop_cut` — where to trim the final text (earliest
  occurrence of any stop; the stop itself is removed by the caller).
- :func:`stop_tail_window` — how many tail tokens a per-token host
  check must decode to be able to see a stop that ends at the newest
  token (longest stop's token length plus slack for a stop/multibyte
  sequence straddling the window head).

A precedence or slack change edited here propagates to every surface;
duplicated inline copies would silently disagree.
"""

from __future__ import annotations

from typing import Iterable


def earliest_stop_cut(text: str, stops: Iterable[str]) -> int:
    """Index of the earliest occurrence of any stop in ``text``; -1 if
    none occurs. Ties across stops resolve to the smallest index."""
    return min(
        (i for s in stops if (i := text.find(s)) >= 0),
        default=-1,
    )


def stop_tail_window(tokenizer, stops: Iterable[str], slack: int = 8) -> int:
    """Tail-token window width for incremental stop checks.

    The window must cover the WORST-CASE token count a model can spend
    emitting the stop text — not the count the tokenizer's own greedy
    encoding uses: a merge-based tokenizer may encode "\\n\\n---" as 2
    ids, but a model can emit the same characters one fine-grained
    token at a time. Every token decodes to at least one byte, so
    ``len(stop.encode("utf-8"))`` bounds the span for any tokenizer;
    the encoded length is kept as a floor for exotic multi-char-per-
    byte cases, and ``slack`` covers a multibyte character (or another
    stop's prefix) straddling the window head. Compute ONCE per
    request/call — tokenizer encodes on the thread pacing device steps
    are not free."""
    stops = list(stops)
    if not stops:
        return 0
    span = max(
        max(len(s.encode("utf-8")), len(tokenizer.encode(s, add_bos=False)))
        for s in stops
    )
    return span + slack
