"""Stop-sequence rules shared by every surface that honors them.

The stop contract (engine batch path, streaming, prefix-cached
generation, and the continuous batcher all promise the same observable
behavior — ``tests/test_paged.py::test_backend_stop_parity_local_vs_
continuous`` asserts it across the Backend seam) lives HERE once:

- :func:`earliest_stop_cut` — where to trim the final text (earliest
  occurrence of any stop; the stop itself is removed by the caller).
- :func:`stop_tail_window` — how many tail tokens a per-token host
  check must decode to be able to see a stop that ends at the newest
  token (longest stop's token length plus slack for a stop/multibyte
  sequence straddling the window head).
- :func:`single_token_stop_ids` — the ids a DEVICE loop may terminate
  on exactly (stops that encode to one id), shared by the engine's
  single-round batch path and anything else that device-stops.
- :func:`derived_stop_screen` — the CONSERVATIVE device-side token
  screen multi-round decode (PR 12) freezes on: every id whose decoded
  bytes could complete some stop. A screen hit is a *candidate*, not a
  verdict — the host's byte-level :meth:`VisibleIdFilter.
  confirmed_stop_hit` stays authoritative at fetch, so text is
  byte-identical whether the screen over- or under-fires; what the
  screen buys is that a row freezes (no further K/V writes, no further
  PRNG folds) at the first token that could possibly end it.

A precedence or slack change edited here propagates to every surface;
duplicated inline copies would silently disagree.
"""

from __future__ import annotations

from typing import Iterable, Sequence


def earliest_stop_cut(text: str, stops: Iterable[str]) -> int:
    """Index of the earliest occurrence of any stop in ``text``; -1 if
    none occurs. Ties across stops resolve to the smallest index."""
    return min(
        (i for s in stops if (i := text.find(s)) >= 0),
        default=-1,
    )


class VisibleIdFilter:
    """Sizes the stop-check tail window by VISIBLE token count.

    Incremental stop checks decode only a tail WINDOW of token ids
    (see :func:`stop_tail_window`); that window math assumes every id
    decodes to >=1 byte. Tokenizers with ids that decode to the empty
    string IN ISOLATION (special pieces, byte-fallback fragments) would
    stretch a stop across more than ``window`` tokens and the check
    would miss it — no wrong text (the final trim is exact) but the
    early exit the incremental check exists for is lost. This filter
    extends the tail slice until it holds ``window`` ids that decode to
    >=1 character on their own, WITHOUT dropping the empty-decoding ids
    from the returned slice: a byte-fallback fragment decodes to
    nothing alone but contributes its bytes in context, so the slice
    must stay contiguous for the window decode to assemble multi-piece
    characters. Only ``skip_ids`` (EOS — never mid-stream) are removed.

    Per-id emptiness is memoized — steady-state cost is dict lookups,
    not decodes. The backward scan is bounded at ``8 * window`` raw ids
    per check: if more than 7/8 of the tail decodes to nothing the
    window may still under-cover (strictly rarer than the unfiltered
    check, and the final full-text trim still guarantees exact output).
    """

    def __init__(self, tokenizer, skip_ids: Iterable[int] = ()):
        self._tok = tokenizer
        self._skip = frozenset(int(i) for i in skip_ids)
        self._empty: dict[int, bool] = {}

    def _is_empty(self, t: int) -> bool:
        e = self._empty.get(t)
        if e is None:
            e = self._tok.decode([t]) == ""
            self._empty[t] = e
        return e

    def visible_tail(self, ids: Sequence[int], window: int) -> list[int]:
        """Contiguous tail of ``ids`` containing ``window`` ids that
        decode to >=1 character (``skip_ids`` removed), scanning back
        at most ``8 * window`` ids."""
        if window <= 0:
            return []
        visible = 0
        span = 0
        for t in reversed(ids[-8 * window :]):
            span += 1
            t = int(t)
            if t in self._skip or self._is_empty(t):
                continue
            visible += 1
            if visible >= window:
                break
        return [int(t) for t in ids[-span:] if int(t) not in self._skip]

    def confirmed_stop_hit(
        self,
        ids: Sequence[int],
        stops: Sequence[str],
        window: int,
        full_text,
    ) -> bool:
        """Incremental stop check: tail-window scan, then full-decode
        confirm.

        The shape both retiring surfaces (engine ``_chunked_stop_decode``
        and the continuous batcher) must agree on: decode only a
        :meth:`visible_tail` window per check (O(T·window) host work,
        not O(T²)); on a window hit, CONFIRM against the full decoded
        text before reporting a stop — a merge-based tokenizer can
        decode a tail window differently from the full text at the
        window head, and retiring on such a false positive silently
        truncates a row the final ``earliest_stop_cut`` pass then finds
        no stop in. ``full_text`` is a zero-arg callable (full decode
        runs only on candidate hits, so the cost stays amortized).
        """
        if not stops:
            return False
        text = self._tok.decode(self.visible_tail(ids, window))
        if not any(s in text for s in stops):
            return False
        full = full_text()
        return any(s in full for s in stops)


def single_token_stop_ids(tokenizer, stops: Iterable[str]) -> tuple[int, ...]:
    """Stops that tokenize to exactly one id — the EXACT device-side
    terminators (a row sampling one of them finishes as if it sampled
    EOS). The engine's batch decode loop has always device-stopped
    these; the derivation lives here so the multi-round batcher and the
    engine read the same rule. Order-preserving, deduplicated."""
    ids = []
    for s in stops:
        enc = tokenizer.encode(s, add_bos=False)
        if len(enc) == 1:
            ids.append(int(enc[0]))
    return tuple(dict.fromkeys(ids))


def derived_stop_screen(
    tokenizer,
    stops: Iterable[str],
    *,
    max_ids: int = 8,
    max_vocab_scan: int = 4096,
) -> tuple[int, ...] | None:
    """Conservative single-token screen for device-side stop freezing.

    A stop sequence can only COMPLETE at a token whose contributed
    bytes contain the stop's final byte — so the set of ids whose
    decoded bytes contain any stop's last byte (plus ids that decode to
    nothing alone: byte-fallback fragments contribute bytes only in
    context, so they might hide the completing byte) is a sound screen
    for per-id-additive tokenizers: freeze the row at the first
    screened token, let the host's byte-level check confirm or resume.
    A false positive costs rounds, never correctness (the host trim at
    fetch is authoritative either way — see the module docstring).

    Returns ``()`` for no stops, a tuple of <= ``max_ids`` candidate
    ids when a usable screen exists, or ``None`` when no bounded screen
    is derivable — more than ``max_ids`` candidates (membership rides
    the decode program as a fixed-width row of data, so a fat screen
    would freeze constantly and bloat the program), or a vocabulary
    too large to scan (``max_vocab_scan``; the one-time scan decodes
    every id). ``None`` tells the caller to bound the multi-round
    window to 1 so the host sees every token — the pre-PR-12 cadence,
    exact for any tokenizer. Callers should memoize per stop tuple:
    the scan is O(vocab) and submit paths pace device steps.
    """
    stops = [s for s in stops if s]
    if not stops:
        return ()
    if getattr(tokenizer, "vocab_size", max_vocab_scan + 1) > max_vocab_scan:
        return None
    last_bytes = {
        s.encode("utf-8", errors="surrogateescape")[-1:] for s in stops
    }
    ids: list[int] = []
    for t in range(tokenizer.vocab_size):
        bs = tokenizer.decode([t]).encode("utf-8", errors="surrogateescape")
        if not bs or any(b in bs for b in last_bytes):
            ids.append(t)
            if len(ids) > max_ids:
                return None
    return tuple(ids)


def stop_tail_window(tokenizer, stops: Iterable[str], slack: int = 8) -> int:
    """Tail-token window width for incremental stop checks.

    The window must cover the WORST-CASE token count a model can spend
    emitting the stop text — not the count the tokenizer's own greedy
    encoding uses: a merge-based tokenizer may encode "\\n\\n---" as 2
    ids, but a model can emit the same characters one fine-grained
    token at a time. Every VISIBLE token decodes to at least one byte
    (callers filter empty-decoding ids out of the window slice with
    :class:`VisibleIdFilter`), so ``len(stop.encode("utf-8"))`` bounds
    the span for any tokenizer;
    the encoded length is kept as a floor for exotic multi-char-per-
    byte cases, and ``slack`` covers a multibyte character (or another
    stop's prefix) straddling the window head. Compute ONCE per
    request/call — tokenizer encodes on the thread pacing device steps
    are not free."""
    stops = list(stops)
    if not stops:
        return 0
    # surrogateescape: a stop carved from decoded model output can carry
    # lone surrogates standing in for invalid bytes (the ByteTokenizer's
    # reversible decode); each encodes back to exactly the one byte it
    # stands for, so the byte-length bound stays exact — strict UTF-8
    # would raise on text the engine itself produced.
    span = max(
        max(
            len(s.encode("utf-8", errors="surrogateescape")),
            len(tokenizer.encode(s, add_bos=False)),
        )
        for s in stops
    )
    return span + slack
