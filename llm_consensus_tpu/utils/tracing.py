"""Span tracing + JAX profiler hooks.

Tracing is ~absent in the reference (wall-clock only paces the readiness
poll, ``src/main.rs:449-454``; SURVEY.md §5). Two layers here:

- :class:`Tracer` / :func:`span` — lightweight flat wall-clock spans
  recorded as structured events (name, start, duration, metadata),
  queryable and dumpable to JSON; the engine's per-call instrumentation
  reports through this. Bounded by a ring buffer (``max_records``,
  evict-oldest) so a long-lived process cannot grow it without limit.
- **Request-scoped traces** (PR 5) — :class:`TraceStore` /
  :class:`Trace`: every gateway request gets a trace id at admission;
  the id propagates through the serving stack via a
  :mod:`contextvars` context (:func:`use_trace` /
  :func:`current_trace` / :func:`request_span`), and worker threads
  that cannot see the caller's context (the continuous batcher's host
  loop) attach spans explicitly via :meth:`Trace.add_span`. The store
  is a bounded ring of traces (evict-oldest), each trace a bounded
  span tree; drops are counted and mirrored into the Prometheus
  registry through :func:`set_drop_hook` (wired by
  :mod:`llm_consensus_tpu.server.metrics` on import, so the two
  surfaces move in lockstep). ``GET /debug/traces`` on the gateway
  renders :meth:`Trace.to_dict` span trees.
- :func:`trace_jax_profile` — context manager around
  ``jax.profiler.trace`` producing a TensorBoard-loadable device trace
  for the real TPU hot loop; the gateway's ``X-Profile: 1`` header
  (with ``serve --profile-dir``) drops one aligned with a request's
  host spans.

Process-wide tracing can be disabled entirely (:func:`set_enabled`,
``serve --no-trace``): :meth:`TraceStore.start` then returns ``None``
and every downstream call site degrades to a no-op — the knob the
``bench.py --serve-trace-overhead`` A/B leg toggles.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import threading
import time
import uuid
from collections import OrderedDict, deque
from contextvars import ContextVar
from dataclasses import dataclass, field


@dataclass
class SpanRecord:
    name: str
    start: float
    duration: float
    meta: dict = field(default_factory=dict)


class Tracer:
    """Collects timed spans; thread-safe (backend calls run in threads).

    ``max_records`` bounds memory: the oldest span is evicted when the
    ring is full, and :attr:`dropped` counts evictions (also mirrored
    into the Prometheus drop counter via the module drop hook).
    """

    def __init__(self, max_records: int = 4096) -> None:
        if max_records <= 0:
            raise ValueError(f"max_records must be > 0, got {max_records}")
        self.max_records = max_records
        self._records: deque[SpanRecord] = deque()
        self._dropped = 0
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def span(self, name: str, **meta):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - t0
            with self._lock:
                if len(self._records) >= self.max_records:
                    self._records.popleft()
                    self._dropped += 1
                    _notify_drop("span", 1)
                self._records.append(
                    SpanRecord(name=name, start=t0, duration=dur, meta=meta)
                )

    @property
    def records(self) -> list[SpanRecord]:
        with self._lock:
            return list(self._records)

    @property
    def dropped(self) -> int:
        """Spans evicted from the ring (recorded-then-lost count)."""
        return self._dropped

    def total(self, name: str) -> float:
        return sum(r.duration for r in self.records if r.name == name)

    def summary(self) -> dict[str, dict]:
        out: dict[str, dict] = {}
        for r in self.records:
            agg = out.setdefault(
                r.name, {"count": 0, "total_s": 0.0, "max_s": 0.0}
            )
            agg["count"] += 1
            agg["total_s"] += r.duration
            agg["max_s"] = max(agg["max_s"], r.duration)
        return out

    def dump_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(
                [
                    {
                        "name": r.name,
                        "start": r.start,
                        "duration": r.duration,
                        **({"meta": r.meta} if r.meta else {}),
                    }
                    for r in self.records
                ],
                f,
            )


_GLOBAL = Tracer()


def span(name: str, **meta):
    """Span on the process-global tracer."""
    return _GLOBAL.span(name, **meta)


def global_tracer() -> Tracer:
    return _GLOBAL


# ---------------------------------------------------------------------------
# Request-scoped traces (PR 5)
# ---------------------------------------------------------------------------

# Process-wide enable switch. Disabled => TraceStore.start returns None
# and request_span/use_trace degrade to no-ops; instrumentation sites
# stay branch-free ("if trace is not None" is the whole protocol).
_ENABLED = True

# Mirror drops into the metrics registry without importing it here
# (utils must stay below server in the layer order; server.metrics sets
# the hook on import). Signature: (kind: "span" | "trace", n: int).
_DROP_HOOK = None


def set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


def enabled() -> bool:
    return _ENABLED


def set_drop_hook(hook) -> None:
    global _DROP_HOOK
    _DROP_HOOK = hook


def _notify_drop(kind: str, n: int) -> None:
    hook = _DROP_HOOK
    if hook is not None and n:
        try:
            hook(kind, n)
        except Exception:  # noqa: BLE001 - metrics must never break tracing
            pass


@dataclass
class Span:
    """One completed span in a trace (times relative to trace start)."""

    span_id: int
    name: str
    start: float  # seconds since the trace began
    duration: float
    parent_id: int
    meta: dict = field(default_factory=dict)


class Trace:
    """One request's bounded span tree; thread-safe.

    Spans carry ids and parent ids; the tree is assembled lazily by
    :meth:`to_dict`. The implicit ROOT span (``root_id``) is the trace
    itself — ``name`` at offset 0, closed by :meth:`finish`. Spans past
    ``max_spans`` are dropped (counted, hook-mirrored); a dropped
    parent's surviving children re-attach to the root at render time.
    """

    def __init__(self, trace_id: str, name: str, max_spans: int, meta=None):
        self.trace_id = trace_id
        self.name = name
        self.meta = dict(meta or {})
        self.max_spans = max_spans
        self.started_at = time.time()  # wall clock, for humans
        self._t0 = time.perf_counter()  # monotonic origin of span offsets
        self.root_id = 0
        self._ids = itertools.count(1)
        self._spans: list[Span] = []
        self._dropped = 0
        self._duration: float | None = None
        self._lock = threading.Lock()

    # -- recording ------------------------------------------------------

    def next_id(self) -> int:
        return next(self._ids)

    def record(
        self,
        span_id: int,
        name: str,
        start_pc: float,
        duration: float,
        parent_id: int,
        meta: dict | None = None,
    ) -> None:
        """Record a completed span; ``start_pc`` is a perf_counter stamp."""
        sp = Span(
            span_id=span_id,
            name=name,
            start=max(0.0, start_pc - self._t0),
            duration=duration,
            parent_id=parent_id,
            meta=dict(meta or {}),
        )
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self._dropped += 1
                _notify_drop("span", 1)
                return
            self._spans.append(sp)

    def add_span(
        self,
        name: str,
        start_pc: float,
        duration: float,
        parent_id: int | None = None,
        **meta,
    ) -> None:
        """Externally-timed span (worker threads that cannot use the
        contextvar protocol); attaches to the root unless parented."""
        self.record(
            self.next_id(),
            name,
            start_pc,
            duration,
            self.root_id if parent_id is None else parent_id,
            meta,
        )

    def finish(self, **meta) -> None:
        """Close the root span (idempotent; first close wins)."""
        with self._lock:
            if self._duration is None:
                self._duration = time.perf_counter() - self._t0
            if meta:
                self.meta.update(meta)

    # -- introspection --------------------------------------------------

    @property
    def duration(self) -> float:
        """Root duration: final after :meth:`finish`, else elapsed."""
        d = self._duration
        return d if d is not None else time.perf_counter() - self._t0

    @property
    def finished(self) -> bool:
        return self._duration is not None

    @property
    def n_spans(self) -> int:
        return len(self._spans)

    @property
    def dropped_spans(self) -> int:
        return self._dropped

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def summary(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "name": self.name,
            "started_at": self.started_at,
            "duration_s": self.duration,
            "finished": self.finished,
            "n_spans": self.n_spans,
            "dropped_spans": self._dropped,
            **({"meta": self.meta} if self.meta else {}),
        }

    def to_dict(self) -> dict:
        """The span TREE: root node (the trace) with nested children."""
        spans = self.spans()
        known = {s.span_id for s in spans}
        children: dict[int, list[Span]] = {}
        for s in sorted(spans, key=lambda s: s.start):
            parent = s.parent_id if s.parent_id in known else self.root_id
            children.setdefault(parent, []).append(s)

        def node(s: Span) -> dict:
            return {
                "name": s.name,
                "start_s": round(s.start, 6),
                "duration_s": round(s.duration, 6),
                **({"meta": s.meta} if s.meta else {}),
                "children": [node(c) for c in children.get(s.span_id, ())],
            }

        return {
            **self.summary(),
            "spans": [node(s) for s in children.get(self.root_id, ())],
        }


class TraceStore:
    """Bounded process-wide ring of request traces (evict-oldest)."""

    def __init__(self, max_traces: int = 256, max_spans: int = 2048):
        # Clamp: a 0/negative trace cap would make the evict-oldest
        # walk popitem() an empty dict on the first start(); "retain
        # ~nothing" is max_traces=1 (use set_enabled(False) / serve
        # --no-trace to turn tracing off entirely).
        self.max_traces = max(1, max_traces)
        self.max_spans = max(0, max_spans)
        self._traces: OrderedDict[str, Trace] = OrderedDict()
        self._evicted = 0
        self._lock = threading.Lock()

    def configure(
        self, max_traces: int | None = None, max_spans: int | None = None
    ) -> None:
        """Adjust the bounds (serve CLI knobs); applies to new traces,
        and an over-full ring sheds down to the new cap immediately."""
        with self._lock:
            if max_traces is not None:
                self.max_traces = max(1, max_traces)
            if max_spans is not None:
                self.max_spans = max(0, max_spans)
            while len(self._traces) > self.max_traces:
                self._traces.popitem(last=False)
                self._evicted += 1
                _notify_drop("trace", 1)

    def start(
        self, name: str, trace_id: str | None = None, **meta
    ) -> Trace | None:
        """Open (and retain) a new trace; ``None`` when tracing is off.

        ``trace_id`` ADOPTS a propagated id instead of minting one
        (PR 20): a gateway receiving a forwarded request under
        ``X-Trace-Id`` opens its local trace under the FRONT's id, so
        the hop's spans join the originating request's trace when the
        fleet view merges them. Adoption is per process — each process
        keeps its own Trace object (its own clock origin and span
        ring); the shared id is the join key, never shared state. An
        invalid propagated id (non-hex, wrong length) is ignored and a
        fresh id minted — a malicious or corrupt header must not poison
        the store's keying."""
        if not _ENABLED:
            return None
        if trace_id is not None and not _adoptable_id(trace_id):
            trace_id = None
        trace = Trace(
            trace_id or uuid.uuid4().hex[:16],
            name,
            max_spans=self.max_spans,
            meta={**meta, **({"adopted": True} if trace_id else {})},
        )
        with self._lock:
            while len(self._traces) >= self.max_traces:
                self._traces.popitem(last=False)
                self._evicted += 1
                _notify_drop("trace", 1)
            self._traces[trace.trace_id] = trace
        return trace

    def get(self, trace_id: str) -> Trace | None:
        with self._lock:
            return self._traces.get(trace_id)

    def discard(self, trace_id: str) -> None:
        """Intentionally forget a trace (e.g. a request shed at the
        admission door did no work worth retaining — under a 429 storm
        these would otherwise churn the ring and evict the slow traces
        being debugged). Not counted as a drop."""
        with self._lock:
            self._traces.pop(trace_id, None)

    def traces(self, limit: int = 50) -> list[Trace]:
        """Newest-first."""
        with self._lock:
            items = list(self._traces.values())
        return items[::-1][: max(0, limit)]

    @property
    def evicted(self) -> int:
        return self._evicted

    def __len__(self) -> int:
        return len(self._traces)

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()


def _adoptable_id(trace_id: str) -> bool:
    """A propagated trace id this store will adopt verbatim: 8-64
    hex-ish chars (the local mint is 16 lowercase hex). Bounded and
    charset-checked so a hostile ``X-Trace-Id`` header cannot stuff
    megabyte keys or control bytes into the store."""
    if not isinstance(trace_id, str) or not (8 <= len(trace_id) <= 64):
        return False
    return all(c in "0123456789abcdefABCDEF-" for c in trace_id)


_STORE = TraceStore()


def trace_store() -> TraceStore:
    return _STORE


# Current (trace, span-id) of this context: tasks inherit it across
# awaits, threads started via asyncio.to_thread inherit a copy, and
# plain worker threads see None (they attach via Trace.add_span).
_CTX: ContextVar[tuple[Trace, int] | None] = ContextVar(
    "llm_consensus_trace", default=None
)


def current_trace() -> Trace | None:
    ctx = _CTX.get()
    return ctx[0] if ctx is not None else None


def trace_id_of(trace: Trace | None) -> str | None:
    """The id of a trace-or-None handle — the stamp every flight
    recorder event and request summary carries (PR 10), so the span
    tree at ``/debug/traces?id=``, the timeline at ``/debug/flight``,
    and the summary at ``/debug/requests?id=`` all join on one key.
    None-safe because every handle in the serving stack is None when
    tracing is disabled."""
    return trace.trace_id if trace is not None else None


@contextlib.contextmanager
def use_trace(trace: Trace | None):
    """Make ``trace`` the context's current trace (no-op for None)."""
    if trace is None:
        yield
        return
    token = _CTX.set((trace, trace.root_id))
    try:
        yield
    finally:
        _CTX.reset(token)


@contextlib.contextmanager
def request_span(name: str, **meta):
    """Span on the context's current trace, nested under the context's
    current span; a silent no-op when no trace is active (library code
    can instrument unconditionally)."""
    ctx = _CTX.get()
    if ctx is None or not _ENABLED:
        yield None
        return
    trace, parent = ctx
    span_id = trace.next_id()
    token = _CTX.set((trace, span_id))
    t0 = time.perf_counter()
    try:
        yield trace
    finally:
        _CTX.reset(token)
        trace.record(
            span_id, name, t0, time.perf_counter() - t0, parent, meta
        )


@contextlib.contextmanager
def trace_jax_profile(logdir: str):
    """Capture a JAX/XLA device profile (TensorBoard format) around a
    block — the real profiling story for the TPU hot loop."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
