"""Span tracing + JAX profiler hooks.

Tracing is ~absent in the reference (wall-clock only paces the readiness
poll, ``src/main.rs:449-454``; SURVEY.md §5). Here:

- :class:`Tracer` / :func:`span` — lightweight wall-clock spans recorded
  as structured events (name, start, duration, metadata), queryable and
  dumpable to JSON; protocol phases (propose/evaluate/refine) and engine
  phases (prefill/decode) report through this.
- :func:`trace_jax_profile` — context manager around
  ``jax.profiler.trace`` producing a TensorBoard-loadable device trace
  for the real TPU hot loop.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from dataclasses import dataclass, field


@dataclass
class SpanRecord:
    name: str
    start: float
    duration: float
    meta: dict = field(default_factory=dict)


class Tracer:
    """Collects timed spans; thread-safe (backend calls run in threads)."""

    def __init__(self) -> None:
        self._records: list[SpanRecord] = []
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def span(self, name: str, **meta):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - t0
            with self._lock:
                self._records.append(
                    SpanRecord(name=name, start=t0, duration=dur, meta=meta)
                )

    @property
    def records(self) -> list[SpanRecord]:
        with self._lock:
            return list(self._records)

    def total(self, name: str) -> float:
        return sum(r.duration for r in self.records if r.name == name)

    def summary(self) -> dict[str, dict]:
        out: dict[str, dict] = {}
        for r in self.records:
            agg = out.setdefault(
                r.name, {"count": 0, "total_s": 0.0, "max_s": 0.0}
            )
            agg["count"] += 1
            agg["total_s"] += r.duration
            agg["max_s"] = max(agg["max_s"], r.duration)
        return out

    def dump_json(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(
                [
                    {
                        "name": r.name,
                        "start": r.start,
                        "duration": r.duration,
                        **({"meta": r.meta} if r.meta else {}),
                    }
                    for r in self.records
                ],
                f,
            )


_GLOBAL = Tracer()


def span(name: str, **meta):
    """Span on the process-global tracer."""
    return _GLOBAL.span(name, **meta)


def global_tracer() -> Tracer:
    return _GLOBAL


@contextlib.contextmanager
def trace_jax_profile(logdir: str):
    """Capture a JAX/XLA device profile (TensorBoard format) around a
    block — the real profiling story for the TPU hot loop."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
