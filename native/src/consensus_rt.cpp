// consensus_rt: native runtime for the TPU consensus framework.
//
// The reference's only native code is its Rust application itself (one
// actix binary; SURVEY.md §2 — no CUDA/C++ compute). This library is the
// rebuild's host-side runtime: the pieces around the XLA device programs
// that want real threads and no GIL —
//
//   1. batch tokenizer  — byte-level encode/decode (id = byte + 3, ids
//      0/1/2 = pad/bos/eos, mirroring engine/tokenizer.py) over request
//      batches, one pass, no Python loop;
//   2. request ring     — bounded MPMC queue for the serving scheduler
//      (REPL/eval producers -> device-batch consumer), condvar-based;
//   3. token data loader — mmap'd int32 token shards + background
//      prefetch thread producing fixed-shape [B, S] training batches.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------------------
// 1. Batch byte tokenizer (ids: 0=pad, 1=bos, 2=eos, byte b -> b+3)
// ---------------------------------------------------------------------------

// Encode n texts into a right-padded [n, max_len] int32 buffer.
// Over-long texts keep their TAIL (same left-truncation the engine does).
// lengths[i] receives the true (post-truncation) token count.
// Returns 0 on success.
int rt_byte_encode_batch(const char** texts, const int64_t* text_lens,
                         int32_t n, int32_t* out, int32_t max_len,
                         int32_t* lengths, int32_t add_bos) {
  if (n < 0 || max_len <= 0) return -1;
  for (int32_t i = 0; i < n; ++i) {
    const unsigned char* t =
        reinterpret_cast<const unsigned char*>(texts[i]);
    int64_t tl = text_lens[i];
    int32_t* row = out + static_cast<int64_t>(i) * max_len;
    int64_t total = tl + (add_bos ? 1 : 0);
    int64_t skip = total > max_len ? total - max_len : 0;  // drop head
    int32_t w = 0;
    if (add_bos && skip == 0) row[w++] = 1;  // bos survives only untruncated
    // Bytes to skip from the text head:
    int64_t byte_skip = skip > 0 ? skip - (add_bos ? 1 : 0) : 0;
    for (int64_t j = byte_skip; j < tl && w < max_len; ++j)
      row[w++] = static_cast<int32_t>(t[j]) + 3;
    lengths[i] = w;
    for (int32_t j = w; j < max_len; ++j) row[j] = 0;  // pad
  }
  return 0;
}

// Decode one id row (stops at eos or len); writes at most cap bytes.
// Returns number of bytes written, or -1 on error.
int64_t rt_byte_decode(const int32_t* ids, int64_t len, char* out,
                       int64_t cap) {
  int64_t w = 0;
  for (int64_t i = 0; i < len; ++i) {
    int32_t id = ids[i];
    if (id == 2) break;             // eos
    if (id < 3 || id > 258) continue;  // pad/bos/out-of-range
    if (w >= cap) return -1;
    out[w++] = static_cast<char>(id - 3);
  }
  return w;
}

// ---------------------------------------------------------------------------
// 2. Bounded MPMC request ring (serving scheduler queue)
// ---------------------------------------------------------------------------

struct RtRing {
  explicit RtRing(int64_t cap) : capacity(cap), closed(false) {}
  int64_t capacity;
  std::deque<std::vector<uint8_t>> items;
  std::mutex mu;
  std::condition_variable not_empty;
  std::condition_variable not_full;
  bool closed;
};

void* rt_ring_create(int64_t capacity) {
  if (capacity <= 0) return nullptr;
  return new RtRing(capacity);
}

void rt_ring_destroy(void* h) { delete static_cast<RtRing*>(h); }

// Push a payload; blocks while full unless timeout_ms >= 0 expires.
// Returns 0 ok, 1 timeout, 2 closed.
int rt_ring_push(void* h, const uint8_t* data, int64_t len,
                 int64_t timeout_ms) {
  auto* r = static_cast<RtRing*>(h);
  std::unique_lock<std::mutex> lk(r->mu);
  auto pred = [&] {
    return r->closed || (int64_t)r->items.size() < r->capacity;
  };
  if (timeout_ms < 0) {
    r->not_full.wait(lk, pred);
  } else if (!r->not_full.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                   pred)) {
    return 1;
  }
  if (r->closed) return 2;
  r->items.emplace_back(data, data + len);
  r->not_empty.notify_one();
  return 0;
}

// Pop into out (cap bytes). On success stores size into *len and returns 0;
// 1 timeout, 2 closed-and-empty, 3 payload larger than cap.
int rt_ring_pop(void* h, uint8_t* out, int64_t cap, int64_t* len,
                int64_t timeout_ms) {
  auto* r = static_cast<RtRing*>(h);
  std::unique_lock<std::mutex> lk(r->mu);
  auto pred = [&] { return r->closed || !r->items.empty(); };
  if (timeout_ms < 0) {
    r->not_empty.wait(lk, pred);
  } else if (!r->not_empty.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                    pred)) {
    return 1;
  }
  if (r->items.empty()) return 2;  // closed and drained
  auto& front = r->items.front();
  if ((int64_t)front.size() > cap) return 3;
  *len = (int64_t)front.size();
  std::memcpy(out, front.data(), front.size());
  r->items.pop_front();
  r->not_full.notify_one();
  return 0;
}

int64_t rt_ring_size(void* h) {
  auto* r = static_cast<RtRing*>(h);
  std::lock_guard<std::mutex> lk(r->mu);
  return (int64_t)r->items.size();
}

void rt_ring_close(void* h) {
  auto* r = static_cast<RtRing*>(h);
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->closed = true;
  }
  r->not_empty.notify_all();
  r->not_full.notify_all();
}

// ---------------------------------------------------------------------------
// 3. mmap token data loader with prefetch thread
// ---------------------------------------------------------------------------

struct RtLoader {
  int fd = -1;
  const int32_t* tokens = nullptr;  // mmap'd
  int64_t n_tokens = 0;
  int64_t batch = 0, seq = 0;
  std::mt19937_64 rng;
  // Prefetch ring of sampled window-start batches. Data is NOT copied
  // here: the worker samples starts and madvise(WILLNEED)s the windows
  // so pages fault in ahead of use; rt_loader_next does the single
  // copy into the caller's buffer, and rt_loader_skip discards starts
  // without ever touching token data.
  std::deque<std::vector<int64_t>> ready;
  std::mutex mu;
  std::condition_variable cv_ready, cv_space;
  int64_t prefetch_depth = 4;
  std::thread worker;
  std::atomic<bool> stop{false};

  std::vector<int64_t> sample_starts() {
    // Random contiguous windows — the standard LM pretraining sampler.
    std::uniform_int_distribution<int64_t> dist(0, n_tokens - seq - 1);
    std::vector<int64_t> starts(batch);
    for (int64_t b = 0; b < batch; ++b) starts[b] = dist(rng);
    return starts;
  }

  void prefault(const std::vector<int64_t>& starts) {
    long page = sysconf(_SC_PAGESIZE);
    for (int64_t s : starts) {
      auto addr = reinterpret_cast<uintptr_t>(tokens + s);
      auto base = addr & ~(uintptr_t)(page - 1);
      size_t len = (addr - base) + (size_t)seq * sizeof(int32_t);
      madvise(reinterpret_cast<void*>(base), len, MADV_WILLNEED);
    }
  }

  void run() {
    while (!stop.load()) {
      auto starts = sample_starts();
      prefault(starts);
      std::unique_lock<std::mutex> lk(mu);
      cv_space.wait(lk, [&] {
        return stop.load() || (int64_t)ready.size() < prefetch_depth;
      });
      if (stop.load()) return;
      ready.emplace_back(std::move(starts));
      cv_ready.notify_one();
    }
  }
};

void* rt_loader_create(const char* path, int64_t batch, int64_t seq,
                       uint64_t seed) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < (off_t)(sizeof(int32_t) * (seq + 1))) {
    close(fd);
    return nullptr;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (map == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  auto* l = new RtLoader();
  l->fd = fd;
  l->tokens = static_cast<const int32_t*>(map);
  l->n_tokens = st.st_size / (int64_t)sizeof(int32_t);
  l->batch = batch;
  l->seq = seq;
  l->rng.seed(seed);
  l->worker = std::thread([l] { l->run(); });
  return l;
}

// Blocks until a [batch, seq] start-set is ready; copies the windows
// into out (the only data copy in the pipeline).
int rt_loader_next(void* h, int32_t* out) {
  auto* l = static_cast<RtLoader*>(h);
  std::unique_lock<std::mutex> lk(l->mu);
  l->cv_ready.wait(lk, [&] { return l->stop.load() || !l->ready.empty(); });
  if (l->ready.empty()) return 1;
  auto starts = std::move(l->ready.front());
  l->ready.pop_front();
  l->cv_space.notify_one();
  lk.unlock();
  for (int64_t b = 0; b < l->batch; ++b)
    std::memcpy(out + b * l->seq, l->tokens + starts[b],
                sizeof(int32_t) * l->seq);
  return 0;
}

// Discard the next n batches (checkpoint-resume fast-forward). The
// stream stays identical to n rt_loader_next calls, and since the ring
// holds window starts — not data — no token bytes are touched.
int rt_loader_skip(void* h, int64_t n) {
  auto* l = static_cast<RtLoader*>(h);
  for (int64_t i = 0; i < n; ++i) {
    std::unique_lock<std::mutex> lk(l->mu);
    l->cv_ready.wait(lk, [&] { return l->stop.load() || !l->ready.empty(); });
    if (l->ready.empty()) return 1;
    l->ready.pop_front();
    l->cv_space.notify_one();
  }
  return 0;
}

void rt_loader_destroy(void* h) {
  auto* l = static_cast<RtLoader*>(h);
  l->stop.store(true);
  l->cv_space.notify_all();
  l->cv_ready.notify_all();
  if (l->worker.joinable()) l->worker.join();
  if (l->tokens)
    munmap(const_cast<int32_t*>(l->tokens),
           (size_t)l->n_tokens * sizeof(int32_t));
  if (l->fd >= 0) close(l->fd);
  delete l;
}

int64_t rt_loader_n_tokens(void* h) {
  return static_cast<RtLoader*>(h)->n_tokens;
}

const char* rt_version() { return "consensus_rt 0.1"; }

}  // extern "C"
