#!/usr/bin/env python
"""Bench-history trajectory + regression gate (PR 10).

Aggregates the per-round chip artifacts the driver commits at the repo
root — ``BENCH_r*.json`` (the headline candidate-tokens/sec/chip leg)
and ``MULTICHIP_r*.json`` (the 8-device dryrun) — into one trajectory
table and a regression VERDICT, enforced in CI next to
``scripts/check_metrics.py``.

The one rule that must never regress: a **CHIP UNREACHABLE round is
no-data, never a 0-tok/s measurement**. Rounds 4 and 5 committed
``{"value": 0.0, "unit": "tokens/sec/chip"}`` rows for a dead tunnel
(rc != 0) — naive tooling averaging or min-ing those would report a
catastrophic regression that never happened, and tooling keying
regressions off "latest value" would fire on every outage. A round
counts as a measurement only when its subprocess rc is 0 AND its
parsed payload says so (the ``status`` field bench.py now emits;
legacy rows without one fall back to the rc / metric-string / zero-
value heuristics this script centralizes).

Verdict semantics (``--check`` exits 1 only on REGRESSION):

- no measured rounds at all -> ``no-data`` (exit 0)
- the newest measured round >= threshold * best earlier measured
  round -> ``ok``
- below the threshold (default 0.85 — chip rounds jitter run to run;
  see the r3/r4 llama-1b medians in README) -> ``regression``
- measured rounds exist but the LATEST round is no-data -> ``stale``
  (exit 0: an outage must not block CI, the trajectory just flags it)
- the ratio compares SAME-UNIT rounds only: a round whose artifact is
  one of the serving A/B legs' payloads (``tokens/sec`` — e.g. PR 12's
  ``--serve-decode-rounds``) is never ratioed against the headline
  ``tokens/sec/chip`` rows; a unit change starts a fresh trajectory.

Stdlib-only, < 1 s, runs anywhere (no jax import).
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def _round_no(path: Path) -> int:
    m = re.search(r"_r(\d+)\.json$", path.name)
    return int(m.group(1)) if m else -1


def load_bench_round(path: Path) -> dict:
    """One BENCH_r*.json -> {round, status, value?, unit?, metric?}.

    ``status``: "ok" (a real measurement), "chip-unreachable" (the
    explicit no-data record), or "no-data" (rc != 0, unparseable, or a
    legacy zero-value unreachable row without a status field).
    """
    rnd = _round_no(path)
    try:
        doc = json.loads(path.read_text())
    except (OSError, ValueError) as e:
        return {"round": rnd, "status": "no-data", "note": f"unreadable: {e}"}
    parsed = doc.get("parsed")
    rc = doc.get("rc")
    # The artifact may be the raw bench emission itself (bench.py
    # --out) rather than the driver's wrapper.
    if parsed is None and "metric" in doc:
        parsed, rc = doc, 0
    if parsed is None or not isinstance(parsed, dict):
        return {
            "round": rnd,
            "status": "no-data",
            "note": f"rc={rc}, no parsed payload",
        }
    # A malformed value (string, list, ...) is an artifact-format
    # problem — by this module's contract that is no-data, never a
    # gate-crashing traceback.
    try:
        value = float(parsed.get("value") or 0.0)
    except (TypeError, ValueError):
        return {
            "round": rnd,
            "status": "no-data",
            "note": f"malformed value {parsed.get('value')!r}",
        }
    status = parsed.get("status")
    if status is None:
        # Legacy rows (pre-PR-10 bench.py): infer. rc != 0 or an
        # explicit CHIP UNREACHABLE metric string is the outage
        # record; so is a 0.0 tokens/sec/chip value (a chip that
        # answered cannot measure 0).
        metric = str(parsed.get("metric", ""))
        if "CHIP UNREACHABLE" in metric:
            status = "chip-unreachable"
        elif rc not in (0, None):
            status = "no-data"
        elif not value:
            status = "no-data"
        else:
            status = "ok"
    elif status == "ok" and rc not in (0, None):
        # A payload claiming ok under a failing subprocess is still
        # not a measurement (partial leg, killed mid-run).
        status = "no-data"
    out = {"round": rnd, "status": status}
    if status == "ok":
        out["value"] = value
        out["unit"] = parsed.get("unit", "")
        out["metric"] = str(parsed.get("metric", ""))[:100]
    return out


def load_multichip_round(path: Path) -> dict:
    rnd = _round_no(path)
    try:
        doc = json.loads(path.read_text())
    except (OSError, ValueError) as e:
        return {"round": rnd, "status": "no-data", "note": f"unreadable: {e}"}
    if doc.get("skipped"):
        return {"round": rnd, "status": "skipped"}
    ok = bool(doc.get("ok")) and doc.get("rc") == 0
    return {"round": rnd, "status": "ok" if ok else "no-data"}


def history(root: Path) -> dict:
    bench = [
        load_bench_round(p)
        for p in sorted(root.glob("BENCH_r*.json"), key=_round_no)
    ]
    multi = [
        load_multichip_round(p)
        for p in sorted(root.glob("MULTICHIP_r*.json"), key=_round_no)
    ]
    return {"bench": bench, "multichip": multi}


def verdict(bench: list[dict], threshold: float) -> dict:
    measured = [r for r in bench if r["status"] == "ok"]
    if not measured:
        return {
            "verdict": "no-data",
            "detail": "no measured bench rounds (outage rounds are "
            "no-data, never 0-tok/s measurements)",
        }
    latest = measured[-1]
    # Same-unit comparison only: the serving A/B legs (PR 12's
    # --serve-decode-rounds and friends, PR 15's --serve-adaptive —
    # every such leg MUST tag its payload with "unit") emit
    # "tokens/sec" payloads a
    # driver may commit as a round artifact next to the headline
    # "tokens/sec/chip" rows — ratioing across units would fire (or
    # mask) regressions that never happened. A unit CHANGE therefore
    # starts a fresh trajectory, like the r4 re-baseline did.
    earlier = [
        r
        for r in measured[:-1]
        if r.get("unit", "") == latest.get("unit", "")
    ]
    doc = {
        "latest_measured_round": latest["round"],
        "latest_value": latest["value"],
        "unit": latest.get("unit", ""),
    }
    if bench and bench[-1]["status"] != "ok":
        # Outage tail: nothing new to gate — flag staleness, pass CI.
        return {
            **doc,
            "verdict": "stale",
            "detail": f"round {bench[-1]['round']} is "
            f"{bench[-1]['status']}; last measurement is round "
            f"{latest['round']} ({latest['value']:.1f})",
        }
    if not earlier:
        return {**doc, "verdict": "ok", "detail": "first measured round"}
    best = max(earlier, key=lambda r: r["value"])
    ratio = latest["value"] / best["value"] if best["value"] else 1.0
    doc.update(
        best_earlier_round=best["round"],
        best_earlier_value=best["value"],
        ratio=round(ratio, 4),
        threshold=threshold,
    )
    if ratio < threshold:
        return {
            **doc,
            "verdict": "regression",
            "detail": f"round {latest['round']} measured "
            f"{latest['value']:.1f} vs best earlier "
            f"{best['value']:.1f} (r{best['round']:02d}): ratio "
            f"{ratio:.3f} < {threshold}",
        }
    return {**doc, "verdict": "ok", "detail": f"ratio {ratio:.3f}"}


def main(argv: list[str]) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "--dir", default=str(ROOT), help="directory holding the artifacts"
    )
    p.add_argument(
        "--threshold",
        type=float,
        default=0.85,
        help="regression floor: latest measured value must stay above "
        "threshold * best earlier measured value (chip rounds jitter "
        "run to run — see the r3/r4 medians)",
    )
    p.add_argument(
        "--check",
        action="store_true",
        help="exit 1 on a regression verdict (CI mode; no-data and "
        "stale pass — an outage must not block the gate)",
    )
    p.add_argument(
        "--json", action="store_true", help="emit the table as JSON"
    )
    args = p.parse_args(argv)
    h = history(Path(args.dir))
    v = verdict(h["bench"], args.threshold)
    if args.json:
        print(json.dumps({**h, "verdict": v}, indent=2))
    else:
        print("round  bench                          multichip")
        multi_by_round = {m["round"]: m for m in h["multichip"]}
        for r in h["bench"]:
            if r["status"] == "ok":
                cell = f"{r['value']:>10.1f} {r.get('unit', '')}"
            else:
                cell = f"{'—':>10} ({r['status']})"
            m = multi_by_round.get(r["round"])
            mcell = m["status"] if m else "—"
            print(f"r{r['round']:02d}   {cell:<30} {mcell}")
        for m in h["multichip"]:
            if m["round"] not in {r["round"] for r in h["bench"]}:
                print(f"r{m['round']:02d}   {'—':>10} {'':<19} {m['status']}")
        print(f"verdict: {v['verdict']} — {v['detail']}")
    if args.check and v["verdict"] == "regression":
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
