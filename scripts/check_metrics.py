#!/usr/bin/env python
"""Metrics-drift gate (PR 5): the canonical surface must stay canonical.

Three invariants, enforced in CI (scripts/ci_tier1.sh) and by a tier-1
test:

1. Every metric family name REFERENCED by the serving stack
   (continuous batcher, batch scheduler, offload tier, gateway,
   admission, coordinator, bench) — i.e. every string literal passed to
   ``.counter( / .gauge( / .histogram( / .get(`` — must be DECLARED in
   ``llm_consensus_tpu/server/metrics.py`` (module-level family or the
   ``INSTANCE_FAMILIES`` manifest for per-instance-registry families).
2. Every declared family must appear (backticked) in the README's
   "### Observability" table.
3. Nothing in the README observability table claims a family that no
   longer exists.

Imports only ``llm_consensus_tpu.server.metrics`` (stdlib-only by
contract) — never jax — so this runs anywhere in < 1 s.

``--table`` prints the markdown rows for the README table (name, kind,
help) to regenerate it after adding a family.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

# Files whose metric references must resolve to declared families.
SCANNED = (
    "llm_consensus_tpu/serving/continuous.py",
    "llm_consensus_tpu/serving/scheduler.py",
    "llm_consensus_tpu/serving/offload.py",
    "llm_consensus_tpu/serving/flight.py",
    "llm_consensus_tpu/serving/fleet.py",
    "llm_consensus_tpu/serving/fleet_control.py",
    "llm_consensus_tpu/serving/control.py",
    "llm_consensus_tpu/serving/disagg.py",
    "llm_consensus_tpu/serving/remote_store.py",
    "llm_consensus_tpu/serving/modelset.py",
    "llm_consensus_tpu/serving/vocab_align.py",
    "llm_consensus_tpu/server/gateway.py",
    "llm_consensus_tpu/server/admission.py",
    "llm_consensus_tpu/consensus/coordinator.py",
    "bench.py",
)

# A family registration with a literal name — reg.counter("name", ...)
# / _REG.histogram(\n    "name", ...) — or a registry lookup; the .get
# pattern is anchored to registry-shaped receivers so plain dict .get
# calls don't count.
_REF = re.compile(
    r"\.(?:counter|gauge|histogram)\(\s*[\"']([A-Za-z_][A-Za-z0-9_]*)[\"']"
)
_REF_GET = re.compile(
    r"[A-Za-z_]*(?:REG(?:ISTRY)?|[Rr]egistry)\.get\("
    r"\s*[\"']([A-Za-z_][A-Za-z0-9_]*)[\"']"
)
_BACKTICKED = re.compile(r"`([A-Za-z_][A-Za-z0-9_]*)`")


def declared_families() -> dict[str, tuple[str, str]]:
    """name -> (kind, help) for every canonical family."""
    from llm_consensus_tpu.server import metrics as M

    out: dict[str, tuple[str, str]] = {}
    for name, fam in M.REGISTRY._families.items():
        out[name] = (fam.kind, fam.help)
    for name, kind in M.INSTANCE_FAMILIES.items():
        out.setdefault(name, (kind, "(per-instance registry family)"))
    return out


def referenced_names() -> dict[str, list[str]]:
    """name -> [files referencing it]."""
    refs: dict[str, list[str]] = {}
    for rel in SCANNED:
        text = (ROOT / rel).read_text()
        for name in _REF.findall(text) + _REF_GET.findall(text):
            refs.setdefault(name, []).append(rel)
    return refs


def readme_table_names(readme: Path) -> set[str]:
    text = readme.read_text()
    m = re.search(
        r"^### Observability$(.*?)(?=^#{1,3} )", text, re.M | re.S
    )
    if not m:
        return set()
    return set(_BACKTICKED.findall(m.group(1)))


def main(argv: list[str]) -> int:
    declared = declared_families()
    if "--table" in argv:
        for name in sorted(declared):
            kind, help_ = declared[name]
            print(f"| `{name}` | {kind} | {help_} |")
        return 0
    refs = referenced_names()
    readme = readme_table_names(ROOT / "README.md")
    failures: list[str] = []
    for name, files in sorted(refs.items()):
        if name not in declared:
            failures.append(
                f"referenced but not declared in server/metrics.py: "
                f"{name!r} (from {', '.join(sorted(set(files)))})"
            )
    if not readme:
        failures.append(
            "README.md has no '### Observability' section (or it is "
            "empty) — the metrics table must live there"
        )
    for name in sorted(declared):
        if name not in readme:
            failures.append(
                f"declared but missing from the README observability "
                f"table: {name!r}"
            )
    for name in sorted(readme - set(declared)):
        # Only flag things that LOOK like metric families: the section
        # also backticks endpoints, config knobs, and module paths.
        if re.search(
            r"_(total|seconds|bytes|size|depth|inflight|rounds|"
            r"occupancy|waiting|slots|second)$",
            name,
        ):
            failures.append(
                f"README observability table names an undeclared "
                f"family: {name!r}"
            )
    if failures:
        print("METRICS DRIFT:", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        print(
            f"\n{len(failures)} problem(s). Declare families in "
            "llm_consensus_tpu/server/metrics.py (module-level or "
            "INSTANCE_FAMILIES) and document them in README "
            "'### Observability' (scripts/check_metrics.py --table "
            "prints the rows).",
            file=sys.stderr,
        )
        return 1
    print(
        f"metrics surface consistent: {len(declared)} declared, "
        f"{len(refs)} referenced, {len(readme)} documented tokens"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
