#!/bin/bash
# Probe the tunnel chip every 5 min; log status. (Round-4 pattern: the
# chip can go unresponsive for hours; queue legs block until it heals.)
cd /root/repo || exit 1
while true; do
  ts=$(date -u +%H:%M:%S)
  out=$(timeout 90 python -c "
import jax, numpy as np, jax.numpy as jnp
d = jax.devices()[0]
x = jnp.full((8,8), 2.0)
v = float(np.asarray(x @ x)[0,0])
print(f'ok {d.platform} {v}')
" 2>/dev/null | tail -1)
  echo "$ts ${out:-TIMEOUT(90s)}" >> runs/chip_watchdog.log
  sleep 300
done
