#!/bin/bash
# Probe the tunnel chip every 5 min; log status. (Round-4 pattern: the
# chip can go unresponsive for hours; queue legs block until it heals.)
# Probe body = bench._PROBE_SRC, the ONE time-salted copy: the tunnel
# replays previously-seen (executable, inputs) pairs across processes,
# so a fixed-operand matmul can "pass" from the replay cache with the
# chip dead. The salt makes every attempt's inputs fresh.
cd /root/repo || exit 1
while true; do
  ts=$(date -u +%H:%M:%S)
  out=$(timeout 90 python -c "
import bench, jax
exec(bench._PROBE_SRC)
print(f'ok {jax.devices()[0].platform}')
" 2>/dev/null | tail -1)
  echo "$ts ${out:-TIMEOUT(90s)}" >> runs/chip_watchdog.log
  sleep 300
done
