#!/bin/bash
# Tier-1 verify gate — the ONE entry point for local and automated runs.
# Wraps the ROADMAP.md "Tier-1 verify" command verbatim (CPU, -m 'not
# slow'); keep the two in sync by editing ROADMAP.md first. Exit code is
# pytest's; DOTS_PASSED echoes the per-test pass count the growth driver
# compares against the seed.
#
#   --smoke   fast paged-serving slice (~2 min) for iterating on the
#             continuous batcher / page-table / shared-prefix-attention
#             stack without the full ~15 min suite.
cd "$(dirname "$0")/.." || exit 1
# Metrics-drift gate (PR 5): every family the serving stack references
# must be declared in server/metrics.py and documented in the README
# observability table. Stdlib-only, < 1 s.
python scripts/check_metrics.py || exit 1
# Bench-history gate (PR 10): the chip-round trajectory's regression
# verdict — CHIP UNREACHABLE rounds count as no-data, never as 0-tok/s
# measurements. Stdlib-only, < 1 s.
python scripts/bench_history.py --check || exit 1
if [ "$1" = "--smoke" ]; then
  exec env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_paged_cache.py tests/test_server.py \
    tests/test_shared_prefix_attention.py tests/test_kv_offload.py \
    tests/test_tracing.py tests/test_decode_pipeline.py \
    tests/test_ragged_attention.py tests/test_serve_speculative.py \
    tests/test_flight.py tests/test_decode_rounds.py \
    tests/test_mesh_serving.py tests/test_replica_fleet.py \
    tests/test_adaptive_control.py tests/test_disagg.py \
    tests/test_kv_transfer.py tests/test_multi_model.py \
    tests/test_fleet_control.py tests/test_fleet_observability.py \
    -q -p no:cacheprovider -p no:xdist -p no:randomly
fi
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 3900 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
