#!/bin/bash
# Round-5 measurement program, outage-resilient version: wait for the
# chip to answer probes, then run every leg whose artifact is missing.
# Idempotent — safe to re-run after another outage. One job at a time
# (chip and the 1-core host are both contended).
cd /root/repo || exit 1
mkdir -p runs/reports
exec >> runs/r5_recovery.log 2>&1

probe() {
  # The ONE probe body bench.py owns (bench._PROBE_SRC): its operand is
  # time-salted per attempt, because the tunnel replays previously-seen
  # (executable, inputs) pairs across processes — a fixed-operand probe
  # can "pass" straight from the replay cache with the chip dead.
  timeout 90 python -c 'import bench; exec(bench._PROBE_SRC)' \
    >/dev/null 2>&1
}

wait_chip() {
  ok=0
  while [ "$ok" -lt 2 ]; do
    if probe; then ok=$((ok + 1)); else ok=0; fi
    sleep 45
  done
  echo "chip healthy at $(date -u +%H:%M:%S)"
}

leg() {  # leg <artifact> <cmd...>
  art=$1; shift
  # An artifact recording an unreachable chip is a FAILED measurement
  # left by an earlier pass — drop it so this pass retries instead of
  # SKIPping past the enshrined 0.0 record (the round-4 failure mode).
  if [ -e "$art" ] && grep -q 'CHIP UNREACHABLE' "$art"; then
    echo "LEG $art: stale CHIP UNREACHABLE artifact — removing to retry"
    rm -f "$art"
  fi
  [ -s "$art" ] && { echo "SKIP (have $art)"; return 0; }
  wait_chip
  echo "LEG $art: $* [$(date -u +%H:%M:%S)]"
  "$@"
  rc=$?
  # A failed measurement is NOT done: drop the artifact when the leg's
  # real exit code is nonzero or the artifact records an unreachable
  # chip, so the next pass retries the leg instead of SKIPping past an
  # enshrined 0.0 record (the round-4 failure mode).
  if [ "$rc" -ne 0 ] || { [ -e "$art" ] && grep -q 'CHIP UNREACHABLE' "$art"; }; then
    echo "LEG $art FAILED rc=$rc — removing artifact so a re-run retries"
    rm -f "$art"
  fi
  echo "LEG $art done rc=$rc [$(date -u +%H:%M:%S)]"
}

date -u

# Q0: (fresh-container case) re-train the arith-14m maturities if the
# checkpoints were wiped with runs/. ~210-275 s each on chip.
for spec in "runs/arith14m_mid 1500" "runs/arith14m_mid2 2500" \
            "runs/arith14m 6000"; do
  set -- $spec
  if [ ! -e "$1/DONE" ] && [ ! -d "$1/LATEST" ]; then
    wait_chip
    python examples/train_arith_em.py --steps "$2" --ckpt-dir "$1" \
      --train-only && touch "$1/DONE"
  fi
done

# Q1: arith-14m on-chip EM at the full N set.
leg runs/reports/arith14m_em_r5.json \
  python examples/train_arith_em.py --eval-only --ckpt-dir runs/arith14m \
    --ns 1 4 8 32 64 --report runs/reports/arith14m_em_r5.json

# Q2: draft training (idempotent via checkpoint marker) + spec demo.
if [ ! -e runs/arith3m/DONE ]; then
  wait_chip
  python examples/train_arith_em.py --model arith-3m --steps 6000 \
    --ckpt-dir runs/arith3m --train-only && touch runs/arith3m/DONE
fi
leg runs/reports/spec_trained_r5.json bash -c \
  'python examples/spec_arith_demo.py --target-ckpt runs/arith14m \
     --draft-ckpt runs/arith3m > runs/reports/spec_trained_r5.json.tmp \
   && mv runs/reports/spec_trained_r5.json.tmp \
         runs/reports/spec_trained_r5.json'

# Q3: arith2 hard-corpus training + 200-problem EM at natural temp.
if [ ! -e runs/arith25m/DONE ]; then
  wait_chip
  python examples/train_arith_em.py --task arith2 --n-problems 200 \
    --ckpt-dir runs/arith25m --train-only && touch runs/arith25m/DONE
fi
leg runs/reports/arith25m_em_arith2_r5.json \
  python examples/train_arith_em.py --task arith2 --eval-only \
    --n-problems 200 --ckpt-dir runs/arith25m --ns 1 4 8 32 64 \
    --report runs/reports/arith25m_em_arith2_r5.json

# Q4: panel + debate wall-clock on chip.
leg runs/reports/panel_config3_r5.json bash -c \
  'python examples/panel_arith_demo.py --ckpt runs/arith14m \
     --ckpt runs/arith14m_mid2 --ckpt runs/arith14m_mid \
     > runs/reports/panel_config3_r5.json.tmp \
   && mv runs/reports/panel_config3_r5.json.tmp \
         runs/reports/panel_config3_r5.json'
leg runs/reports/debate_arith_r5.json \
  python examples/debate_arith_eval.py --ckpt runs/arith14m \
    --report runs/reports/debate_arith_r5.json

# Q5: bench legs (PERF.md pending rows). Artifacts land via bench's
# atomic --out (tmp + os.replace) — shell redirection committed a torn
# 0-byte spec_trained_r5.json when the container recycled mid-write
# (VERDICT.md), so no leg writes its artifact through `>` anymore.
leg runs/r5_bench_serve3.json \
  python bench.py --serve --serve-chunk 16 --out runs/r5_bench_serve3.json
leg runs/r5_bench_moe_auto.json \
  python bench.py --model moe-1b-4e --out runs/r5_bench_moe_auto.json
leg runs/r5_bench_moe_dense.json \
  python bench.py --model moe-1b-4e --moe-dense --out runs/r5_bench_moe_dense.json
leg runs/r5_bench_moe_pinned.json \
  python bench.py --model moe-1b-4e --moe-capacity --out runs/r5_bench_moe_pinned.json
leg runs/r5_bench_spec_self2.json \
  python bench.py --draft self --n-candidates 8 --out runs/r5_bench_spec_self2.json
leg runs/r5_bench_default_a.json \
  python bench.py --out runs/r5_bench_default_a.json
leg runs/r5_bench_default_b.json \
  python bench.py --out runs/r5_bench_default_b.json

# Q7: candidate-count scaling under the post-fix methodology.
for N in 16 128 256 512 1024; do
  leg "runs/r5_bench_scale_n$N.json" \
    python bench.py --n-candidates "$N" --out "runs/r5_bench_scale_n$N.json"
done

echo RECOVERY-ALL-DONE "$(date -u)"
# Appended: exact-N legs for BASELINE configs[2] and [4].
leg runs/r5_bench_moe_n16.json \
  python bench.py --model moe-1b-4e --n-candidates 16 --out runs/r5_bench_moe_n16.json
leg runs/reports/debate_arith_n32_r5.json \
  python examples/debate_arith_eval.py --ckpt runs/arith14m \
    --n-candidates 32 --report runs/reports/debate_arith_n32_r5.json
echo RECOVERY-APPENDED-DONE "$(date -u)"
