"""Test configuration: force JAX onto a simulated 8-device CPU mesh.

The reference has no tests at all (SURVEY.md §4). Our multi-device tests
(DP/TP/EP shardings, ring attention collectives) run on CPU-simulated
devices via ``--xla_force_host_platform_device_count`` so they need no TPU
(SURVEY.md §4's prescription).

Must run before the first ``import jax`` anywhere in the test process.
"""

import os

# Force CPU: the ambient environment preimports jax (sitecustomize) and
# registers a real-TPU tunnel backend whose initialization blocks on the
# (single, shared) chip. Tests must never contend for it, and the
# multi-device tests need the 8 simulated CPU devices below. Because jax
# is already imported before this file runs, the env var alone is not
# enough — flip the live config too, before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# This environment's default matmul precision truncates fp32 matmuls to
# bf16 passes; numerics tests compare against exact numpy references, so
# pin full precision for the test process only (production keeps the fast
# default — bf16 on the MXU is the intended TPU path).
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(scope="session")
def cpu_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected 8 simulated devices, got {len(devices)}"
    return devices


def pytest_collection_modifyitems(config, items):
    """Schedule the heaviest modules FIRST.

    The GPipe pipeline tests compile shard_map programs whose peak
    process memory exceeds what's left after an xdist worker has
    accumulated several other modules' XLA:CPU state — the worker
    aborts ("worker crashed") even though every test passes in
    isolation. Heavy modules first means they land on fresh workers;
    the light tail fills in afterwards. Stable sort preserves
    within-module order.
    """
    heavy = (
        "test_pipeline.py",
        "test_train_loop.py",
        "test_training.py",
        "test_parallel.py",
    )
    items.sort(
        key=lambda it: 0 if any(h in it.nodeid for h in heavy) else 1
    )


@pytest.fixture(autouse=True, scope="module")
def _bounded_xla_arena():
    """Clear JAX compile caches between test modules.

    XLA:CPU keeps every compiled executable alive for the process; an
    xdist worker that accumulates several heavy modules' programs can
    hit the process arena limit and abort (the round-2 monolithic-run
    failure mode, which grows back as the suite grows). Clearing per
    module bounds each worker at its heaviest single module; cross-
    module cache hits are rare (different shapes), so the runtime cost
    is small.
    """
    import jax

    jax.clear_caches()
    yield
