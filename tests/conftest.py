"""Test configuration: force JAX onto a simulated 8-device CPU mesh.

The reference has no tests at all (SURVEY.md §4). Our multi-device tests
(DP/TP/EP shardings, ring attention collectives) run on CPU-simulated
devices via ``--xla_force_host_platform_device_count`` so they need no TPU
(SURVEY.md §4's prescription).

Must run before the first ``import jax`` anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected 8 simulated devices, got {len(devices)}"
    return devices
