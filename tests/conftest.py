"""Test configuration: force JAX onto a simulated 8-device CPU mesh.

The reference has no tests at all (SURVEY.md §4). Our multi-device tests
(DP/TP/EP shardings, ring attention collectives) run on CPU-simulated
devices via ``--xla_force_host_platform_device_count`` so they need no TPU
(SURVEY.md §4's prescription).

Must run before the first ``import jax`` anywhere in the test process.
"""

import os

# Force CPU: the ambient environment preimports jax (sitecustomize) and
# registers a real-TPU tunnel backend whose initialization blocks on the
# (single, shared) chip. Tests must never contend for it, and the
# multi-device tests need the 8 simulated CPU devices below. Because jax
# is already imported before this file runs, the env var alone is not
# enough — flip the live config too, before any backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# This environment's default matmul precision truncates fp32 matmuls to
# bf16 passes; numerics tests compare against exact numpy references, so
# pin full precision for the test process only (production keeps the fast
# default — bf16 on the MXU is the intended TPU path).
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(scope="session")
def cpu_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected 8 simulated devices, got {len(devices)}"
    return devices


def pytest_collection_modifyitems(config, items):
    """Schedule the heaviest modules FIRST.

    The GPipe pipeline tests compile shard_map programs whose peak
    process memory exceeds what's left after an xdist worker has
    accumulated several other modules' XLA:CPU state — the worker
    aborts ("worker crashed") even though every test passes in
    isolation. Heavy modules first means they land on fresh workers;
    the light tail fills in afterwards. Stable sort preserves
    within-module order.
    """
    heavy = (
        "test_pipeline.py",
        "test_train_loop.py",
        "test_training.py",
        "test_parallel.py",
    )
    items.sort(
        key=lambda it: 0 if any(h in it.nodeid for h in heavy) else 1
    )
    # Smoke-tier marking (see _SMOKE_TESTS at the bottom of this file).
    for item in items:
        if item.name.split("[")[0] in _SMOKE_TESTS:
            item.add_marker(pytest.mark.smoke)


@pytest.fixture(autouse=True, scope="module")
def _bounded_xla_arena():
    """Clear JAX compile caches between test modules.

    XLA:CPU keeps every compiled executable alive for the process; an
    xdist worker that accumulates several heavy modules' programs can
    hit the process arena limit and abort (the round-2 monolithic-run
    failure mode, which grows back as the suite grows). Clearing per
    module bounds each worker at its heaviest single module; cross-
    module cache hits are rare (different shapes), so the runtime cost
    is small.
    """
    import jax

    jax.clear_caches()
    yield


# ---------------------------------------------------------------------------
# Smoke tier: one-or-two fast tests per subsystem, selected centrally so
# the list is auditable in one place. `pytest -m smoke` runs in <2 min
# (gate iteration / future-round triage); the FULL suite stays the merge
# gate. Names, not nodeids: parametrized variants all count.
# ---------------------------------------------------------------------------

_SMOKE_TESTS = {
    # protocol: messages/parsing/prompts/personas/coordinator/debate
    "test_good_verdict",
    "test_answer_prompt_shape",
    "test_default_panel_matches_reference",
    "test_unanimous_first_round",
    "test_debate_validates_before_generating",
    "test_faults_are_seeded_and_counted",
    # voting / eval
    "test_majority_vote_basic",
    "test_bundled_dataset_loads_and_golds_extract",
    # ops / model / quant
    "test_rms_norm_matches_numpy",
    "test_forward_shapes_and_dtype",
    "test_quantize_roundtrip_error_bound",
    "test_quantize_kv_roundtrip",
    # engine / tokenizer / backends
    "test_byte_tokenizer_roundtrip",
    "test_engine_text_roundtrip",
    "test_generate_batch_returns_aligned_results",
    # training / data / checkpoint
    "test_sft_loader_mask_and_resume",
    "test_loss_is_finite_and_near_uniform_at_init",
    "test_params_roundtrip",
    # parallel / multihost
    "test_make_mesh_default_all_data",
    "test_param_pspecs_cover_dense_and_moe",
    "test_pp_param_pspecs_shard_layer_axis",
    "test_initialize_noop_single_host",
    # serving / paged
    "test_page_write_gather_roundtrip",
    "test_submit_after_close_raises",
    # native runtime / utils / cli
    "test_batch_encode_matches_python_tokenizer",
    "test_tracer_spans_and_summary",
    "test_parser_defaults",
    "test_one_shot_question_fake_backend",
}
