"""Roofline-adaptive runtime control (PR 15, serving/control.py).

Contract layers:

- CONTROLLER UNITS: EWMA/decision arithmetic in isolation — per-group
  acceptance shrink/regrow over the {1, spec_k} menu, the disengage +
  probe state machine, the two-arm rounds regime (stretch-level
  measured rates, compile-sample discard, probe backoff), chunk/depth
  steering bounds, restore-pacing debt, and ``--hbm-gbps auto``
  resolution.
- BATCHER E2E: with a controller attached, text stays BYTE-IDENTICAL
  to every fixed knob setting (the spec accept rule, multi-round
  early-exit masking, and depth/chunk invariance are pre-existing
  contracts the controller rides); an adversarial draft records a
  spec_k shrink and disengage, a self-draft probe regrows; the
  compiled-program families stay bounded across a steering burst
  (no-recompile guarantee).
- ADMISSION: cost-budget mode bounds queues in MODELED BYTES — the
  same unit the router's load_cost compares — so one 32k-context
  request sheds where N small ones fit, and the overflow hard cap is
  bytes too (the unit-normalization fix).
- SURFACES: gateway_autotune_value/_decisions_total, the stats()
  autotune_* mirrors, and ``autotune`` flight events move in lockstep
  from one decision site.
"""

import subprocess
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import pytest

from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import init_params
from llm_consensus_tpu.serving import flight as _flight
from llm_consensus_tpu.serving.continuous import (
    ContinuousBatcher,
    ContinuousConfig,
)
from llm_consensus_tpu.serving.control import (
    AdaptiveController,
    ControlConfig,
    resolve_hbm_gbps,
)

CFG = get_config("test-tiny")

_CCFG = dict(
    max_slots=4,
    page_size=16,
    n_pages=96,
    pages_per_seq=12,
    max_new_tokens=10,
    seq_buckets=(16, 32, 64),
    prefill_chunk=16,
    share_prefix=True,
)

_HEADER = "Panel shared header for every persona, forty ch: "


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


@pytest.fixture(scope="module")
def adv_dparams():
    # Random draft weights from another seed: proposes garbage,
    # accepts ~nothing — the adversarial draft spec_k auto-tune
    # exists for.
    return init_params(CFG, jax.random.PRNGKey(1), dtype=jnp.float32)


def _serve(batcher, prompts, **kw):
    futs = [batcher.submit(p, **kw) for p in prompts]
    return [f.result(timeout=180) for f in futs]


def _quiesce(batcher, timeout=30.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        s = batcher.stats()
        if (
            s["active_slots"] == 0
            and s["prefilling_slots"] == 0
            and s["dispatch_inflight"] == 0
            and s["waiting"] == 0
        ):
            return
        time.sleep(0.01)
    raise RuntimeError(f"no quiesce: {batcher.stats()}")


# ---------------------------------------------------------------------------
# Controller units
# ---------------------------------------------------------------------------


def test_spec_k_shrink_and_regrow_units():
    """Per-group acceptance EWMAs drive the {1, k_max} menu: unknown
    groups get the full window (optimistic start), a rejecting group
    shrinks to 1 after min_samples, recovery past accept_low regrows,
    and ONE high-acceptance group keeps the whole dispatch at full k
    (the program-wide k helps whoever has something to gain)."""
    c = AdaptiveController(
        ControlConfig(accept_min_samples=3, ewma_alpha=0.5)
    )
    assert c.spec_k_for([7], 4) == 4  # no samples yet
    for _ in range(4):
        c.note_spec_round([(7, 0, 4)])
    assert c.group_acceptance(7) == pytest.approx(0.0)
    assert c.spec_k_for([7], 4) == 1
    # A second, accepting group keeps the dispatch at full width.
    for _ in range(4):
        c.note_spec_round([(9, 4, 4)])
    assert c.spec_k_for([7, 9], 4) == 4
    # The rejecting group alone recovers past accept_low -> regrow.
    for _ in range(4):
        c.note_spec_round([(7, 4, 4)])
    assert c.spec_k_for([7], 4) == 4


def test_spec_disengage_and_probe_state_machine():
    """Every group rejecting (EWMA < disengage floor) flips the gate
    off with a spec_k=0 decision; plain windows advance the probe
    clock; the armed probe re-engages at the k=1 floor, and a fully
    accepted probe window re-engages for real."""
    c = AdaptiveController(
        ControlConfig(
            accept_min_samples=2, spec_probe_every=5, ewma_alpha=0.5
        )
    )
    for _ in range(3):
        c.note_spec_round([(1, 0, 4), (2, 0, 4)])
    assert c.spec_gate([1, 2]) is False  # disengage decision
    assert c.stats()["autotune_spec_k"] == 0
    assert c.stats()["autotune_spec_engaged"] == 0
    for _ in range(4):
        c.note_plain_window()
        assert c.spec_gate([1, 2]) is False
    c.note_plain_window()  # 5th plain window arms the probe
    assert c.spec_gate([1, 2]) is True
    assert c.spec_k_for([1, 2], 4) == 1  # probes run at the floor
    c.note_spec_round([(1, 1, 1)])  # fully accepted probe window
    assert c.stats()["autotune_spec_engaged"] == 1
    assert c.spec_gate([1, 2]) is True
    # A probe that runs OUT still rejecting restores the knob's
    # disengaged reading (the probe windows recorded spec_k=1; the
    # gauge contract says 0 = disengaged).
    for _ in range(6):
        c.note_spec_round([(1, 0, 4), (2, 0, 4)])
    assert c.spec_gate([1, 2]) is False  # re-disengaged
    for _ in range(5):
        c.note_plain_window()
    assert c.spec_gate([1, 2]) is True  # probe armed again
    assert c.spec_k_for([1, 2], 4) == 1
    for _ in range(4):
        c.note_spec_round([(1, 0, 1)])  # every probe window rejects
    assert c.stats()["autotune_spec_engaged"] == 0
    assert c.stats()["autotune_spec_k"] == 0  # not left at the probe 1


def test_rounds_regime_measured_rates_and_near_stop():
    """The two-arm rounds decision: near-stop always forces 1; the
    first window of an arm (its jit compile) never enters a rate;
    stretch-level measured throughput flips the regime to whichever
    arm actually serves faster; a losing probe backs off."""
    c = AdaptiveController(
        ControlConfig(
            rounds_stretch_windows=3,
            rounds_stretch_min=3,
            rounds_stretch_gap_s=10.0,
            rounds_probe_stretches=2,
            ewma_alpha=0.2,
        )
    )
    clock = [0.0]

    def feed(arm, tokens, step):
        clock[0] += step
        c.note_rounds_window(arm, tokens, now=clock[0])

    assert c.rounds_cap(2, 4) == 1  # near-stop, no data needed
    assert c.rounds_cap(100, 4) == 4  # cold start: configured intent
    # Arm 4: first window discarded (its jit compile), then an
    # anchor + a 3-window stretch at 4 tokens / 0.04 s = 100 tok/s.
    feed(4, 999, 60.0)  # compile window, discarded
    feed(4, 0, 0.04)  # stretch anchor
    for _ in range(3):
        feed(4, 4, 0.04)
    # Stretch folded -> calibration switches the regime to arm 1.
    assert c._arm_rate(4) == pytest.approx(100.0)
    assert c.rounds_cap(100, 4) == 1
    feed(1, 999, 60.0)  # arm 1 compile, discarded + re-anchor
    for _ in range(4):
        feed(1, 4, 0.01)  # anchor + 3 windows at 400 tok/s
    # Both arms measured; arm 1 wins.
    assert c._arm_rate(1) == pytest.approx(400.0)
    assert c.rounds_cap(100, 4) == 1
    # Probe cadence: after rounds_probe_stretches more arm-1
    # stretches the regime probes arm 4 once...
    for _ in range(3):
        feed(1, 4, 0.01)
    assert c._regime_arm == 4 and c._rounds_probing
    assert c.rounds_cap(100, 4) == 4
    # ... which measures slow again -> snaps back + backs off.
    for _ in range(3):
        feed(4, 4, 0.04)
    assert c._regime_arm == 1
    assert c._rounds_probe_backoff == 2  # lost probe -> backoff
    # An idle gap folds the partial stretch (>= rounds_stretch_min)
    # without counting the idle: two windows, then a gap, then one —
    # the 2-window partial is below min and is discarded.
    tok0 = dict(c._rate_tok)
    feed(1, 4, 0.01)
    feed(1, 4, 0.01)
    feed(1, 4, 100.0)  # gap: partial (2 < min 3) discarded
    assert c._rate_tok == tok0
    # A chunk/depth decision mid-stretch poisons it: the fold
    # DISCARDS the stretch (its windows measured the transition —
    # and the steered width's jit — not the arm) and the arms'
    # rates stand. The next clean stretch folds normally.
    feed(1, 4, 0.01)
    c.note_overhead(1.0)
    assert c.depth_for(2) == 2  # first depth decision -> a change
    feed(1, 4, 0.01)
    feed(1, 4, 0.01)
    feed(1, 4, 0.01)  # 3 windows: folds, but dirty -> discarded
    assert c._rate_tok == tok0
    for _ in range(3):
        feed(1, 4, 0.01)  # clean 3-window stretch folds again
    assert c._rate_tok != tok0


def test_chunk_and_depth_steering_units():
    """Chunk: full width while overhead is visible, unknown, or the
    peak is unresolved; half (when it divides the bucket) only once
    the host loop is hidden AND the measured lane MBU reads
    bandwidth-starved — halving is an MBU-driven decision, with
    hysteresis back to full when overhead re-appears. Depth: visible
    overhead pins the configured depth, a hidden one probes lower
    and commits when it stays hidden."""
    c = AdaptiveController(
        ControlConfig(
            overhead_high_s=0.002,
            overhead_low_s=0.0005,
            depth_probe_every=3,
            depth_probe_len=2,
            ewma_alpha=1.0,
        )
    )
    assert c.chunk_for(64, 16) == 16  # no overhead signal yet
    c.note_overhead(0.01)
    assert c.chunk_for(64, 16) == 16  # host-bound: full width
    assert c.depth_for(2) == 2
    c.note_overhead(0.0)
    # Hidden host but NO resolved peak: the configured width stands
    # (halving doubles the per-prompt program count on no evidence
    # that's free — the overhead signal can't price it).
    assert c.chunk_for(64, 16) == 16
    c.bind(hbm_gbps=1.0)
    starved = {
        "hbm_bytes": int(4e8),
        "kv_read_tokens": 0,
        "kv_write_tokens": 0,
    }
    c.note_program("decode", starved, 1.0)  # MBU 0.4: starved lane
    assert c.chunk_for(64, 16) == 8  # hidden + starved: halve
    assert c.chunk_for(64, 15) == 15  # odd width: menu has no half
    assert c.chunk_for(10, 6) == 6  # half wouldn't divide bucket
    assert c.chunk_for(9, 6) == 3  # ... but divides this one
    # An efficient lane (MBU past the 0.6 hysteresis edge) restores
    # the full width even while the host stays hidden.
    c.note_program("decode", {**starved, "hbm_bytes": int(8e8)}, 1.0)
    assert c.chunk_for(64, 16) == 16
    c.note_program("decode", starved, 1.0)
    assert c.chunk_for(64, 16) == 8  # starved again: halve again
    # Depth probes lower after depth_probe_every hidden dispatches,
    # and commits once the probe survives depth_probe_len dispatches.
    seen = [c.depth_for(2) for _ in range(8)]
    assert 1 in seen  # probed
    assert c.depth_for(2) == 1  # committed
    # Overhead re-appearing reverts to the configured depth AND the
    # configured chunk width (the halving hysteresis's other exit).
    c.note_overhead(0.01)
    assert c.depth_for(2) == 2
    assert c.chunk_for(64, 16) == 16


def test_restore_pacing_debt():
    """The preempt hook's consult: demoted-not-restored modeled bytes
    must stay under restore_debt_frac x the host budget; restores
    repay the debt."""
    c = AdaptiveController(ControlConfig(restore_debt_frac=0.5))
    c.bind(host_budget_bytes=1000)
    assert c.restore_pacing_ok(4, 100)  # 400 <= 500
    c.note_preempt_demote(400)
    assert not c.restore_pacing_ok(2, 100)  # 400 + 200 > 500
    c.note_restore(300)
    assert c.restore_pacing_ok(2, 100)  # 100 + 200 <= 500
    # No host budget bound => pacing never blocks (controller-less
    # fleets keep the PR-14 behavior; so do budget-less controllers).
    c2 = AdaptiveController()
    assert c2.restore_pacing_ok(10_000, 10_000)


def test_hbm_gbps_auto_resolution(caplog):
    """Numbers pass through; 'auto' resolves from the platform table
    (the CPU sentinel on this box); an unknown device kind warns once
    and returns 0.0 (MBU-driven steering disables itself)."""
    import logging

    assert resolve_hbm_gbps(3.5) == 3.5
    assert resolve_hbm_gbps("819") == 819.0
    auto = resolve_hbm_gbps("auto")
    assert auto == 10.0  # the CPU-smoke sentinel (JAX_PLATFORMS=cpu)
    # Unknown device kind: patch the table empty to simulate.
    import llm_consensus_tpu.serving.control as control

    with caplog.at_level(logging.WARNING):
        old = control.HBM_GBPS_TABLE
        control.HBM_GBPS_TABLE = ()
        try:
            assert control.resolve_hbm_gbps("auto") == 0.0
        finally:
            control.HBM_GBPS_TABLE = old
    assert any(
        "no roofline entry" in r.message for r in caplog.records
    )
    c = AdaptiveController()
    c.bind(hbm_gbps=0.0)
    assert not c.mbu_driven


# ---------------------------------------------------------------------------
# Batcher e2e
# ---------------------------------------------------------------------------


def test_adversarial_shrink_disengage_and_byte_parity(
    params, adv_dparams
):
    """An adversarial draft under the controller: text byte-identical
    to the controller-less plain batcher (the accept rule + masking
    contracts), with a spec_k shrink/disengage decision recorded on
    every surface — flight events, the Prometheus counter, and the
    stats() mirrors — in lockstep."""
    from llm_consensus_tpu.server.metrics import REGISTRY

    prompts = [_HEADER + f"Q{i}" for i in range(4)]
    b0 = ContinuousBatcher(
        CFG, params, config=ContinuousConfig(**_CCFG)
    )
    try:
        want = [r.text for r in _serve(b0, prompts, max_new_tokens=16)]
    finally:
        b0.close()

    ctrl = AdaptiveController(
        ControlConfig(accept_min_samples=2, spec_probe_every=10_000)
    )
    _flight.flight_recorder().clear()

    def autotune_counter():
        return sum(
            v
            for k, v in REGISTRY.snapshot().items()
            if k.startswith("gateway_autotune_decisions_total")
        )

    before = autotune_counter()
    b = ContinuousBatcher(
        CFG,
        params,
        config=ContinuousConfig(**_CCFG, spec_k=4),
        draft=(CFG, adv_dparams),
        controller=ctrl,
    )
    try:
        got = [r.text for r in _serve(b, prompts, max_new_tokens=16)]
        _quiesce(b)
        st = b.stats()
    finally:
        b.close()
    assert got == want, "adaptive spec must not change text"
    # The rejects shrank/disengaged spec_k (decision value < 4).
    evs = [
        e
        for e in _flight.flight_recorder().events()
        if e.kind == "autotune" and e.meta.get("knob") == "spec_k"
    ]
    assert any(e.meta["value"] < 4 for e in evs), evs
    assert st["autotune_spec_engaged"] == 0  # disengaged by the end
    # Lockstep: the Prometheus counter moved by exactly the stats()
    # decision totals, and every decision change is a flight event.
    decisions = sum(
        st[f"autotune_decisions_{k}"]
        for k in ("spec_k", "rounds", "chunk", "depth")
    )
    assert autotune_counter() - before == decisions
    all_evs = [
        e
        for e in _flight.flight_recorder().events()
        if e.kind == "autotune"
    ]
    assert len(all_evs) == decisions


def test_self_draft_probe_regrows(params):
    """A disengaged controller re-probes and REGROWS on a self-draft
    (acceptance 1.0): force the disengaged state with poisoned EWMAs,
    serve, and the probe window's full acceptance re-engages."""
    ctrl = AdaptiveController(
        ControlConfig(accept_min_samples=1, spec_probe_every=2)
    )
    # Poison: pretend every group rejected until disengaged.
    for _ in range(3):
        ctrl.note_spec_round([(-1, 0, 4)])
    assert ctrl.spec_gate([-1]) is False
    b = ContinuousBatcher(
        CFG,
        params,
        config=ContinuousConfig(**_CCFG, spec_k=4),
        draft=(CFG, params),  # self-draft: acceptance 1.0
        controller=ctrl,
    )
    try:
        _serve(
            b,
            [_HEADER + f"regrow {i}" for i in range(3)],
            max_new_tokens=24,
        )
        _quiesce(b)
        st = b.stats()
    finally:
        b.close()
    assert st["autotune_spec_engaged"] == 1, st
    assert st["device_programs_spec"] > 0


def test_adaptive_rounds_byte_parity_vs_fixed_grid(params):
    """Adaptive-R (and chunk/depth steering with it) vs the fixed R
    grid: byte-identical text for R in {1, 4} with and without the
    controller, with at least one rounds decision recorded."""
    prompts = [_HEADER + f"R{i}" for i in range(5)]

    def run(R, ctrl):
        b = ContinuousBatcher(
            CFG,
            params,
            config=ContinuousConfig(**_CCFG, decode_rounds=R),
            controller=ctrl,
        )
        try:
            # 14 % 4 != 0: the tail window must cap.
            return [
                r.text for r in _serve(b, prompts, max_new_tokens=14)
            ]
        finally:
            b.close()

    want = run(1, None)
    assert run(4, None) == want  # the PR-12 contract itself
    _flight.flight_recorder().clear()
    ctrl = AdaptiveController(ControlConfig())
    assert run(4, ctrl) == want
    evs = [
        e
        for e in _flight.flight_recorder().events()
        if e.kind == "autotune" and e.meta.get("knob") == "rounds"
    ]
    assert evs, "no adaptive-R decision recorded"
    assert any(e.meta["value"] == 1 for e in evs), (
        "the tail windows must have capped to 1"
    )


def test_no_recompile_across_steering_burst(params, adv_dparams):
    """The no-recompile guarantee: after a warmup burst has visited
    the controller's menus, further steering bursts leave every
    compiled-program family untouched (jit trace counts and the
    chunk/fused wrapper keys are stable)."""
    ctrl = AdaptiveController(
        ControlConfig(accept_min_samples=2, spec_probe_every=10_000)
    )
    b = ContinuousBatcher(
        CFG,
        params,
        config=ContinuousConfig(**_CCFG, spec_k=4, decode_rounds=4),
        draft=(CFG, adv_dparams),
        controller=ctrl,
    )

    def caches():
        out = {
            "chunk": sorted(b._jit_chunk),
            "fused": sorted(b._jit_fused),
            "chunk_d": sorted(b._jit_chunk_d),
        }
        for name in ("_jit_decode", "_jit_rounds", "_jit_spec"):
            try:
                out[name] = getattr(b, name)._cache_size()
            except Exception:  # noqa: BLE001 - jax without _cache_size
                out[name] = -1
        return out

    try:
        # Warmup: two bursts land the shrink/disengage and the capped
        # tail window, and a half-chunk burst compiles the chunk
        # steering menu's other width (the bench leg's warmup does
        # the same) — the menus are bounded, so warmup covers them.
        for w in range(2):
            _serve(
                b,
                [_HEADER + f"warm{w} {i}" for i in range(4)],
                max_new_tokens=14,
            )
            _quiesce(b)
        b.controller = None
        b.config.prefill_chunk = _CCFG["prefill_chunk"] // 2
        # Spec off + several prompts: later chunks must RIDE earlier
        # rows' plain decode so the FUSED half-width variant compiles
        # (spec-engaged chunks run standalone and would skip it).
        b.config.spec_decode = False
        _serve(
            b, [_HEADER + f"half {i}" for i in range(3)], max_new_tokens=6
        )
        _quiesce(b)
        b.config.prefill_chunk = _CCFG["prefill_chunk"]
        b.config.spec_decode = True
        b.controller = ctrl
        c0 = caches()
        for w in range(2):
            _serve(
                b,
                [_HEADER + f"steer{w} {i}" for i in range(4)],
                max_new_tokens=14,
            )
            _quiesce(b)
        c1 = caches()
    finally:
        b.close()
    assert c1 == c0, f"steering burst recompiled: {c0} -> {c1}"


# ---------------------------------------------------------------------------
# Modeled-cost admission
# ---------------------------------------------------------------------------


def test_cost_admission_sheds_large_before_small():
    """Cost-budget mode: the queue bound is modeled bytes, so one
    32k-context-sized request sheds while N small ones keep fitting —
    and the overflow hard cap is the SAME byte unit (budget x factor),
    regardless of request count (the unit-normalization fix)."""
    import asyncio

    from llm_consensus_tpu.server import metrics as M
    from llm_consensus_tpu.server.admission import (
        AdmissionConfig,
        AdmissionController,
        QueueFullError,
    )

    async def main():
        reg = M.MetricsRegistry()
        c = AdmissionController(
            AdmissionConfig(
                max_queue=4,
                max_inflight=1,
                cost_budget_bytes=1000.0,
                max_overflow_factor=2,
            ),
            registry=reg,
        )
        gate = asyncio.Event()

        async def wait():
            await gate.wait()

        # An over-budget request on an EMPTY queue still admits: the
        # budget bounds the backlog, never one request's size (a
        # request the backend supports must not be unservable).
        inflight = asyncio.create_task(c.submit(wait, cost=5000))
        await asyncio.sleep(0.02)
        assert not inflight.done()
        small = [
            asyncio.create_task(c.submit(wait, cost=100))
            for _ in range(9)
        ]
        await asyncio.sleep(0.02)
        # 900 bytes queued: the big request (500) does not fit ...
        with pytest.raises(QueueFullError):
            await c.submit(wait, cost=500)
        # ... but a small one still does.
        ok = asyncio.create_task(c.submit(wait, cost=90))
        await asyncio.sleep(0.02)
        assert not ok.done()
        # The queue-cost gauge mirrors the account.
        fam = reg.get("gateway_queue_cost_bytes")
        assert fam.labels(priority="interactive").value == 990.0
        # A granting overflow hook stretches the bound in BYTES: the
        # hard cap lands at budget x factor = 2000 bytes, not at any
        # request count.
        c.overflow_hook = lambda: True
        granted = []
        for _ in range(20):
            granted.append(
                asyncio.create_task(c.submit(wait, cost=300))
            )
            await asyncio.sleep(0.005)
        await asyncio.sleep(0.02)
        queued = c._queue_cost["interactive"]
        assert queued <= 2000.0 + 300.0, queued
        shed = sum(
            1
            for t in granted
            if t.done() and isinstance(t.exception(), QueueFullError)
        )
        assert shed > 0, "the byte hard cap never engaged"
        gate.set()
        await asyncio.gather(
            inflight, ok, *small, *granted, return_exceptions=True
        )
        assert c._queue_cost["interactive"] == 0.0

    asyncio.run(main())


def test_modeled_request_cost_matches_load_cost_units(params):
    """modeled_request_cost prices a waiting request EXACTLY as
    load_cost integrates it — one formula, one byte unit (the
    admission bound and the fleet router can never drift)."""
    b = ContinuousBatcher(CFG, params, config=ContinuousConfig(**_CCFG))
    try:
        base = b.load_cost()
        ids = b.tokenizer.encode(_HEADER + "cost probe")
        want = b.modeled_request_cost(len(ids), 7)
        # Stage a waiting request without letting the worker admit it:
        # hold the admission lock while probing.
        with b._lock:
            from llm_consensus_tpu.serving.continuous import _Request
            from concurrent.futures import Future
            import numpy as np

            b._waiting.append(
                _Request(
                    prompt_ids=np.asarray(ids, np.int32),
                    max_new_tokens=7,
                    temperature=0.0,
                    seed=0,
                    future=Future(),
                )
            )
            # load_cost takes the same lock: compute inline instead.
            kvb = b._kv_token_bytes + b._draft_kv_token_bytes
            got = float(
                b._cost_tokens(len(ids), 7) * kvb
            )
            b._waiting.pop()
        assert got == want
        assert b.load_cost() == base  # nothing leaked
        # A long context costs proportionally more than a short one in
        # the SAME unit (the whole point of cost-budget admission);
        # prompts past the largest bucket clamp like the submit path.
        assert b.modeled_request_cost(64, 8) > 5 * b.modeled_request_cost(
            4, 8
        )
        assert b.modeled_request_cost(4096, 8) == b.modeled_request_cost(
            64, 8
        )
    finally:
        b.close()


def test_fleet_restore_pacing_blocks_preempt(params):
    """A fleet whose victim controller reports restore debt past the
    cap stops granting overflow admissions (classic backpressure
    resumes); repaying the debt re-enables preemption."""
    from llm_consensus_tpu.serving.fleet import FleetConfig, ReplicaSet

    rs = ReplicaSet(
        CFG,
        params,
        config=ContinuousConfig(**_CCFG, host_cache_bytes=1 << 20),
        fleet=FleetConfig(replicas=2),
        control=ControlConfig(),
    )
    try:
        # Give replica 0 a resident chain so the hook has a victim.
        rs.submit_to(0, _HEADER + "resident chain", max_new_tokens=4)
        for b in rs.batchers:
            _quiesce(b)
        assert rs.batchers[0].cached_chain_pages() > 0
        assert rs.preempt_for_admission() is True
        # Saturate the victim's modeled restore debt.
        ctrl = rs.batchers[0].controller
        assert ctrl is not None
        ctrl.note_preempt_demote(10 << 20)
        assert rs.preempt_for_admission() is False
        ctrl.note_restore(10 << 20)
        assert rs.preempt_for_admission() is True
    finally:
        rs.close()


# ---------------------------------------------------------------------------
# Bench leg
# ---------------------------------------------------------------------------


def test_bench_serve_adaptive_cpu_ab_leg():
    """The CPU A/B leg (acceptance): adaptive >= every fixed
    (spec_k x R) grid point under the dual gate, byte-identical text,
    >= 1 spec_k shrink + >= 1 adaptive-R decision in the flight
    trace, zero recompiles after warmup, unit-tagged JSON."""
    r = subprocess.run(
        [
            sys.executable, "bench.py", "--tiny", "--cpu",
            "--serve-adaptive", "--serve-requests", "8",
            "--serve-slots", "8", "--new-tokens", "18",
            "--prompt-len", "96", "--serve-prefill-chunk", "64",
            "--adaptive-ab-rounds", "2",
        ],
        cwd=Path(__file__).resolve().parent.parent,
        capture_output=True,
        text=True,
        timeout=1200,
    )
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert '"status": "ok"' in r.stdout
    assert '"unit": "tokens/sec"' in r.stdout
    assert "text unchanged=True" in r.stdout
