"""Tests for the multi-step multi-template arithmetic corpus
(eval/arith2.py) — the round-5 hard accuracy task.

Checks the properties the EM evidence depends on: exact arithmetic in
every rendered CoT, chain-level holdout really excluding eval
computations from training, deterministic splits, bounded values, and
surface diversity (all frames exercised, distractors present).
"""

import random

import pytest

from llm_consensus_tpu.consensus.voting import extract_final_number
from llm_consensus_tpu.engine.tokenizer import ByteTokenizer
from llm_consensus_tpu.eval.arith2 import (
    N_FRAMES,
    Chain,
    build_sft_examples,
    eval_problems,
    render_completion,
    render_question,
    sample_chain,
)


def test_chain_values_exact():
    c = Chain(15, ("*", "/", "-"), (8, 3, 7))
    assert c.values == [15, 120, 40, 33]
    assert c.answer == 33


def test_chain_inexact_division_raises():
    with pytest.raises(ValueError):
        Chain(10, ("/",), (3,)).values


def test_sample_chain_bounds():
    rng = random.Random(7)
    for _ in range(500):
        c = sample_chain(rng)
        assert 2 <= len(c.ops) <= 4
        assert len(c.ops) == len(c.operands)
        for v in c.values:
            assert 2 <= v <= 999, c


def test_completion_parses_to_answer():
    rng = random.Random(3)
    for _ in range(100):
        c = sample_chain(rng)
        comp = render_completion(c)
        assert extract_final_number(comp) == str(c.answer)
        # Every intermediate step is stated and arithmetically true.
        for step in comp.split("####")[0].strip().split(". "):
            step = step.rstrip(".")
            lhs, rhs = step.split(" = ")
            a, op, b = lhs.split(" ")
            got = {
                "+": int(a) + int(b),
                "-": int(a) - int(b),
                "*": int(a) * int(b),
                "/": int(a) // int(b),
            }[op]
            assert got == int(rhs), step


def test_question_contains_operands_and_distractors():
    rng = random.Random(11)
    c = sample_chain(rng)
    q = render_question(c, 0, rng, n_distractors=2)
    assert str(c.v0) in q
    for b in c.operands:
        assert str(b) in q
    # Two distractor sentences -> more sentences than start+steps+question.
    assert q.count(".") + q.count("?") >= len(c.ops) + 2 + 2


def test_eval_problems_deterministic_and_unique():
    a, sa = eval_problems(40, seed=5)
    b, sb = eval_problems(40, seed=5)
    assert [p.question for p in a] == [p.question for p in b]
    assert sa == sb
    assert len(sa) == 40  # signatures unique
    c, _ = eval_problems(40, seed=6)
    assert [p.question for p in c] != [p.question for p in a]


def test_all_frames_rotate_in_eval():
    probs, _ = eval_problems(N_FRAMES * 2, seed=0)
    # Round-robin frames: every frame's protagonist appears.
    text = " ".join(p.question for p in probs)
    for word in ("Maya", "Liam", "library", "Priya", "farmer", "Noah"):
        assert word in text


def _signature_from_completion(text: str) -> tuple:
    """Recover a chain signature from a rendered CoT completion."""
    steps = text.split("####")[0].strip().rstrip(".").split(". ")
    v0 = None
    ops, operands = [], []
    for step in steps:
        lhs, _ = step.split(" = ")
        a, op, b = lhs.split(" ")
        if v0 is None:
            v0 = int(a)
        ops.append(op)
        operands.append(int(b))
    return (v0, tuple(ops), tuple(operands))


def test_sft_holdout_excluded():
    """The EMITTED training examples avoid every held-out chain: parse
    each example's CoT back into its chain signature and check it
    against the eval set (a corpus-side leak here would turn the EM
    numbers into memorization measurements)."""
    _, sigs = eval_problems(30, seed=0)
    tok = ByteTokenizer()
    examples = build_sft_examples(tok, 300, exclude=sigs, seed=1)
    assert len(examples) == 300
    for _, c_ids in examples:
        text = tok.decode(c_ids)
        assert _signature_from_completion(text) not in sigs
    # Sanity: the recovery round-trips a known chain.
    c = Chain(15, ("*", "/"), (8, 3))
    assert _signature_from_completion(render_completion(c)) == c.signature
    # And the leak WOULD be caught: with an empty exclude set and the
    # eval chains' own seed, the walk does emit eval signatures.
    rng = random.Random(0)
    leaky = sample_chain(rng)
    assert (
        _signature_from_completion(render_completion(leaky))
        == leaky.signature
    )


def test_sft_examples_trainable_shapes():
    _, sigs = eval_problems(10, seed=0)
    tok = ByteTokenizer()
    examples = build_sft_examples(tok, 50, exclude=sigs, seed=2)
    for p_ids, c_ids in examples:
        assert p_ids[0] == tok.bos_id
        assert c_ids[-1] == tok.eos_id
        text = tok.decode(c_ids)
        assert "####" in text
        # Fits the arith-25m context (the preset built for this task).
        assert len(p_ids) + len(c_ids) <= 768
