"""LocalBackend: the Backend seam over on-device engines.

Covers routing by model name (heterogeneous panels, BASELINE.md
config[3]) and end-to-end consensus with a real (tiny) model standing
where the reference put the Gemini API (``src/main.rs:82-86``).
"""

import asyncio

import jax
import pytest

from llm_consensus_tpu.backends.base import (
    BackendError,
    GenerationRequest,
    SamplingParams,
)
from llm_consensus_tpu.backends.local import LocalBackend
from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import init_params


@pytest.fixture(scope="module")
def backend():
    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0))
    ec = EngineConfig(max_new_tokens=6, seq_buckets=(32,), batch_buckets=(1, 2, 4))
    eng = InferenceEngine(cfg, params, engine_config=ec)
    moe_cfg = get_config("test-tiny-moe")
    moe = InferenceEngine(
        moe_cfg,
        init_params(moe_cfg, jax.random.PRNGKey(1)),
        engine_config=ec,
    )
    return LocalBackend(eng, engines={"test-tiny-moe": moe})


def test_generate_batch_returns_aligned_results(backend):
    reqs = [
        GenerationRequest(prompt="What is 2+2?"),
        GenerationRequest(prompt="Name a color."),
    ]
    results = asyncio.run(backend.generate_batch(reqs))
    assert len(results) == 2
    for r in results:
        assert isinstance(r.text, str)
        assert r.num_tokens >= 1
        assert r.logprob is not None


def test_routes_by_model_name(backend):
    reqs = [
        GenerationRequest(prompt="hi", model="test-tiny"),
        GenerationRequest(prompt="hi", model="test-tiny-moe"),
    ]
    results = asyncio.run(backend.generate_batch(reqs))
    assert len(results) == 2


def test_unknown_model_raises(backend):
    with pytest.raises(BackendError):
        asyncio.run(
            backend.generate_batch(
                [GenerationRequest(prompt="hi", model="nope")]
            )
        )


def test_empty_batch(backend):
    assert asyncio.run(backend.generate_batch([])) == []


def test_consensus_over_local_backend(backend):
    """Full protocol loop with the tiny model as the substrate: must
    terminate (unanimity or round cap) without error."""
    from llm_consensus_tpu.consensus.coordinator import (
        Coordinator,
        CoordinatorConfig,
    )
    from llm_consensus_tpu.consensus.personas import default_panel

    coord = Coordinator(
        default_panel(),
        backend,
        CoordinatorConfig(
            max_rounds=2,
            seed=0,
            sampling=SamplingParams(max_new_tokens=6, temperature=0.8),
        ),
    )
    result = asyncio.run(coord.run("What is the capital of France?"))
    assert isinstance(result.answer, str) and result.answer != ""
    assert 1 <= result.rounds <= 2
    assert result.author in {p.name for p in coord.panel}


def test_greedy_requests_ride_speculative_with_draft():
    """A draft-equipped engine serves greedy generate_batch through the
    speculative path with unchanged output."""
    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0))
    ec = EngineConfig(max_new_tokens=6, seq_buckets=(16,), batch_buckets=(1, 2))
    plain = LocalBackend(InferenceEngine(cfg, params, engine_config=ec))
    drafted = LocalBackend(
        InferenceEngine(
            cfg, params, engine_config=ec,
            draft=(cfg, init_params(cfg, jax.random.PRNGKey(7))),
        )
    )
    reqs = [
        GenerationRequest(prompt="What is 2+2?"),
        GenerationRequest(prompt="Name a color."),
    ]
    want = asyncio.run(plain.generate_batch(reqs))
    got = asyncio.run(drafted.generate_batch(reqs))
    assert [r.text for r in got] == [r.text for r in want]


def test_speculative_routing_actually_fires(monkeypatch):
    """The greedy batch takes the speculative path (spy), and a
    mesh/kv_quant engine does NOT."""
    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0))
    ec = EngineConfig(max_new_tokens=4, seq_buckets=(16,), batch_buckets=(2,))
    eng = InferenceEngine(
        cfg, params, engine_config=ec,
        draft=(cfg, init_params(cfg, jax.random.PRNGKey(7))),
    )
    calls = {"spec": 0, "plain": 0}
    orig_spec = eng.generate_texts_speculative
    orig_plain = eng.generate_texts
    monkeypatch.setattr(
        eng, "generate_texts_speculative",
        lambda *a, **k: calls.__setitem__("spec", calls["spec"] + 1)
        or orig_spec(*a, **k),
    )
    monkeypatch.setattr(
        eng, "generate_texts",
        lambda *a, **k: calls.__setitem__("plain", calls["plain"] + 1)
        or orig_plain(*a, **k),
    )
    backend = LocalBackend(eng)
    asyncio.run(
        backend.generate_batch([GenerationRequest(prompt="greedy one")])
    )
    assert calls == {"spec": 1, "plain": 0}
    # A sampled request keeps the plain path.
    from llm_consensus_tpu.backends.base import SamplingParams

    asyncio.run(
        backend.generate_batch(
            [GenerationRequest(prompt="hot", params=SamplingParams(temperature=0.9))]
        )
    )
    assert calls == {"spec": 1, "plain": 1}

    # kv_quant engine with a draft: greedy requests must NOT reroute
    # (int8-KV greedy is a different numerics class).
    eng_q = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            max_new_tokens=4, seq_buckets=(16,), batch_buckets=(2,),
            kv_quant=True,
        ),
        draft=(cfg, init_params(cfg, jax.random.PRNGKey(7))),
    )
    spec_called = []
    monkeypatch.setattr(
        eng_q, "generate_texts_speculative",
        lambda *a, **k: spec_called.append(1),
    )
    asyncio.run(
        LocalBackend(eng_q).generate_batch(
            [GenerationRequest(prompt="greedy q")]
        )
    )
    assert not spec_called


def test_speculative_logprobs_match_plain_greedy():
    """Draft-path logprobs follow the plain greedy convention (close up
    to fp reassociation between the chunk and one-token programs)."""
    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0))
    ec = EngineConfig(max_new_tokens=5, seq_buckets=(16,), batch_buckets=(2,))
    plain = InferenceEngine(cfg, params, engine_config=ec)
    drafted = InferenceEngine(
        cfg, params, engine_config=ec, draft=(cfg, params)
    )
    prompts = ["alpha beta", "gamma"]
    want = plain.generate_texts(prompts)
    got = drafted.generate_texts_speculative(prompts)
    for w, g in zip(want, got):
        assert abs(w.logprob - g.logprob) < 1e-3
