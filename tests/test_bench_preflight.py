"""bench.py chip preflight (_await_chip subprocess probe loop).

The real failure modes (hung jax.devices(), UNAVAILABLE backend init)
were driven live against a down tunnel (round 5); these tests pin the
loop's budget/retry contract with stubbed probe bodies so the logic
stays testable offline.
"""

import bench


def test_await_chip_success_first_probe(monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_SRC", "pass")
    # Record (rather than time) the retry sleeps: wall-clock bounds are
    # flaky under xdist contention on the 1-core CI host.
    sleeps = []
    monkeypatch.setattr(bench.time, "sleep", sleeps.append)
    assert bench._await_chip(budget_s=600, probe_timeout_s=60) is True
    # Success on the first probe => no 45 s retry sleep. Patching the
    # global time.sleep also records subprocess's own Popen._wait poll
    # backoff (sub-0.05 s values) whenever a loaded box reaps the probe
    # child slowly — those are not retries and must not fail the test.
    assert 45.0 not in sleeps


def test_await_chip_budget_expires_on_failing_probe(monkeypatch):
    monkeypatch.setattr(
        bench, "_PROBE_SRC", "import sys; sys.exit(1)"
    )
    # Patch the retry sleep: on a fast machine the first probe can
    # finish inside the budget, which would otherwise hit the real
    # 45 s sleep before the deadline check fails the next attempt.
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench._await_chip(budget_s=0.5, probe_timeout_s=10) is False


def test_await_chip_retries_until_budget(monkeypatch, tmp_path):
    """A probe that fails once then succeeds: the loop sleeps and
    retries within budget. The probe flips state via a marker file."""
    marker = tmp_path / "flip"
    src = (
        "import pathlib, sys\n"
        f"m = pathlib.Path({str(marker)!r})\n"
        "if m.exists():\n"
        "    sys.exit(0)\n"
        "m.write_text('x')\n"
        "sys.exit(1)\n"
    )
    monkeypatch.setattr(bench, "_PROBE_SRC", src)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench._await_chip(budget_s=300, probe_timeout_s=10) is True
    assert marker.exists()


# ---------------------------------------------------------------------------
# Structured attempt reports + escalating backoff (PR 16)
# ---------------------------------------------------------------------------


def test_await_chip_attempts_record_success(monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_SRC", "pass")
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    attempts = []
    assert bench._await_chip(600, probe_timeout_s=60, attempts=attempts)
    assert attempts[-1]["phase"] == "probe"
    assert attempts[-1]["rc"] == 0
    assert attempts[-1]["elapsed"] >= 0


def test_await_chip_backoff_escalates_on_identical_failures(monkeypatch):
    """Two identical consecutive (phase, rc) failures climb one rung
    of _CHIP_BACKOFF_S: the sleep sequence runs 45, 90, 90, 180, ...
    and every attempt lands a structured record in ``attempts``."""
    monkeypatch.setattr(bench, "_PROBE_SRC", "import sys; sys.exit(7)")
    sleeps = []
    monkeypatch.setattr(bench.time, "sleep", sleeps.append)
    attempts = []
    assert (
        bench._await_chip(2.0, probe_timeout_s=30, attempts=attempts)
        is False
    )
    assert attempts and all(
        a == {"phase": "probe", "rc": 7, "elapsed": a["elapsed"]}
        for a in attempts
    )
    # Patching global time.sleep also records subprocess reaping polls;
    # only the backoff rungs count.
    rungs = [s for s in sleeps if s in bench._CHIP_BACKOFF_S]
    expected = [45.0, 90.0, 90.0, 180.0]
    assert rungs[: len(expected)] == expected[: len(rungs)]


def test_await_chip_timeout_phase_recorded(monkeypatch):
    monkeypatch.setattr(
        bench, "_PROBE_SRC", "import time; time.sleep(30)"
    )
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    attempts = []
    assert (
        bench._await_chip(1.0, probe_timeout_s=0.3, attempts=attempts)
        is False
    )
    assert attempts[0]["phase"] == "timeout"
    assert attempts[0]["rc"] is None
