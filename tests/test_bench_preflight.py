"""bench.py chip preflight (_await_chip subprocess probe loop).

The real failure modes (hung jax.devices(), UNAVAILABLE backend init)
were driven live against a down tunnel (round 5); these tests pin the
loop's budget/retry contract with stubbed probe bodies so the logic
stays testable offline.
"""

import bench


def test_await_chip_success_first_probe(monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_SRC", "pass")
    # Record (rather than time) the retry sleeps: wall-clock bounds are
    # flaky under xdist contention on the 1-core CI host.
    sleeps = []
    monkeypatch.setattr(bench.time, "sleep", sleeps.append)
    assert bench._await_chip(budget_s=600, probe_timeout_s=60) is True
    # Success on the first probe => no 45 s retry sleep. Patching the
    # global time.sleep also records subprocess's own Popen._wait poll
    # backoff (sub-0.05 s values) whenever a loaded box reaps the probe
    # child slowly — those are not retries and must not fail the test.
    assert 45.0 not in sleeps


def test_await_chip_budget_expires_on_failing_probe(monkeypatch):
    monkeypatch.setattr(
        bench, "_PROBE_SRC", "import sys; sys.exit(1)"
    )
    # Patch the retry sleep: on a fast machine the first probe can
    # finish inside the budget, which would otherwise hit the real
    # 45 s sleep before the deadline check fails the next attempt.
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench._await_chip(budget_s=0.5, probe_timeout_s=10) is False


def test_await_chip_retries_until_budget(monkeypatch, tmp_path):
    """A probe that fails once then succeeds: the loop sleeps and
    retries within budget. The probe flips state via a marker file."""
    marker = tmp_path / "flip"
    src = (
        "import pathlib, sys\n"
        f"m = pathlib.Path({str(marker)!r})\n"
        "if m.exists():\n"
        "    sys.exit(0)\n"
        "m.write_text('x')\n"
        "sys.exit(1)\n"
    )
    monkeypatch.setattr(bench, "_PROBE_SRC", src)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench._await_chip(budget_s=300, probe_timeout_s=10) is True
    assert marker.exists()


# ---------------------------------------------------------------------------
# Structured attempt reports + escalating backoff (PR 16)
# ---------------------------------------------------------------------------


def test_await_chip_attempts_record_success(monkeypatch):
    monkeypatch.setattr(bench, "_PROBE_SRC", "pass")
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    attempts = []
    assert bench._await_chip(600, probe_timeout_s=60, attempts=attempts)
    assert attempts[-1]["phase"] == "probe"
    assert attempts[-1]["rc"] == 0
    assert attempts[-1]["elapsed"] >= 0


def test_await_chip_backoff_escalates_on_identical_failures(monkeypatch):
    """EVERY further identical consecutive (phase, rc) failure climbs
    one rung of _CHIP_BACKOFF_S (45, 90, 180, 180, ... — PR 19's
    faster ladder), every attempt lands an enriched structured record,
    and after _CHIP_SAME_SIG_MAX identical failures the loop gives up
    EARLY with a terminal ``gave_up`` entry instead of burning the
    rest of the wait budget on a provably hard-down tunnel."""
    monkeypatch.setattr(bench, "_PROBE_SRC", "import sys; sys.exit(7)")
    sleeps = []
    monkeypatch.setattr(bench.time, "sleep", sleeps.append)
    attempts = []
    # Budget far beyond the probes' wall time: the identical-failure
    # cap, not the deadline, must terminate the loop.
    assert (
        bench._await_chip(3600.0, probe_timeout_s=30, attempts=attempts)
        is False
    )
    probes = [a for a in attempts if a["phase"] == "probe"]
    assert len(probes) == bench._CHIP_SAME_SIG_MAX
    for i, a in enumerate(probes):
        assert a["attempt"] == i + 1
        assert a["rc"] == 7
        assert a["elapsed"] >= 0 and a["t_offset"] >= 0
        assert "stderr" in a  # tail captured (empty for a bare exit)
    # Retried attempts record the backoff they slept.
    assert [a["sleep_s"] for a in probes if "sleep_s" in a] == [
        45.0,
        90.0,
        180.0,
        180.0,
    ]
    assert attempts[-1]["phase"] == "gave_up"
    assert attempts[-1]["rc"] == 7
    assert (
        attempts[-1]["identical_failures"] == bench._CHIP_SAME_SIG_MAX
    )
    # Patching global time.sleep also records subprocess reaping polls;
    # only the backoff rungs count.
    rungs = [s for s in sleeps if s in bench._CHIP_BACKOFF_S]
    assert rungs == [45.0, 90.0, 180.0, 180.0]


def test_await_chip_timeout_phase_recorded(monkeypatch):
    monkeypatch.setattr(
        bench, "_PROBE_SRC", "import time; time.sleep(30)"
    )
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    attempts = []
    assert (
        bench._await_chip(1.0, probe_timeout_s=0.3, attempts=attempts)
        is False
    )
    assert attempts[0]["phase"] == "timeout"
    assert attempts[0]["rc"] is None
