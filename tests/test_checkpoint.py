"""Checkpoint/resume tests (subsystem NOT PRESENT in the reference,
SURVEY.md §5 — its state dies with the process)."""

import jax
import jax.numpy as jnp
import numpy as np

from llm_consensus_tpu.checkpoint.io import (
    load_params,
    restore_train_state,
    save_params,
    save_train_state,
)
from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import init_params
from llm_consensus_tpu.training.train import (
    TrainConfig,
    init_train_state,
    make_train_step,
)


def test_params_roundtrip(tmp_path):
    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0))
    save_params(tmp_path / "ckpt", params)
    restored = load_params(tmp_path / "ckpt")
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params,
        restored,
    )


def test_train_state_resume_continues_identically(tmp_path):
    """Save mid-training, restore, and verify the next step is bit-equal
    to an uninterrupted run — true resume, not just param reload."""
    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tcfg = TrainConfig(warmup_steps=1, total_steps=20, remat=False)
    step = make_train_step(cfg, tcfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
    mask = jnp.ones((2, 8), jnp.float32)

    state = init_train_state(cfg, params, tcfg)
    state, _ = step(state, tokens, mask)
    save_train_state(tmp_path / "ckpt", state, extra={"data_pos": 123})

    # Uninterrupted continuation.
    cont_state, cont_loss = step(state, tokens, mask)

    # Resume from disk and take the same step.
    template = init_train_state(
        cfg, init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32), tcfg
    )
    restored, extra = restore_train_state(tmp_path / "ckpt", template)
    assert extra == {"data_pos": 123}
    assert int(restored.step) == 1
    resumed_state, resumed_loss = step(restored, tokens, mask)

    assert float(resumed_loss) == float(cont_loss)
    np.testing.assert_array_equal(
        np.asarray(resumed_state.params["norm_f"]),
        np.asarray(cont_state.params["norm_f"]),
    )


def test_quantized_params_roundtrip(tmp_path):
    """int8 and packed-int4 param trees (registered dataclass leaves)
    survive orbax save/restore and produce identical logits."""
    import numpy as np

    from llm_consensus_tpu.checkpoint.io import load_params, save_params
    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import forward, init_params
    from llm_consensus_tpu.ops.quant import quantize_params

    cfg = get_config("test-tiny")
    base = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
    for bits in (8, 4):
        qp = quantize_params(base, bits=bits)
        want = forward(cfg, qp, tokens)
        path = tmp_path / f"q{bits}"
        save_params(path, qp)
        back = load_params(path, target=qp)
        got = forward(cfg, back, tokens)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # The quantized leaf types survive (not silently densified).
        assert type(back["blocks"]["wq"]) is type(qp["blocks"]["wq"])
