"""CLI / REPL driver tests (reference L4, ``src/main.rs:428-471``)."""

import json

import pytest

from llm_consensus_tpu.cli import build_parser, main


def test_parser_defaults():
    args = build_parser().parse_args([])
    assert args.backend == "fake"
    assert args.max_rounds == 5  # reference hard-codes 5 (src/main.rs:299)
    assert args.question is None


def test_one_shot_question_fake_backend(capsys):
    rc = main(["--backend", "fake", "--question", "What is 2+2?", "--seed", "0"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "What is 2+2?" in out  # FakeBackend echoes the question


def test_panel_file_roundtrip(tmp_path, capsys):
    from llm_consensus_tpu.consensus.personas import default_panel, save_panel

    panel_file = tmp_path / "panel.json"
    save_panel(default_panel()[:2], panel_file)
    rc = main(
        ["--backend", "fake", "--panel", str(panel_file), "--question", "hi"]
    )
    assert rc == 0


def test_hf_checkpoint_backend(tmp_path, capsys):
    """--backend local --hf-checkpoint loads real HF weights end-to-end."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    config = transformers.LlamaConfig(
        vocab_size=384,  # >= ByteTokenizer's 259 ids
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    transformers.LlamaForCausalLM(config).save_pretrained(
        tmp_path, safe_serialization=True
    )
    rc = main(
        [
            "--backend",
            "local",
            "--hf-checkpoint",
            str(tmp_path),
            "--quant",
            "int8",
            "--question",
            "hi",
            "--max-new-tokens",
            "4",
            "--seed",
            "0",
        ]
    )
    assert rc == 0


def test_eval_requires_local_backend(capsys):
    rc = main(["--backend", "fake", "--eval-gsm8k", "synthetic"])
    assert rc == 2


def test_repl_loop_exit(monkeypatch, capsys):
    """REPL parity: prompts 'Enter a question: ', answers, 'exit' quits."""
    import asyncio
    import io

    from llm_consensus_tpu.backends.fake import FakeBackend
    from llm_consensus_tpu.cli import repl
    from llm_consensus_tpu.consensus.coordinator import (
        Coordinator,
        CoordinatorConfig,
    )
    from llm_consensus_tpu.consensus.personas import default_panel

    answers = iter(["What is up?\n", "exit\n"])
    monkeypatch.setattr(
        "sys.stdin", type("S", (), {"readline": lambda self: next(answers)})()
    )
    coord = Coordinator(
        default_panel(), FakeBackend(), CoordinatorConfig(seed=0)
    )
    asyncio.run(repl(coord))
    out = capsys.readouterr().out
    assert out.count("Enter a question: ") == 2
    assert "What is up?" in out


def test_eval_bundled_dataset_with_local_backend(capsys):
    """--eval-gsm8k bundled runs the harness on the packaged dataset
    through a (random-weight) local engine, emitting the JSON report."""
    import json

    from llm_consensus_tpu.cli import main

    rc = main(
        [
            "--backend", "local",
            "--model", "test-tiny",
            "--eval-gsm8k", "bundled",
            "--eval-n", "2",
            "--eval-limit", "2",
            "--max-new-tokens", "4",
        ]
    )
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["n_problems"] == 2
    assert report["n_candidates"] == 2


def test_eval_synthetic2_hard_task(capsys):
    """--eval-gsm8k synthetic2 runs the multi-step arith2 task through
    a (random-weight) local engine — CLI surface for the hard corpus."""
    import json

    from llm_consensus_tpu.cli import main

    rc = main(
        [
            "--backend", "local",
            "--model", "test-tiny",
            "--eval-gsm8k", "synthetic2",
            "--eval-n", "2",
            "--eval-limit", "2",
            "--max-new-tokens", "4",
        ]
    )
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["n_problems"] == 2
    assert report["n_candidates"] == 2


def test_cli_mesh_flag_shards_engine(capsys):
    """--mesh data=8 answers a one-shot question on a sharded engine."""
    from llm_consensus_tpu.cli import main

    rc = main(
        [
            "--backend", "local",
            "--model", "test-tiny",
            "--mesh", "data=8",
            "--question", "What is 2+2?",
            "--max-new-tokens", "4",
            "--max-rounds", "1",
            "--seed", "0",
        ]
    )
    assert rc == 0
    assert capsys.readouterr().out.strip()


def test_debate_mode_one_shot(capsys):
    from llm_consensus_tpu.cli import main

    rc = main(
        [
            "--backend", "local",
            "--model", "test-tiny",
            "--question", "What is 2+2?",
            "--debate", "4",
            "--max-rounds", "2",
            "--max-new-tokens", "4",
            "--seed", "0",
        ]
    )
    assert rc == 0
    assert capsys.readouterr().out.strip()


def test_debate_requires_local_and_question(capsys):
    from llm_consensus_tpu.cli import main

    assert main(["--debate", "4", "--question", "q"]) == 2  # fake backend
    assert main(["--backend", "local", "--model", "test-tiny", "--debate", "4"]) == 2


def test_debate_rejects_bad_n(capsys):
    from llm_consensus_tpu.cli import main

    rc = main([
        "--backend", "local", "--model", "test-tiny",
        "--question", "q", "--debate", "-1",
    ])
    assert rc == 2


def test_cli_stream_prints_completion(capsys):
    """--stream emits a single-model streamed completion."""
    from llm_consensus_tpu.cli import main

    rc = main(
        [
            "--backend", "local",
            "--model", "test-tiny",
            "--question", "hello there",
            "--stream",
            "--max-new-tokens", "6",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert out.endswith("\n")


def test_cli_stream_requires_local_backend():
    from llm_consensus_tpu.cli import main

    assert main(["--stream", "--question", "q"]) == 2
    assert main(["--backend", "local", "--model", "test-tiny", "--stream"]) == 2


def test_plan_capacity_command(capsys):
    """--plan prints a config-only HBM plan and exits 1 when the config
    cannot fit the budget (scripting-friendly capacity checks)."""
    import json

    from llm_consensus_tpu.cli import main

    rc = main(
        [
            "--plan", "--model", "mixtral-8x7b", "--plan-n", "64",
            "--plan-context", "256", "--max-new-tokens", "128",
            "--plan-mesh", "expert=4,model=2",
        ]
    )
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["fits"] is True
    assert out["params_gib"] > out["kv_cache_gib"] > 0

    rc = main(
        [
            "--plan", "--model", "mixtral-8x7b", "--plan-n", "64",
            "--plan-context", "256", "--max-new-tokens", "128",
        ]
    )
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["fits"] is False  # 44.7 GiB on one chip


def test_serve_parser_defaults_and_dispatch(monkeypatch):
    """`serve` owns its parser (shared backend flags, gateway knobs) and
    main() dispatches to it before the main parser sees the argv."""
    from llm_consensus_tpu import cli

    args = cli.build_serve_parser().parse_args([])
    assert args.backend == "fake"
    assert args.port == 8080
    assert args.queue_bound == 64
    assert args.max_inflight == 8
    assert args.default_deadline_s is None

    seen = {}

    def fake_run(argv):
        seen["argv"] = argv
        return 0

    monkeypatch.setattr(cli, "_run_serve", fake_run)
    assert main(["serve", "--port", "0"]) == 0
    assert seen["argv"] == ["--port", "0"]


def test_serve_subcommand_boots_and_drains_on_sigterm(tmp_path):
    """End-to-end `serve` process: ephemeral port, fake backend, one
    consensus request over HTTP, then SIGTERM -> graceful exit 0."""
    import json as _json
    import os
    import re
    import signal
    import subprocess
    import sys
    import time
    import urllib.request

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "llm_consensus_tpu", "serve", "--port", "0"],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        env=env,
    )
    try:
        # Read the boot log in a thread: a bare readline() would block
        # past the deadline if the process stays alive but never prints
        # the listening line, turning one quiet server into a whole
        # tier-1 gate timeout instead of a clean assertion here.
        import queue as _queue
        import threading

        lines: _queue.Queue = _queue.Queue()
        threading.Thread(
            target=lambda: [lines.put(ln) for ln in proc.stdout],
            daemon=True,
        ).start()
        port, deadline = None, time.time() + 60
        while port is None and time.time() < deadline:
            try:
                line = lines.get(timeout=1.0)
            except _queue.Empty:
                assert proc.poll() is None, "serve process died before binding"
                continue
            m = re.search(r"listening on 127\.0\.0\.1:(\d+)", line)
            if m:
                port = int(m.group(1))
        assert port is not None, "never saw the listening log line"
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/consensus",
            data=_json.dumps({"question": "What is 2+2?"}).encode(),
            headers={"Content-Type": "application/json"},
        )
        doc = _json.load(urllib.request.urlopen(req, timeout=30))
        assert doc["endorsed"] is True and doc["rounds"] >= 1
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.stdout.close()
