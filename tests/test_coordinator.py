"""Protocol tests driving the consensus state machine through unanimous,
split, round-cap, and stale-message paths (the test strategy the reference
lacks — SURVEY.md §4; behaviors cited to src/main.rs)."""

import asyncio

import pytest

from llm_consensus_tpu.backends.base import BackendError, GenerationRequest
from llm_consensus_tpu.backends.fake import FakeBackend, ScriptedBackend
from llm_consensus_tpu.consensus.coordinator import Coordinator, CoordinatorConfig
from llm_consensus_tpu.consensus.messages import (
    AnswerEvaluation,
    AnswerQuestion,
    Feedback,
)
from llm_consensus_tpu.consensus.personas import Persona, default_panel


def run(coro):
    return asyncio.run(coro)


def make_coordinator(backend, **cfg):
    cfg.setdefault("seed", 0)
    return Coordinator(default_panel(), backend, CoordinatorConfig(**cfg))


# ---------------------------------------------------------------------------
# Happy path: unanimous first round (reference src/main.rs:242-291)
# ---------------------------------------------------------------------------


def test_unanimous_first_round():
    backend = FakeBackend()
    coord = make_coordinator(backend)
    result = run(coord.run("What is 2+2?"))
    assert result.answer == "Echo: What is 2+2?"
    assert result.rounds == 1
    assert result.endorsed
    assert set(result.feedback) == {p.name for p in default_panel()}
    assert all(f is Feedback.GOOD for f in result.feedback.values())
    # 1 answer call + 4 evaluation calls.
    assert len(backend.calls) == 5


def test_author_also_evaluates_own_answer():
    # Quirk #2: the broadcast includes the author (src/main.rs:250).
    backend = FakeBackend()
    coord = make_coordinator(backend)
    result = run(coord.run("Q"))
    assert result.author in result.feedback


# ---------------------------------------------------------------------------
# Split vote -> refinement loop (reference src/main.rs:259-314)
# ---------------------------------------------------------------------------


def test_one_dissent_triggers_refinement_then_approval():
    state = {"round": 0}

    def evaluator(prompt):
        # Dissent in round 1 only.
        if state["round"] == 0:
            state["count"] = state.get("count", 0) + 1
            if state["count"] == 4:  # last judge of round 1 dissents
                state["round"] = 1
                return "NeedsRefinement\nNot detailed enough."
            if state["count"] == 1:
                return "NeedsRefinement\nToo terse."
        return "Good\nFine now."

    backend = FakeBackend(evaluator=evaluator)
    coord = make_coordinator(backend)
    result = run(coord.run("Q"))
    assert result.rounds == 2
    assert result.endorsed
    assert result.answer.startswith("Refined: ")


def test_refiner_is_a_dissenter():
    # Reference picks the refiner among NeedsRefinement voters only
    # (src/main.rs:268-272).
    dissenter = "The Technician"

    def evaluator(prompt):
        if "Technical Detail" in prompt and "Refined:" not in prompt:
            return "NeedsRefinement\nNeeds specifics."
        return "Good\nOk."

    backend = FakeBackend(evaluator=evaluator)
    coord = make_coordinator(backend)
    result = run(coord.run("Q"))
    assert result.endorsed
    refinements = [e for e in result.transcript if e.kind == "refinement"]
    assert len(refinements) == 1
    assert refinements[0].payload["author"] == dissenter


# ---------------------------------------------------------------------------
# Round cap (reference src/main.rs:293-314; quirk #5)
# ---------------------------------------------------------------------------


def test_round_cap_forces_termination_unendorsed():
    backend = FakeBackend(evaluator=lambda p: "NeedsRefinement\nNever satisfied.")
    coord = make_coordinator(backend, max_rounds=5)
    result = run(coord.run("Q"))
    # evaluation_count: 1 initial + 4 re-evals = 5, then one final
    # refinement is force-approved without re-evaluation.
    assert result.rounds == 5
    assert not result.endorsed  # the forced approval is surfaced, not hidden
    assert coord.answer_ready()  # readiness gate still opens (parity)
    assert all(f is Feedback.GOOD for f in result.feedback.values())
    # Calls: 1 answer + 5 rounds x 4 evals + 5 refinements = 26.
    assert len(backend.calls) == 26


def test_round_cap_configurable():
    # The reference hard-codes 5 with a TODO (src/main.rs:299-300).
    backend = FakeBackend(evaluator=lambda p: "NeedsRefinement\nNope.")
    coord = make_coordinator(backend, max_rounds=2)
    result = run(coord.run("Q"))
    assert result.rounds == 2
    assert not result.endorsed


def test_malformed_verdict_counts_as_dissent():
    # Quirk #4: garbage verdict == NeedsRefinement, drives a refinement.
    calls = {"n": 0}

    def evaluator(prompt):
        calls["n"] += 1
        if calls["n"] == 2:
            return "Absolutely fantastic!"
        return "Good\nOk."

    backend = FakeBackend(evaluator=evaluator)
    coord = make_coordinator(backend)
    result = run(coord.run("Q"))
    assert result.rounds == 2
    assert result.endorsed


# ---------------------------------------------------------------------------
# Epoch/round staleness (the reference race, SURVEY.md §5 quirk #6 — fixed)
# ---------------------------------------------------------------------------


def test_stale_evaluation_from_previous_round_dropped():
    coord = make_coordinator(FakeBackend())
    coord.current_question = "Q"
    coord.on_answer(AnswerQuestion(answer="A", author="High Society", epoch=0))
    assert coord.evaluation_count == 1
    # A verdict tagged with round 0 (before the answer) must be dropped.
    out = coord.on_evaluation(
        AnswerEvaluation(
            name="Art Boy",
            evaluation=Feedback.NEEDS_REFINEMENT,
            epoch=0,
            round=0,
        )
    )
    assert out is None
    assert "Art Boy" not in coord.feedback


def test_stale_epoch_after_reset_dropped():
    coord = make_coordinator(FakeBackend())
    coord.current_question = "Q"
    coord.on_answer(AnswerQuestion(answer="A", author="Art Boy", epoch=0))
    coord.reset()
    out = coord.on_evaluation(
        AnswerEvaluation(name="Art Boy", evaluation=Feedback.GOOD, epoch=0, round=1)
    )
    assert out is None
    assert coord.feedback == {}


def test_duplicate_persona_names_rejected():
    # The reference silently clobbers duplicates (src/main.rs:214).
    p = default_panel()
    with pytest.raises(ValueError):
        Coordinator(p + [p[0]], FakeBackend())


# ---------------------------------------------------------------------------
# Readiness / GetAnswer parity (reference src/main.rs:316-336)
# ---------------------------------------------------------------------------


def test_get_answer_error_string_when_not_ready():
    coord = make_coordinator(FakeBackend())
    assert coord.get_answer() == (
        "System error: Requested answer when answer was not ready."
    )
    assert not coord.answer_ready()


def test_wait_for_answer_while_run_in_flight():
    # Regression: run() must not destroy the background-task handle that
    # ask_question holds — wait_for_answer after a yield must still await
    # the in-flight run, and a second ask_question must be rejected.
    async def go():
        coord = make_coordinator(FakeBackend(latency=0.05))
        assert await coord.ask_question("Q1")
        await asyncio.sleep(0.01)  # let run() start and reset state
        assert not await coord.ask_question("Q2")  # still busy
        answer = await coord.wait_for_answer()
        assert answer == "Echo: Q1"

    run(go())


def test_stale_refinement_from_previous_round_dropped():
    # on_refinement must check the round tag too: a delayed refinement from
    # round k arriving during round k+1 is dropped.
    from llm_consensus_tpu.consensus.messages import AnswerRefinement

    coord = make_coordinator(FakeBackend())
    coord.current_question = "Q"
    coord.on_answer(AnswerQuestion(answer="A", author="Art Boy", epoch=0))
    assert coord.evaluation_count == 1
    stale = coord.on_refinement(
        AnswerRefinement(answer="OLD", author="Art Boy", epoch=0, round=0)
    )
    assert stale == []
    assert coord.answer == "A"  # not clobbered


def test_repl_parity_ask_then_wait():
    async def go():
        coord = make_coordinator(FakeBackend())
        accepted = await coord.ask_question("Q")
        assert accepted
        answer = await coord.wait_for_answer()
        assert answer == "Echo: Q"
        assert coord.answer_ready()
        coord.reset()
        assert not coord.answer_ready()

    run(go())


# ---------------------------------------------------------------------------
# Failure detection (NOT PRESENT in reference — SURVEY.md §5)
# ---------------------------------------------------------------------------


class FailingBackend(FakeBackend):
    def __init__(self, fail_times: int, **kw):
        super().__init__(**kw)
        self.fail_times = fail_times

    async def generate_batch(self, requests):
        if self.fail_times > 0:
            self.fail_times -= 1
            raise BackendError("injected fault")
        return await super().generate_batch(requests)


def test_proposer_failure_retried_then_succeeds():
    backend = FailingBackend(fail_times=1)
    coord = make_coordinator(backend, retries=2)
    result = run(coord.run("Q"))
    assert result.answer == "Echo: Q"


def test_proposer_permanent_failure_raises():
    backend = FailingBackend(fail_times=99)
    coord = make_coordinator(backend, retries=1)
    with pytest.raises(BackendError):
        run(coord.run("Q"))


def test_evaluation_failure_degrades_to_dissent():
    # Answer call succeeds; first evaluation batch fails twice (exhausting
    # retries), degrading all verdicts to NeedsRefinement -> refinement round.
    class EvalFailBackend(FakeBackend):
        def __init__(self):
            super().__init__()
            self.eval_failures = 2

        async def generate_batch(self, requests):
            from llm_consensus_tpu.backends.fake import classify_prompt

            if (
                self.eval_failures > 0
                and classify_prompt(requests[0].prompt) == "evaluate"
            ):
                self.eval_failures -= 1
                raise BackendError("eval fault")
            return await super().generate_batch(requests)

    coord = make_coordinator(EvalFailBackend(), retries=1)
    result = run(coord.run("Q"))
    assert result.endorsed
    assert result.rounds == 2  # degraded round forced one refinement


# ---------------------------------------------------------------------------
# Heterogeneous panels (BASELINE.md config[3])
# ---------------------------------------------------------------------------


def test_per_persona_backend_routing():
    default_b = FakeBackend()
    tech_b = FakeBackend(evaluator=lambda p: "Good\nTech ok.")
    panel = default_panel()
    coord = Coordinator(
        panel,
        default_b,
        CoordinatorConfig(seed=0),
        backends={"The Technician": tech_b},
    )
    result = run(coord.run("Q"))
    assert result.endorsed
    # The Technician's evaluation went to its own backend.
    assert any("Technical Detail" in c for c in tech_b.calls)
    assert not any("Technical Detail" in c for c in default_b.calls if "evaluate" in c)


def test_scripted_backend_exact_trace():
    script = [
        "The answer is 4.",  # proposer
        "Good\nok",
        "Good\nok",
        "Good\nok",
        "Good\nok",  # 4 judges
    ]
    backend = ScriptedBackend(script)
    coord = make_coordinator(backend)
    result = run(coord.run("What is 2+2?"))
    assert result.answer == "The answer is 4."
    assert backend.script == []
