"""Training data pipeline tests (native and numpy fallback paths)."""

import numpy as np
import pytest

from llm_consensus_tpu.training.data import TokenBatchLoader, write_token_shard


@pytest.fixture()
def shard(tmp_path):
    path = tmp_path / "tokens.bin"
    write_token_shard(path, np.arange(4096, dtype=np.int32))
    return path


@pytest.mark.parametrize("prefer_native", [True, False])
def test_loader_batches(shard, prefer_native):
    ld = TokenBatchLoader(shard, batch=4, seq=32, seed=1, prefer_native=prefer_native)
    assert ld.n_tokens == 4096
    toks, mask = ld.next()
    assert toks.shape == (4, 32) and toks.dtype == np.int32
    assert mask.shape == (4, 32) and (mask == 1.0).all()
    assert (np.diff(toks, axis=1) == 1).all()  # contiguous windows
    ld.close()


def test_loader_iteration_and_missing(tmp_path, shard):
    ld = TokenBatchLoader(shard, batch=2, seq=16, prefer_native=False)
    it = iter(ld)
    a, _ = next(it)
    b, _ = next(it)
    assert a.shape == b.shape == (2, 16)
    with pytest.raises((FileNotFoundError, ValueError)):
        TokenBatchLoader(tmp_path / "missing.bin", batch=1, seq=8)


def test_loader_feeds_train_step(shard):
    import jax

    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params
    from llm_consensus_tpu.training.train import (
        TrainConfig,
        init_train_state,
        make_train_step,
    )

    cfg = get_config("test-tiny")
    tcfg = TrainConfig(warmup_steps=1, total_steps=4, remat=False)
    state = init_train_state(cfg, init_params(cfg, jax.random.PRNGKey(0)), tcfg)
    step = make_train_step(cfg, tcfg)
    ld = TokenBatchLoader(shard, batch=2, seq=16, seed=0)
    for _ in range(2):
        toks, mask = ld.next()
        toks = toks % cfg.vocab_size
        state, loss = step(state, toks, mask)
    assert int(state.step) == 2
    assert np.isfinite(float(loss))
    ld.close()


def test_sft_loader_mask_and_resume():
    """SftBatchLoader: completion-only masks with causal_lm_loss's
    one-position shift (mask[i]=1 iff tokens[i+1] is a completion
    token), pad fill, and the position/seek resume contract."""
    from llm_consensus_tpu.training.data import SftBatchLoader

    # prompt [5,6,7], completion [8,9]: predictors of 8,9 sit at
    # positions 2,3 -> mask exactly there.
    ex = [([5, 6, 7], [8, 9]), ([1, 2], [3])]
    ld = SftBatchLoader(ex, batch=4, seq=8, seed=7, pad_id=0)
    toks, mask = ld.next()
    assert toks.shape == (4, 8) and mask.shape == (4, 8)
    for r in range(4):
        row = toks[r].tolist()
        if row[:5] == [5, 6, 7, 8, 9]:
            assert mask[r].tolist() == [0, 0, 1, 1, 0, 0, 0, 0]
            assert row[5:] == [0, 0, 0]
        else:
            assert row[:3] == [1, 2, 3]
            assert mask[r].tolist() == [0, 1, 0, 0, 0, 0, 0, 0]

    # Same-seed loader seeked to position k reproduces batch k exactly.
    ld2 = SftBatchLoader(ex, batch=4, seq=8, seed=7, pad_id=0)
    b1 = ld.next()  # batch index 1
    ld2.seek(1)
    b2 = ld2.next()
    np.testing.assert_array_equal(b1[0], b2[0])
    assert ld.position == ld2.position == 2


def test_sft_loader_drops_truncated_completions():
    from llm_consensus_tpu.training.data import SftBatchLoader

    # First example's completion falls entirely past seq -> dropped.
    ld = SftBatchLoader(
        [([1] * 8, [2, 3]), ([1, 2], [3])], batch=2, seq=8, seed=0
    )
    assert ld.n_examples == 1
    import pytest

    with pytest.raises(ValueError):
        SftBatchLoader([([1] * 8, [2])], batch=1, seq=8)
