"""Debate / ToT multi-round re-vote tests (BASELINE.md config[4])."""

import jax
import pytest

from llm_consensus_tpu.consensus.debate import (
    DebateConfig,
    run_debate,
)


class FakeEngine:
    """Scripted generate_texts: returns per-round canned answers."""

    def __init__(self, rounds):
        self.rounds = list(rounds)
        self.calls = []

    def generate_texts(self, prompts, temperatures=None, seed=0, max_new_tokens=None):
        self.calls.append(list(prompts))
        answers = self.rounds.pop(0)
        assert len(answers) == len(prompts)

        class R:
            def __init__(self, t):
                self.text = t
                self.num_tokens = max(len(t.split()), 1)
                self.logprob = -1.0

        return [R(a) for a in answers]


def test_debate_quorum_early_exit():
    eng = FakeEngine([["answer 7"] * 3 + ["answer 9"]])  # 3/4 = quorum
    res = run_debate(
        eng, "What?", DebateConfig(n_candidates=4, max_rounds=3, quorum=0.75)
    )
    assert res.n_rounds == 1  # early exit, rounds 2-3 never run
    assert res.vote.winner == "7"
    assert res.answer == "answer 7"
    assert len(eng.calls) == 1


def test_debate_runs_to_cap_without_quorum():
    split = ["1", "2", "3", "4"]  # never converges
    eng = FakeEngine([split, split, split])
    res = run_debate(
        eng, "Q", DebateConfig(n_candidates=4, max_rounds=3, quorum=0.75)
    )
    assert res.n_rounds == 3
    assert len(eng.calls) == 3
    assert res.total_tokens == 12  # 1 token per answer x 4 x 3


def test_debate_revision_prompts_carry_peers():
    eng = FakeEngine([["A", "B", "C", "D"], ["B", "B", "B", "B"]])
    res = run_debate(
        eng, "The question", DebateConfig(n_candidates=4, max_rounds=2)
    )
    assert res.n_rounds == 2
    revise_prompts = eng.calls[1]
    # Candidate 0's revision prompt contains its own answer and a peer's.
    assert "The question" in revise_prompts[0]
    assert "A" in revise_prompts[0]
    assert any(p in revise_prompts[0] for p in ("B", "C", "D"))
    assert res.vote.winner == "b"  # unanimity after revision


def test_panel_debate_weighted_majority_and_cross_model_peers():
    """run_panel_debate: a heavy member's answer wins the weighted vote
    even when outnumbered, and revision prompts show candidates peers
    from OTHER members' answer pools."""
    from llm_consensus_tpu.consensus.debate import run_panel_debate

    strong = FakeEngine([["X", "X"], ["X", "X"]])
    weak = FakeEngine([["Y", "Y"], ["Y", "Y"]])
    res = run_panel_debate(
        {"strong": (strong, 3.0), "weak": (weak, 1.0)},
        "The question",
        DebateConfig(n_candidates=2, max_rounds=2, quorum=0.9),
    )
    # Weighted tally: X = 2*3 = 6, Y = 2*1 = 2 -> X wins; 6/8 < 0.9
    # quorum so a second round runs.
    assert res.n_rounds == 2
    assert res.vote.winner == "x"
    assert res.total_tokens == 8  # 1 token x 2 cand x 2 members x 2 rounds
    # The weak member's round-2 prompts carry the strong member's answer.
    assert any("X" in p for p in weak.calls[1])
    assert all("The question" in p for p in weak.calls[1])


def test_panel_debate_quorum_is_headcount_not_weighted():
    """A single heavy member must not end the debate unilaterally: the
    weighted tally picks the WINNER, but the quorum early-exit measures
    headcount agreement (the run_debate invariant)."""
    from llm_consensus_tpu.consensus.debate import run_panel_debate

    heavy = FakeEngine([["A", "A"], ["A", "A"]])
    light = FakeEngine([["B", "B"], ["B", "B"]])
    res = run_panel_debate(
        {"heavy": (heavy, 9.0), "light": (light, 1.0)},
        "Q",
        DebateConfig(n_candidates=2, max_rounds=2, quorum=0.75),
    )
    # Weighted lead 18/20 = 0.9 >= quorum, but headcount is 2/4 = 0.5:
    # the revision round must still run.
    assert res.n_rounds == 2
    assert res.vote.winner == "a"  # weighted vote still picks A


def test_panel_debate_quorum_early_exit_and_method_guard():
    from llm_consensus_tpu.consensus.debate import run_panel_debate

    a = FakeEngine([["7", "7"]])
    b = FakeEngine([["7", "7"]])
    res = run_panel_debate(
        {"a": (a, 1.0), "b": (b, 2.0)},
        "Q",
        DebateConfig(n_candidates=2, max_rounds=3, quorum=0.75),
    )
    assert res.n_rounds == 1  # unanimity -> early exit
    assert len(a.calls) == 1 and len(b.calls) == 1
    with pytest.raises(ValueError, match="weighted majority"):
        run_panel_debate(
            {"a": (a, 1.0)}, "Q", DebateConfig(method="logit_pool")
        )
    with pytest.raises(ValueError, match="at least one"):
        run_panel_debate({}, "Q", DebateConfig())
    with pytest.raises(ValueError, match="max_rounds"):
        run_panel_debate(
            {"a": (a, 1.0)}, "Q", DebateConfig(max_rounds=0)
        )
    with pytest.raises(ValueError, match="max_rounds"):
        run_debate(FakeEngine([]), "Q", DebateConfig(max_rounds=0))


def test_debate_on_real_tiny_engine():
    from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params

    cfg = get_config("test-tiny")
    eng = InferenceEngine(
        cfg,
        init_params(cfg, jax.random.PRNGKey(0)),
        engine_config=EngineConfig(
            max_new_tokens=4, seq_buckets=(64, 128), batch_buckets=(4,)
        ),
    )
    res = run_debate(
        eng,
        "2+2?",
        DebateConfig(n_candidates=4, max_rounds=2, temperature=1.5),
    )
    assert 1 <= res.n_rounds <= 2
    assert isinstance(res.answer, str)
    assert res.total_tokens >= 4


def test_debate_vote_methods():
    """logit_pool and rescore vote methods run end to end; unknown
    methods are rejected."""
    import jax
    import pytest

    from llm_consensus_tpu.consensus.debate import DebateConfig, run_debate
    from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params

    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            max_new_tokens=6, seq_buckets=(64, 128, 256),
            batch_buckets=(1, 2, 4),
        ),
    )
    for method in ("logit_pool", "rescore"):
        res = run_debate(
            eng, "What is 2+2?",
            DebateConfig(
                n_candidates=2, max_rounds=1, max_new_tokens=6,
                method=method,
            ),
        )
        assert res.n_rounds == 1
        assert isinstance(res.answer, str)
    with pytest.raises(ValueError, match="unknown debate vote method"):
        run_debate(
            eng, "q",
            DebateConfig(n_candidates=2, max_rounds=1, method="nope"),
        )


def test_debate_quorum_uses_headcount_not_pooled_mass():
    """With logit_pool voting, a split panel must still run revision
    rounds — the early exit measures headcount, not pooled mass."""
    from llm_consensus_tpu.consensus.debate import DebateConfig, run_debate
    from llm_consensus_tpu.engine.engine import EngineResult

    class SplitEngine:
        """Half the panel answers 4, half answers 5, with very different
        logprobs (pooled mass would be one-hot)."""

        def __init__(self):
            self.calls = 0

        def generate_texts(self, prompts, temperatures=None, seed=0,
                           max_new_tokens=None, sampler=None):
            self.calls += 1
            out = []
            for i in range(len(prompts)):
                ans = "#### 4" if i % 2 == 0 else "#### 5"
                lp = -1.0 if i % 2 == 0 else -20.0
                out.append(EngineResult(
                    text=ans, num_tokens=3, logprob=lp, token_ids=[1, 2, 3]
                ))
            return out

    eng = SplitEngine()
    res = run_debate(
        eng, "2+2?",
        DebateConfig(n_candidates=4, max_rounds=3, method="logit_pool",
                     quorum=0.75),
    )
    assert eng.calls == 3  # 50/50 headcount never reaches quorum
    assert res.n_rounds == 3


def test_debate_validates_before_generating():
    from llm_consensus_tpu.consensus.debate import DebateConfig, run_debate

    class ExplodingEngine:
        mesh = None

        def generate_texts(self, *a, **k):
            raise AssertionError("must not generate")

    with pytest.raises(ValueError, match="unknown debate vote method"):
        run_debate(ExplodingEngine(), "q", DebateConfig(method="typo"))

    # No score_texts at all (e.g. a serving backend adapter).
    with pytest.raises(ValueError, match="score_texts"):
        run_debate(ExplodingEngine(), "q", DebateConfig(method="rescore"))

    class MeshEngine(ExplodingEngine):
        # Sharded engines are first-class for rescore now (score_texts
        # shards completions over `data`): validation must PASS and the
        # debate proceed to generation.
        mesh = object()

        def score_texts(self, *a, **k):
            raise AssertionError("must not score")

    with pytest.raises(AssertionError, match="must not generate"):
        run_debate(MeshEngine(), "q", DebateConfig(method="rescore"))


def test_debate_custom_templates_used():
    """DebateConfig.initial_template/revise_template override the
    built-in CoT prompts (narrow SFT models answer reliably only in
    their trained format) — every round's prompts must use them."""
    from llm_consensus_tpu.consensus.debate import DebateConfig, run_debate

    seen: list[str] = []

    class Echo:
        mesh = None

        def generate_texts(self, prompts, temperatures=None, seed=0,
                           max_new_tokens=None):
            from llm_consensus_tpu.engine.engine import EngineResult

            seen.extend(prompts)
            # Disagreeing numeric answers: quorum never met -> the
            # debate must take all rounds through the revise template.
            return [
                EngineResult(text=f"#### {i}", num_tokens=2,
                             logprob=-1.0, token_ids=[])
                for i in range(len(prompts))
            ]

    cfg = DebateConfig(
        n_candidates=4, max_rounds=2, quorum=1.0,
        initial_template="MYFMT Q={q} A:",
        revise_template="REVISE Q={q} MINE={own}",
    )
    res = run_debate(Echo(), "what?", cfg)
    assert res.n_rounds == 2
    assert seen[0] == "MYFMT Q=what? A:"
    assert seen[4].startswith("REVISE Q=what? MINE=")
    assert all("panel debate" not in p for p in seen)  # builtin unused


def test_debate_bad_templates_fail_fast():
    """Template problems must surface BEFORE any generation (the same
    fail-fast invariant as the method checks)."""
    from llm_consensus_tpu.consensus.debate import DebateConfig, run_debate

    class Exploding:
        mesh = None

        def generate_texts(self, *a, **k):
            raise AssertionError("must not generate")

    with pytest.raises(ValueError, match="template"):
        run_debate(  # typo'd revise placeholder
            Exploding(), "q",
            DebateConfig(revise_template="Revise {peer}: {own}"),
        )
    with pytest.raises(ValueError, match="embed the question"):
        run_debate(  # initial template drops {q}
            Exploding(), "q", DebateConfig(initial_template="Answer:")
        )
    with pytest.raises(ValueError, match="template"):
        run_debate(  # literal JSON brace, unescaped
            Exploding(), "q",
            DebateConfig(initial_template='{"answer": {q}}'),
        )
