"""Debate / ToT multi-round re-vote tests (BASELINE.md config[4])."""

import jax
import pytest

from llm_consensus_tpu.consensus.debate import (
    DebateConfig,
    run_debate,
)


class FakeEngine:
    """Scripted generate_texts: returns per-round canned answers."""

    def __init__(self, rounds):
        self.rounds = list(rounds)
        self.calls = []

    def generate_texts(self, prompts, temperatures=None, seed=0, max_new_tokens=None):
        self.calls.append(list(prompts))
        answers = self.rounds.pop(0)
        assert len(answers) == len(prompts)

        class R:
            def __init__(self, t):
                self.text = t
                self.num_tokens = max(len(t.split()), 1)
                self.logprob = -1.0

        return [R(a) for a in answers]


def test_debate_quorum_early_exit():
    eng = FakeEngine([["answer 7"] * 3 + ["answer 9"]])  # 3/4 = quorum
    res = run_debate(
        eng, "What?", DebateConfig(n_candidates=4, max_rounds=3, quorum=0.75)
    )
    assert res.n_rounds == 1  # early exit, rounds 2-3 never run
    assert res.vote.winner == "7"
    assert res.answer == "answer 7"
    assert len(eng.calls) == 1


def test_debate_runs_to_cap_without_quorum():
    split = ["1", "2", "3", "4"]  # never converges
    eng = FakeEngine([split, split, split])
    res = run_debate(
        eng, "Q", DebateConfig(n_candidates=4, max_rounds=3, quorum=0.75)
    )
    assert res.n_rounds == 3
    assert len(eng.calls) == 3
    assert res.total_tokens == 12  # 1 token per answer x 4 x 3


def test_debate_revision_prompts_carry_peers():
    eng = FakeEngine([["A", "B", "C", "D"], ["B", "B", "B", "B"]])
    res = run_debate(
        eng, "The question", DebateConfig(n_candidates=4, max_rounds=2)
    )
    assert res.n_rounds == 2
    revise_prompts = eng.calls[1]
    # Candidate 0's revision prompt contains its own answer and a peer's.
    assert "The question" in revise_prompts[0]
    assert "A" in revise_prompts[0]
    assert any(p in revise_prompts[0] for p in ("B", "C", "D"))
    assert res.vote.winner == "b"  # unanimity after revision


def test_debate_on_real_tiny_engine():
    from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
    from llm_consensus_tpu.models.configs import get_config
    from llm_consensus_tpu.models.transformer import init_params

    cfg = get_config("test-tiny")
    eng = InferenceEngine(
        cfg,
        init_params(cfg, jax.random.PRNGKey(0)),
        engine_config=EngineConfig(
            max_new_tokens=4, seq_buckets=(64, 128), batch_buckets=(4,)
        ),
    )
    res = run_debate(
        eng,
        "2+2?",
        DebateConfig(n_candidates=4, max_rounds=2, temperature=1.5),
    )
    assert 1 <= res.n_rounds <= 2
    assert isinstance(res.answer, str)
    assert res.total_tokens >= 4
