"""Pipelined decode dispatch (PR 6).

The continuous batcher's host loop is a software pipeline: program n+1
is enqueued before program n's tokens are fetched, fed from the
device-resident token output of the previous dispatch. These tests pin
the acceptance contract — ``pipeline_depth=2`` (the default) serves
byte-identical text to the serialized ``pipeline_depth=1`` baseline
across the hard shapes (multi-token string stops mid-chunk, staggered
retirement shrinking a decode group, eviction + host-tier restore with
programs in flight, concurrent same-prefix bursts), the PRNG stream is
chunk- and depth-invariant, the flush/inflight metrics stay in lockstep
with ``stats()``, and a wedged in-flight fetch still goes stale on the
liveness heartbeat.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import init_params
from llm_consensus_tpu.serving.continuous import (
    ContinuousBatcher,
    ContinuousConfig,
)

CFG = get_config("test-tiny")

_HEADER = "Panel shared header for every persona, forty ch: "  # 49 chars
_CCFG = dict(
    max_slots=4,
    page_size=16,
    n_pages=64,
    pages_per_seq=8,
    max_new_tokens=8,
    seq_buckets=(16, 32, 64),
    prefill_chunk=16,
    share_prefix=True,
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def _serve(batcher, prompts, **kw):
    futs = [batcher.submit(p, **kw) for p in prompts]
    return [f.result(timeout=120) for f in futs]


def _run_depth(params, depth, prompts, cfgkw=None, submit_kw=None, cfg=CFG):
    b = ContinuousBatcher(
        cfg,
        params,
        config=ContinuousConfig(**(cfgkw or _CCFG), pipeline_depth=depth),
    )
    try:
        outs = _serve(b, prompts, **(submit_kw or {}))
        return outs, b.stats()
    finally:
        b.close()


# ---------------------------------------------------------------------------
# Parity: the hard retirement shapes, depth 2 vs the serialized baseline
# ---------------------------------------------------------------------------


def test_string_stop_mid_chunk_parity(params):
    """Multi-token string stop landing mid-chunk: retirement lags one
    pipeline stage AND up to steps_per_sync-1 tokens — the post-stop
    tokens decoded in flight must be discarded with the exact depth-1
    stop-trim semantics (text cut at the stop, honest num_tokens)."""
    cfgkw = dict(_CCFG, steps_per_sync=4, max_new_tokens=16)
    prompts = [_HEADER + "stop probe"]
    # Derive a stop the tiny random model actually emits: a 2-char
    # substring from the middle of the baseline's output (random
    # weights make a fixed stop string unhittable).
    [free], _ = _run_depth(params, 1, prompts, cfgkw)
    assert len(free.text) >= 4
    mid = len(free.text) // 2
    stop = free.text[mid : mid + 2]
    kw = dict(stop=[stop])
    [want], _ = _run_depth(params, 1, prompts, cfgkw, kw)
    [got], _ = _run_depth(params, 2, prompts, cfgkw, kw)
    assert stop not in want.text  # the baseline really trimmed
    assert len(want.text) < len(free.text)
    assert (got.text, got.num_tokens) == (want.text, want.num_tokens)


def test_staggered_retirement_shrinks_group_parity(params):
    """Same-prefix panel whose members retire at different steps (the
    decode group shrinks while programs are in flight): every text and
    token count identical to the serialized loop."""
    prompts = [_HEADER + f"persona {i} answers" for i in range(4)]
    caps = [2, 9, 5, 13]

    def run(depth):
        b = ContinuousBatcher(
            CFG,
            params,
            config=ContinuousConfig(
                **dict(_CCFG, max_new_tokens=16),
                pipeline_depth=depth,
            ),
        )
        try:
            futs = [
                b.submit(p, max_new_tokens=c) for p, c in zip(prompts, caps)
            ]
            return [(f.result(timeout=120).text,
                     f.result(timeout=120).num_tokens) for f in futs]
        finally:
            b.close()

    assert run(2) == run(1)


def test_concurrent_same_prefix_burst_parity(params):
    """The panel shape submitted all at once: admissions dedup against
    the first request's in-flight prefill WHILE decode programs are in
    flight — text and sharing counters identical to depth 1."""
    prompts = [_HEADER + f"Q{i}: what is {i}+{i}?" for i in range(6)]
    want, st1 = _run_depth(params, 1, prompts)
    got, st2 = _run_depth(params, 2, prompts)
    assert [r.text for r in got] == [r.text for r in want]
    assert st2["prefix_pages_shared"] == st1["prefix_pages_shared"]
    assert st2["prefix_hits"] == st1["prefix_hits"]
    # All pages come home afterwards at either depth.
    assert st2["free_pages"] == st2["total_pages"]


def test_eviction_and_host_restore_during_flight_parity(params):
    """PR 4's hardest shape under the pipeline: a starved pool forces
    eviction (demote to host tier) while decode programs are in
    flight, and the re-vote round restores pages — restore flushes the
    pipeline (metered) and the text stays byte-identical to depth 1."""
    from llm_consensus_tpu.server.metrics import PIPELINE_FLUSHES

    kw = dict(
        max_slots=2,
        page_size=16,
        n_pages=13,  # 12 usable vs a 2x6-page unshared working set
        pages_per_seq=8,
        max_new_tokens=6,
        seq_buckets=(16, 32, 64),
        prefill_chunk=16,
        share_prefix=True,
        host_cache_bytes=8 << 20,
    )
    rounds = [
        [_HEADER + f"p{i} proposes" for i in range(2)],
        [f"{i} unique filler storm with plenty of padding text {i}"
         for i in range(4)],
        [_HEADER + f"r{i} re-votes" for i in range(2)],
    ]

    def run(depth):
        b = ContinuousBatcher(
            CFG, params,
            config=ContinuousConfig(**kw, pipeline_depth=depth),
        )
        try:
            texts = []
            for burst in rounds:
                texts.append([r.text for r in _serve(b, burst)])
            return texts, b.stats()
        finally:
            b.close()

    want, st1 = run(1)
    before = PIPELINE_FLUSHES.value
    got, st2 = run(2)
    assert got == want
    assert st2["offload_restored_pages"] >= 1  # the tier really engaged
    assert st2["offload_restored_pages"] == st1["offload_restored_pages"]
    # Restores are stable-cache operations: each drained the pipeline
    # when programs were in flight, and the Prometheus family moved by
    # exactly the batcher's own count (lockstep).
    assert PIPELINE_FLUSHES.value - before == st2["pipeline_flushes"]


# ---------------------------------------------------------------------------
# PRNG stream: chunk-size x depth invariance (greedy AND sampled)
# ---------------------------------------------------------------------------


def test_prng_stream_chunk_and_depth_invariant(params):
    """The per-token PRNG stream is (seed, index) — independent of how
    many steps ride one program (steps_per_sync) AND how many programs
    ride in flight (pipeline_depth)."""

    def run(sync, depth):
        b = ContinuousBatcher(
            CFG,
            params,
            config=ContinuousConfig(
                **dict(_CCFG, steps_per_sync=sync),
                pipeline_depth=depth,
            ),
        )
        try:
            futs = [
                b.submit("hello world"),
                b.submit("the quick", temperature=0.9, seed=7),
                b.submit("abc", temperature=1.3, seed=11, top_k=4),
            ]
            return [f.result(timeout=120).text for f in futs]
        finally:
            b.close()

    want = run(1, 1)
    assert all(
        run(sync, depth) == want
        for sync, depth in ((1, 2), (4, 1), (4, 2), (1, 3))
    )


# ---------------------------------------------------------------------------
# Page-overshoot budget: exact-fit tables absorb depth*chunk-1 tokens
# ---------------------------------------------------------------------------


def test_overshoot_budget_tight_pages(params):
    """A config whose pages_per_seq is sized EXACTLY for the deepest
    overshoot (bucket + max_new + depth*chunk - 1): rows that finish at
    the first token of a chunk keep writing through the in-flight
    programs without escaping their reservation — completion, parity,
    and a clean pool prove the budget holds."""
    kw = dict(
        max_slots=2,
        page_size=16,
        n_pages=16,
        pages_per_seq=2,  # ceil((16 + 8 + 2*4 - 1) / 16) = 2
        max_new_tokens=8,
        seq_buckets=(16,),
        steps_per_sync=4,
        prefill_chunk=16,
        share_prefix=False,
    )
    prompts = ["hi", "yo"]

    def run(depth):
        b = ContinuousBatcher(
            CFG, params, config=ContinuousConfig(**kw, pipeline_depth=depth)
        )
        try:
            outs = _serve(b, prompts, max_new_tokens=8)
            st = b.stats()
            return [r.text for r in outs], st
        finally:
            b.close()

    want, _ = run(1)
    got, st = run(2)
    assert got == want
    assert st["free_pages"] == st["total_pages"]


# ---------------------------------------------------------------------------
# Metrics: inflight gauge / flush counter surfaces
# ---------------------------------------------------------------------------


def test_pipeline_metrics_exported_and_lockstep(params):
    """gateway_dispatch_inflight and gateway_pipeline_flushes_total are
    declared on the process registry and mirrored in stats(); the dense
    (prefill_chunk=0) path flushes per admission that lands while
    programs are in flight."""
    from llm_consensus_tpu.server.metrics import (
        DISPATCH_INFLIGHT,
        PIPELINE_FLUSHES,
        REGISTRY,
    )

    before = PIPELINE_FLUSHES.value
    b = ContinuousBatcher(
        CFG,
        params,
        config=ContinuousConfig(
            **dict(
                _CCFG, prefill_chunk=0, share_prefix=False,
                max_new_tokens=128, pages_per_seq=12,
            ),
            pipeline_depth=2,
        ),
    )
    try:
        first = b.submit("a long-running request", max_new_tokens=128)
        # Wait until the first request is decoding with a program in
        # flight, then admit a second: its dense prefill MUST flush.
        deadline = time.time() + 60
        while b.stats()["decode_steps"] < 2 and time.time() < deadline:
            time.sleep(0.01)
        second = b.submit("late arrival", max_new_tokens=4)
        second.result(timeout=120)
        first.result(timeout=120)
        # Futures resolve DURING fetch bookkeeping; the loop drains the
        # remaining in-flight program(s) on its next ticks.
        deadline = time.time() + 30
        while b.stats()["dispatch_inflight"] and time.time() < deadline:
            time.sleep(0.01)
        st = b.stats()
    finally:
        b.close()
    assert st["pipeline_flushes"] >= 1
    assert PIPELINE_FLUSHES.value - before == st["pipeline_flushes"]
    assert st["dispatch_inflight"] == 0  # drained at rest
    text = REGISTRY.render()
    assert "gateway_pipeline_flushes_total" in text
    assert "gateway_dispatch_inflight" in text


def test_sched_overhead_observes_overlapped_dispatches(params):
    """Depth 2 keeps the overhead histogram count-comparable to depth
    1 — one observation per dispatch after the first — but overlapped
    dispatches observe ~0 (the un-overlapped-host-time semantics)."""
    from llm_consensus_tpu.server.metrics import SCHED_OVERHEAD_SECONDS

    h0 = (SCHED_OVERHEAD_SECONDS.count, SCHED_OVERHEAD_SECONDS.sum)
    s0 = None
    b = ContinuousBatcher(
        CFG, params, config=ContinuousConfig(**_CCFG, pipeline_depth=2)
    )
    try:
        s0 = b.stats()
        b.submit("overlap probe", max_new_tokens=8).result(timeout=120)
        st = b.stats()
    finally:
        b.close()
    d_cnt = st["sched_overhead_seconds_count"] - s0["sched_overhead_seconds_count"]
    assert d_cnt >= 1
    # stats() and the process histogram moved together.
    assert SCHED_OVERHEAD_SECONDS.count - h0[0] == d_cnt
    assert SCHED_OVERHEAD_SECONDS.sum - h0[1] == pytest.approx(
        st["sched_overhead_seconds_sum"] - s0["sched_overhead_seconds_sum"]
    )


# ---------------------------------------------------------------------------
# Liveness: a wedged in-flight fetch goes stale on the heartbeat
# ---------------------------------------------------------------------------


def test_wedged_inflight_fetch_flips_heartbeat(params):
    """The acceptance bullet: a wedged in-flight program (the fetch
    never returns) stalls the loop tick, which is exactly what the
    gateway's /readyz stall threshold watches."""
    b = ContinuousBatcher(
        CFG,
        params,
        config=ContinuousConfig(
            **dict(
                _CCFG, prefill_chunk=0, share_prefix=False,
                max_new_tokens=256, pages_per_seq=20,
            ),
            pipeline_depth=2,
        ),
    )
    try:
        fut = b.submit("wedge probe", max_new_tokens=256)
        deadline = time.time() + 60
        while b.stats()["decode_steps"] < 1 and time.time() < deadline:
            time.sleep(0.01)
        # Wedge: the instance attribute shadows the bound method, so
        # every fetch of an in-flight program now hangs 1.5 s.
        b._fetch_one = lambda: time.sleep(1.5)
        try:
            stale = False
            for _ in range(40):
                if b.heartbeat()["last_tick_age_s"] > 1.0:
                    stale = True
                    break
                time.sleep(0.1)
            assert stale, "wedged fetch never stalled the heartbeat"
        finally:
            del b._fetch_one
        # Recovery: the real fetch path drains and the request finishes.
        assert fut.result(timeout=120).num_tokens == 256
        assert b.heartbeat()["alive"] is True
    finally:
        b.close()
