"""Multi-round on-device decode (PR 12).

Contract layers:

- STEP MASKING: ``decode_step_paged(write_mask=...)`` freezes a row —
  no K/V lands in its real pages, its length holds — while neighbors
  step normally.
- STOP MACHINERY: ``utils.stops.derived_stop_screen`` yields a bounded
  conservative candidate set (or None when none exists), and
  ``single_token_stop_ids`` is the engine's shared exact-terminator
  derivation.
- BATCHER: with ``decode_rounds`` R > 1, ONE device program runs up to
  R decode rounds (stop scan + sampling + emit/length bookkeeping on
  device; early-exit masking) and the host fetches once per window —
  text BYTE-IDENTICAL to R = 1 across pipeline depths, prefill-chunk
  widths, staggered panel retirement, stop tokens and max-tokens
  budgets landing mid-window (with no K/V written past the stop),
  eviction + host-tier restores with multi-round programs in flight,
  speculation composed and flipped live, and sampled (PRNG-addressed)
  rows — plus metrics/flight lockstep and the bench A/B leg.
"""

import subprocess
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.engine.tokenizer import ByteTokenizer
from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.paged_cache import NULL_PAGE, PagedKVCache
from llm_consensus_tpu.models.transformer import (
    decode_step_paged,
    init_params,
)
from llm_consensus_tpu.serving.continuous import (
    ContinuousBatcher,
    ContinuousConfig,
)
from llm_consensus_tpu.utils.stops import (
    derived_stop_screen,
    single_token_stop_ids,
)

CFG = get_config("test-tiny")

_HEADER = "Panel shared header for every persona, forty ch: "

_CCFG = dict(
    max_slots=4,
    page_size=16,
    n_pages=96,
    pages_per_seq=10,
    max_new_tokens=8,
    seq_buckets=(16, 32, 64),
    prefill_chunk=16,
    share_prefix=True,
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def _serve(batcher, prompts, **kw):
    futs = [batcher.submit(p, **kw) for p in prompts]
    return [f.result(timeout=180) for f in futs]


def _quiesce(batcher, timeout=20.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        s = batcher.stats()
        if (
            s["active_slots"] == 0
            and s["prefilling_slots"] == 0
            and s["dispatch_inflight"] == 0
            and s["waiting"] == 0
        ):
            return s
        time.sleep(0.01)
    raise AssertionError(f"batcher did not quiesce: {batcher.stats()}")


def _burst(params, rounds, prompts, cfgkw=None, submit_kw=None):
    b = ContinuousBatcher(
        CFG,
        params,
        config=ContinuousConfig(
            **(cfgkw or _CCFG), decode_rounds=rounds
        ),
    )
    try:
        outs = _serve(b, prompts, **(submit_kw or {}))
        _quiesce(b)
        return [(o.text, o.num_tokens) for o in outs], b.stats()
    finally:
        b.close()


def _real_page_writes(batcher):
    """Set of non-NULL (page, offset) positions holding any K/V, and
    the full non-NULL planes — the KV footprint assertions compare
    these between R values (the NULL page is the sanctioned garbage
    sink for inactive and frozen rows and is excluded)."""
    k = np.asarray(batcher.cache.k)
    v = np.asarray(batcher.cache.v)
    nz = (np.abs(k[:, 1:]).sum(axis=(0, 3, 4)) > 0) | (
        np.abs(v[:, 1:]).sum(axis=(0, 3, 4)) > 0
    )
    return nz, k[:, 1:], v[:, 1:]


# ---------------------------------------------------------------------------
# Step masking (models/transformer.py)
# ---------------------------------------------------------------------------


def test_write_mask_freezes_row(params):
    """A frozen row's real pages and length are untouched by a masked
    decode step; live rows write and advance exactly as unmasked."""
    cache = PagedKVCache.create(CFG, n_pages=8, page_size=4, max_seqs=2,
                                pages_per_seq=2)
    table = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    cache = PagedKVCache(
        k=cache.k, v=cache.v, page_table=table,
        length=jnp.asarray([2, 3], jnp.int32),
    )
    toks = jnp.asarray([[5], [6]], jnp.int32)
    mask = jnp.asarray([True, False])
    _, out = decode_step_paged(CFG, params, toks, cache, write_mask=mask)
    assert out.length.tolist() == [3, 3]  # row 1 frozen
    k = np.asarray(out.k)
    # Row 0 wrote position 2 -> page 1 offset 2; row 1's would-be write
    # (page 3 offset 3) was redirected to the NULL page.
    assert np.abs(k[:, 1, 2]).sum() > 0
    assert np.abs(k[:, 3, 3]).sum() == 0
    assert np.abs(k[:, NULL_PAGE, 3]).sum() > 0


# ---------------------------------------------------------------------------
# Derived-stop machinery (utils/stops.py)
# ---------------------------------------------------------------------------


def test_derived_stop_screen_byte_tokenizer():
    tok = ByteTokenizer()
    assert derived_stop_screen(tok, ()) == ()
    scr = derived_stop_screen(tok, ("ab",))
    assert scr is not None
    # The completing byte's id must be screened (conservatively).
    (b_id,) = tok.encode("b", add_bos=False)
    assert b_id in scr
    # The non-final byte's id need not be.
    (a_id,) = tok.encode("a", add_bos=False)
    assert a_id not in scr
    # Ids that decode to nothing alone (specials) stay screened: their
    # contribution is invisible to the per-id byte check.
    assert all(tok.decode([i]) == "" or i == b_id for i in scr)


def test_derived_stop_screen_bounds():
    tok = ByteTokenizer()
    # Many distinct final bytes blow the max_ids cap -> None (the
    # batcher then bounds the window to 1 round).
    many = tuple("stop" + c for c in "abcdefghij")
    assert derived_stop_screen(tok, many, max_ids=8) is None

    class _Huge:
        vocab_size = 1 << 20

    assert derived_stop_screen(_Huge(), ("x",)) is None


def test_single_token_stop_ids_shared_with_engine():
    tok = ByteTokenizer()
    assert single_token_stop_ids(tok, ("a",)) == tuple(
        tok.encode("a", add_bos=False)
    )
    # Multi-token stops are not exact device terminators.
    assert single_token_stop_ids(tok, ("ab",)) == ()
    from llm_consensus_tpu.engine.engine import InferenceEngine

    assert InferenceEngine._stop_ids.__doc__  # the engine shares it


# ---------------------------------------------------------------------------
# Byte parity: R x depth x chunk grid over a staggered panel
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("chunk", [16, 32])
def test_parity_grid(params, chunk):
    """R in {1, 2, 4} x depth in {1, 2}: the shared-prefix panel with
    STAGGERED caps (members retire at different windows from the
    lagged mirror, shrinking the decode group mid-flight) serves
    byte-identical text and token counts everywhere."""
    prompts = [_HEADER + f"persona {i} answers" for i in range(4)]
    caps = [2, 7, 5, 8]
    cfgkw = dict(_CCFG, prefill_chunk=chunk)

    def run(rounds, depth):
        b = ContinuousBatcher(
            CFG,
            params,
            config=ContinuousConfig(
                **cfgkw, decode_rounds=rounds, pipeline_depth=depth
            ),
        )
        try:
            futs = [
                b.submit(p, max_new_tokens=c)
                for p, c in zip(prompts, caps)
            ]
            return [
                (f.result(timeout=180).text,
                 f.result(timeout=180).num_tokens)
                for f in futs
            ]
        finally:
            b.close()

    want = run(1, 1)
    for rounds in (2, 4):
        for depth in (1, 2):
            assert run(rounds, depth) == want, (rounds, depth)


def test_prng_count_invariance_sampled(params):
    """Sampled rows: per-request streams are (seed, output-index)
    addressed, and a frozen row folds nothing — so the emitted token
    sequence is R-invariant even at temperature > 0."""
    prompts = [_HEADER + f"sampled {i}" for i in range(4)]
    kw = dict(temperature=0.9, seed=11, top_k=7)
    want, _ = _burst(params, 1, prompts, submit_kw=kw)
    got, _ = _burst(params, 4, prompts, submit_kw=kw)
    assert got == want


# ---------------------------------------------------------------------------
# Early-exit masking: stop / max-tokens mid-window, no KV past the stop
# ---------------------------------------------------------------------------


def _footprint_run(params, rounds, submit_kw):
    b = ContinuousBatcher(
        CFG,
        params,
        config=ContinuousConfig(
            **dict(_CCFG, max_new_tokens=16),
            decode_rounds=rounds,
            pipeline_depth=1,
        ),
    )
    try:
        [out] = _serve(b, [_HEADER + "stop probe"], **submit_kw)
        _quiesce(b)
        nz, k, v = _real_page_writes(b)
        return (out.text, out.num_tokens), nz, k, v
    finally:
        b.close()


def test_stop_token_mid_window_freezes_and_writes_no_kv(params):
    """A stop sequence hit inside an R=4 window: the row freezes on
    device (conservative screen + host byte confirm), the text is
    byte-identical to R=1, and the REAL-page KV footprint — positions
    and values — is exactly the R=1 footprint: nothing written past
    the stop."""
    (free, _), _, _, _ = _footprint_run(params, 1, {})
    assert len(free) >= 4
    mid = len(free) // 2
    stop = free[mid : mid + 2]
    want, nz1, k1, v1 = _footprint_run(params, 1, dict(stop=[stop]))
    got, nz4, k4, v4 = _footprint_run(params, 4, dict(stop=[stop]))
    assert got == want
    assert want[1] < 16  # the stop really ended decoding early
    assert np.array_equal(nz1, nz4)
    assert np.array_equal(k1, k4) and np.array_equal(v1, v4)


def test_max_tokens_mid_window_freezes_and_writes_no_kv(params):
    """max_new_tokens reached mid-window: same contract as a stop —
    identical text and identical real-page KV writes vs R=1 (the
    budget check is exact on device at depth 1)."""
    want, nz1, k1, v1 = _footprint_run(
        params, 1, dict(max_new_tokens=3)
    )
    got, nz4, k4, v4 = _footprint_run(
        params, 4, dict(max_new_tokens=3)
    )
    assert got == want and want[1] == 3
    assert np.array_equal(nz1, nz4)
    assert np.array_equal(k1, k4) and np.array_equal(v1, v4)


def test_unscreenable_stop_bounds_window_to_one_round(params):
    """A request whose stops admit no bounded screen collapses every
    window it rides to ONE round (host-checked cadence) — and text
    parity holds regardless."""
    prompts = [_HEADER + "unscreenable"]
    stop = ("\x7fnever-hit\x7f",)

    def run(rounds):
        b = ContinuousBatcher(
            CFG,
            params,
            config=ContinuousConfig(**_CCFG, decode_rounds=rounds),
        )
        try:
            # Poison the memoized screen: stand-in for a tokenizer
            # whose vocabulary admits no bounded candidate set.
            b._screen_cache[stop] = None
            outs = _serve(b, prompts, stop=list(stop))
            s = _quiesce(b)
            return [(o.text, o.num_tokens) for o in outs], s
        finally:
            b.close()

    want, _ = run(1)
    got, st = run(4)
    assert got == want
    # Every decode-advancing window the row rode collapsed to 1 round.
    assert st["decode_rounds_count"] > 0
    assert st["decode_rounds_sum"] == st["decode_rounds_count"]


def test_screen_cache_bounded(params):
    """The derived-screen memo is evict-oldest bounded: stop tuples
    are client-supplied, so per-request-unique stops must not grow a
    long-running batcher without bound."""
    from llm_consensus_tpu.serving import continuous as C

    b = ContinuousBatcher(CFG, params, config=ContinuousConfig(**_CCFG))
    try:
        for i in range(C._SCREEN_CACHE_MAX):
            b._screen_cache[(f"synthetic-{i}",)] = ()
        b.submit(
            _HEADER + "cache probe", max_new_tokens=2, stop=["zz"]
        ).result(timeout=120)
        assert len(b._screen_cache) <= C._SCREEN_CACHE_MAX
        assert ("zz",) in b._screen_cache
        assert ("synthetic-0",) not in b._screen_cache  # oldest evicted
    finally:
        b.close()


# ---------------------------------------------------------------------------
# Interactions: host-tier round trip, speculation, live flips
# ---------------------------------------------------------------------------


def test_eviction_and_host_restore_with_rounds_in_flight(params):
    """Demote/restore under multi-round windows: the panel's header
    pages round-trip through the host tier while R=4 programs are in
    flight, with text parity and the same restore count as R=1."""
    cfgkw = dict(
        max_slots=2,
        page_size=16,
        n_pages=17,  # 16 usable vs a 2x8-page unshared working set
        pages_per_seq=10,
        max_new_tokens=6,
        seq_buckets=(16, 32, 64),
        prefill_chunk=16,
        share_prefix=True,
        host_cache_bytes=8 << 20,
    )
    rounds_bursts = [
        [_HEADER + f"p{i} proposes" for i in range(2)],
        [
            f"{i} unique filler storm with plenty of padding text {i}"
            for i in range(4)
        ],
        [_HEADER + f"r{i} re-votes" for i in range(2)],
    ]

    def run(rounds):
        b = ContinuousBatcher(
            CFG,
            params,
            config=ContinuousConfig(**cfgkw, decode_rounds=rounds),
        )
        try:
            texts = []
            for burst in rounds_bursts:
                texts.append([x.text for x in _serve(b, burst)])
            return texts, b.stats()
        finally:
            b.close()

    want, st1 = run(1)
    got, st4 = run(4)
    assert got == want
    assert st4["offload_restored_pages"] >= 1
    assert st4["offload_restored_pages"] == st1["offload_restored_pages"]


def test_spec_compose_and_live_flips(params):
    """decode_rounds and spec decode configured together: spec windows
    keep one verify round per dispatch, plain windows run R rounds,
    and live spec_decode flips drain the pipeline between modes —
    text identical to the no-draft R=1 baseline in every phase."""
    prompts = [_HEADER + f"member {i}" for i in range(4)]
    base = dict(_CCFG, n_pages=128, pages_per_seq=12)
    want, _ = _burst(params, 1, prompts, cfgkw=base)

    b = ContinuousBatcher(
        CFG,
        params,
        config=ContinuousConfig(**base, spec_k=3, decode_rounds=4),
        draft=(CFG, params),  # self-draft: high acceptance
    )
    try:
        for spec_on in (True, False, True):
            b.config.spec_decode = spec_on
            outs = _serve(b, prompts)
            assert [(o.text, o.num_tokens) for o in outs] == want, spec_on
        s = _quiesce(b)
    finally:
        b.close()
    # Both program families ran; every decode-advancing program
    # observed its rounds (spec = 1 per verify round).
    assert s["device_programs_spec"] > 0
    assert s["device_programs_decode"] > 0
    assert s["decode_rounds_count"] == (
        s["device_programs_spec"]
        + s["device_programs_decode"]
        + s["device_programs_fused"]
    )


def test_rounds_do_not_engage_with_steps_per_sync(params):
    """steps_per_sync > 1 keeps the legacy unmasked chunk (the tunnel
    RTT knob); decode_rounds stays dormant — parity and the legacy
    rounds-per-program accounting (k per chunk program)."""
    prompts = [_HEADER + f"legacy {i}" for i in range(2)]
    want, _ = _burst(params, 1, prompts)
    cfgkw = dict(_CCFG, steps_per_sync=4)
    got, st = _burst(params, 4, prompts, cfgkw=cfgkw)
    assert got == want
    b = ContinuousBatcher(
        CFG,
        params,
        config=ContinuousConfig(**cfgkw, decode_rounds=4),
    )
    try:
        assert b._rounds == 1
    finally:
        b.close()


# ---------------------------------------------------------------------------
# Metrics + flight lockstep
# ---------------------------------------------------------------------------


def test_rounds_metrics_prometheus_stats_lockstep(params):
    """gateway_device_rounds_total / gateway_decode_rounds_per_program
    move by the batcher's own stats() deltas — one site, two
    surfaces."""
    from llm_consensus_tpu.server.metrics import (
        DECODE_ROUNDS_PER_PROGRAM,
        DEVICE_ROUNDS,
    )

    before = (
        DEVICE_ROUNDS.value,
        DECODE_ROUNDS_PER_PROGRAM.count,
        DECODE_ROUNDS_PER_PROGRAM.sum,
    )
    _, st = _burst(
        params, 4, [_HEADER + f"lockstep {i}" for i in range(3)]
    )
    assert DEVICE_ROUNDS.value - before[0] == st["device_rounds_total"]
    assert (
        DECODE_ROUNDS_PER_PROGRAM.count - before[1]
        == st["decode_rounds_count"]
    )
    assert DECODE_ROUNDS_PER_PROGRAM.sum - before[2] == pytest.approx(
        st["decode_rounds_sum"]
    )
    # The cross-checks the bench leg gates: a round emits at most one
    # token per row, and a window folds up to R rounds per program.
    assert st["device_rounds_total"] >= st["decode_rounds_count"]
    assert st["decode_rounds_sum"] <= 4 * st["decode_rounds_count"]


def test_flight_program_events_carry_rounds_and_stay_count_exact(params):
    """PROGRAM flight events for multi-round programs carry ``rounds``
    in meta, and the Chrome device track still holds exactly the
    programs gateway_device_programs_total counted at R > 1."""
    import json

    from llm_consensus_tpu.server.metrics import REGISTRY
    from llm_consensus_tpu.serving import flight

    def programs_total():
        return sum(
            v
            for k, v in REGISTRY.snapshot().items()
            if k.startswith("gateway_device_programs_total")
        )

    b = ContinuousBatcher(
        CFG, params, config=ContinuousConfig(**_CCFG, decode_rounds=4)
    )
    try:
        _serve(b, [_HEADER + "warm"], max_new_tokens=4)
        _quiesce(b)
        flight.flight_recorder().clear()
        before = programs_total()
        _serve(b, [_HEADER + f"flight {i}" for i in range(3)])
        _quiesce(b)
        delta = programs_total() - before
    finally:
        b.close()
    evs = flight.flight_recorder().events()
    prog = [e for e in evs if e.kind == "program"]
    assert len(prog) == delta > 0
    dec = [e for e in prog if e.meta.get("kind") in ("decode", "fused")]
    assert dec and all("rounds" in e.meta for e in dec)
    assert any(e.meta["rounds"] == 4 for e in dec)
    doc = json.loads(json.dumps(flight.to_chrome(evs)))
    dev = [
        e
        for e in doc["traceEvents"]
        if e.get("cat") == "device" and e["ph"] == "X"
    ]
    # Count-exact at R > 1: one slice still means one program; its
    # ``rounds`` arg says how much decoding it held.
    assert len(dev) == delta
    assert any(e["args"].get("rounds") == 4 for e in dev)


# ---------------------------------------------------------------------------
# Bench A/B leg (subprocess)
# ---------------------------------------------------------------------------


def test_bench_serve_decode_rounds_cpu_ab_leg():
    """The CPU-run A/B leg (acceptance): R=1/R=4 byte-identical text
    through one batcher, device programs per generated token dropping
    >= 3x at R=4, rc 0, explicit status in the JSON line."""
    r = subprocess.run(
        [
            sys.executable, "bench.py", "--tiny", "--cpu",
            "--serve-decode-rounds", "--serve-requests", "6",
            "--serve-slots", "3", "--new-tokens", "48",
            "--prompt-len", "96", "--serve-prefill-chunk", "64",
            "--rounds-ab-rounds", "1",
        ],
        cwd=Path(__file__).resolve().parent.parent,
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "programs/token" in r.stdout
    assert "text unchanged=True" in r.stdout
    assert '"status": "ok"' in r.stdout
