"""Disaggregated prefill/decode serving (PR 16).

Covers the role split (:mod:`llm_consensus_tpu.serving.disagg`) and the
remote page-store transport (:mod:`~.serving.remote_store`) end to end:
role resolution and the prefill config specialization, the
length-prefixed wire protocol round-trips pages bit-exactly over a real
TCP socket, every remote-store failure mode (server down at
construction, mid-put disconnect, slow peer hitting the client timeout)
degrades to a local miss with a warning and a
``gateway_remote_store_errors_total`` bump rather than wedging, a roled
fleet over the remote store streams byte-identical text versus a
mixed-role control with >= 1 prefill->decode handoff and ZERO header
pages re-prefilled on the decode side, a roled fleet over a DEAD store
still completes every request with a fresh heartbeat, the controller's
restore-batch knob follows the overhead EWMA, the gateway's
``/debug/chains`` probe and cross-host peer forwarding route by
residency, and the ``bench.py --serve-disagg`` CPU leg gates the whole
stack in a subprocess.
"""

import json
import logging
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.backends.fake import FakeBackend
from llm_consensus_tpu.engine.tokenizer import ByteTokenizer
from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import init_params
from llm_consensus_tpu.server.admission import AdmissionConfig
from llm_consensus_tpu.server.client import GatewayClient, GatewayHTTPError
from llm_consensus_tpu.server.gateway import (
    Gateway,
    GatewayConfig,
    GatewayThread,
)
from llm_consensus_tpu.server.metrics import REGISTRY, MetricsRegistry
from llm_consensus_tpu.serving import flight
from llm_consensus_tpu.serving.continuous import ContinuousConfig
from llm_consensus_tpu.serving.control import (
    KNOBS,
    AdaptiveController,
    ControlConfig,
)
from llm_consensus_tpu.serving.disagg import (
    ROLES,
    HandoffCoordinator,
    resolve_roles,
    role_config,
)
from llm_consensus_tpu.serving.fleet import (
    FleetConfig,
    ReplicaSet,
)
from llm_consensus_tpu.serving.offload import HostPageStore
from llm_consensus_tpu.serving.remote_store import (
    PageStoreServer,
    RemotePageStore,
    parse_endpoint,
)

CFG = get_config("test-tiny")

# 49 chars -> 3 full 16-token pages + a tail at page_size 16.
_HEADER = "Panel shared header for every persona, forty ch: "

_FCFG = dict(
    max_slots=2,
    page_size=16,
    n_pages=32,
    pages_per_seq=8,
    max_new_tokens=4,
    seq_buckets=(16, 32, 64),
    prefill_chunk=16,
    share_prefix=True,
    host_cache_bytes=64 << 20,
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)


def _serve(target, prompts, **kw):
    futs = [target.submit(p, **kw) for p in prompts]
    return [f.result(timeout=300) for f in futs]


def _dead_endpoint():
    """A (host, port) nothing listens on: bind, read the port, close."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return ("127.0.0.1", port)


def _errors_total() -> float:
    return REGISTRY.get("gateway_remote_store_errors_total").value


# ---------------------------------------------------------------------------
# Role resolution and the prefill config specialization (units)
# ---------------------------------------------------------------------------


def test_resolve_roles_broadcast_and_tuple():
    assert resolve_roles("mixed", 3) == ("mixed",) * 3
    assert resolve_roles("decode", 1) == ("decode",)
    assert resolve_roles(("prefill", "decode"), 2) == ("prefill", "decode")
    assert resolve_roles(["prefill", "mixed"], 2) == ("prefill", "mixed")


def test_resolve_roles_rejects_bad_splits():
    with pytest.raises(ValueError, match="2 entries for 3"):
        resolve_roles(("prefill", "decode"), 3)
    with pytest.raises(ValueError, match="unknown replica role"):
        resolve_roles(("prefill", "verifier"), 2)
    # A prefill-only fleet can never stream a token.
    with pytest.raises(ValueError, match="decode-capable"):
        resolve_roles("prefill", 2)
    with pytest.raises(ValueError, match="decode-capable"):
        resolve_roles(("prefill", "prefill"), 2)
    assert "mixed" in ROLES and "prefill" in ROLES and "decode" in ROLES


def test_role_config_prefill_copy_vs_shared_instance():
    base = ContinuousConfig(
        **{**_FCFG, "spec_decode": True, "spec_k": 2, "decode_rounds": 2}
    )
    pre = role_config(base, "prefill")
    assert pre is not base
    assert pre.spec_decode is False and pre.decode_rounds == 1
    # Everything outside the decode-phase machinery is untouched, so
    # the PR-14 store-key scope (which excludes both fields) matches.
    assert pre.page_size == base.page_size
    assert pre.prefill_chunk == base.prefill_chunk
    # Decode/mixed replicas SHARE the live config instance — the
    # fleet-wide knob-flip lever must keep working.
    assert role_config(base, "decode") is base
    assert role_config(base, "mixed") is base


def test_parse_endpoint_forms():
    assert parse_endpoint("tcp://10.0.0.7:9000") == ("tcp", ("10.0.0.7", 9000))
    assert parse_endpoint("10.0.0.7:9000") == ("tcp", ("10.0.0.7", 9000))
    assert parse_endpoint(("127.0.0.1", 123)) == ("tcp", ("127.0.0.1", 123))
    assert parse_endpoint("uds:///tmp/pages.sock") == ("uds", "/tmp/pages.sock")
    assert parse_endpoint("/tmp/pages.sock") == ("uds", "/tmp/pages.sock")


# ---------------------------------------------------------------------------
# Remote store: wire round-trip (real TCP socket)
# ---------------------------------------------------------------------------


def test_remote_store_round_trip_preserves_planes():
    store = HostPageStore(budget_bytes=64 << 20)
    server = PageStoreServer(store)
    server.start()
    rtt0 = REGISTRY.get("gateway_remote_store_rtt_seconds").count
    client = RemotePageStore(server.endpoint, timeout_s=5.0)
    try:
        key = ("chain", 0, 7, 42)
        import ml_dtypes

        planes = (
            np.arange(512, dtype=np.int8).reshape(4, 128),
            np.full((4, 1), 0.5, dtype=np.float32),
            # The KV pool's real dtype: bfloat16 must survive the wire
            # (its ``.str`` form is an opaque void code jax rejects).
            np.arange(64, dtype=np.float32)
            .astype(ml_dtypes.bfloat16)
            .reshape(8, 8),
        )
        resident, demoted, dropped = client.put_counted(key, planes)
        assert resident is True and demoted == 1 and dropped == 0
        assert key in store  # landed in the AUTHORITATIVE store
        assert key in client
        got = client.get(key)
        assert got is not None and len(got) == len(planes)
        for a, b in zip(planes, got):
            assert b.dtype == a.dtype and b.shape == a.shape
            assert np.array_equal(a, b)
        assert client.touch(key) is True
        assert client.touch(("missing",)) is False
        # Piggybacked stats mirror the authoritative store.
        assert len(client) == 1
        assert client.bytes_used == store.bytes_used > 0
        assert client.headroom_bytes == store.headroom_bytes
        assert client.errors == 0
        # Prometheus lockstep: the bytes gauge carries the last
        # exchange's piggybacked view, the RTT histogram observed
        # every successful exchange.
        assert (
            REGISTRY.get("gateway_remote_store_bytes").value
            == store.bytes_used
        )
        assert REGISTRY.get("gateway_remote_store_rtt_seconds").count > rtt0
    finally:
        client.close()
        server.close()


# ---------------------------------------------------------------------------
# Remote store: failure modes degrade to local miss (satellite 3)
# ---------------------------------------------------------------------------


def test_remote_store_server_down_at_construction(caplog):
    e0 = _errors_total()
    with caplog.at_level(
        logging.WARNING, logger="llm_consensus_tpu.serving.remote_store"
    ):
        client = RemotePageStore(
            _dead_endpoint(), timeout_s=0.5, retry_s=0.05
        )
        planes = (np.zeros((2, 4), dtype=np.int8),)
        assert client.put_counted(("k",), planes) == (False, 0, 1)
        assert client.put(("k",), planes) is False
        assert client.get(("k",)) is None
        assert ("k",) not in client
        assert client.touch(("k",)) is False
    # Every failed op counts; reads of the cached stats cost nothing.
    assert client.errors >= 1
    assert _errors_total() - e0 >= client.errors >= 2
    assert client.headroom_bytes == 0  # outage reads as zero headroom
    warned = [
        r
        for r in caplog.records
        if "degrading to local miss" in r.getMessage()
    ]
    # Warn once per outage TRANSITION, not once per failed op.
    assert len(warned) == 1
    client.close()


def test_remote_store_mid_put_disconnect():
    """A peer that accepts then drops the connection mid-exchange:
    the put degrades to (False, 0, 1) instead of raising or hanging."""
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    stop = threading.Event()

    def accept_and_slam():
        while not stop.is_set():
            try:
                c, _ = srv.accept()
            except OSError:
                return
            c.close()

    t = threading.Thread(target=accept_and_slam, daemon=True)
    t.start()
    e0 = _errors_total()
    client = RemotePageStore(
        srv.getsockname(), timeout_s=0.5, retry_s=0.0
    )
    try:
        planes = (np.ones((4, 16), dtype=np.float32),)
        assert client.put_counted(("mid",), planes) == (False, 0, 1)
        assert client.errors >= 1
        assert _errors_total() > e0
    finally:
        client.close()
        stop.set()
        srv.close()


def test_remote_store_slow_peer_hits_client_timeout():
    """A peer that accepts and never replies: the configured client
    timeout bounds the stall, then the op degrades to a local miss."""
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    held: list[socket.socket] = []
    stop = threading.Event()

    def accept_and_hold():
        while not stop.is_set():
            try:
                c, _ = srv.accept()
            except OSError:
                return
            held.append(c)  # read nothing, reply never

    t = threading.Thread(target=accept_and_hold, daemon=True)
    t.start()
    e0 = _errors_total()
    client = RemotePageStore(
        srv.getsockname(), timeout_s=0.2, retry_s=0.0
    )
    try:
        t0 = time.monotonic()
        assert client.get(("slow",)) is None
        assert time.monotonic() - t0 < 3.0  # bounded by timeout_s
        assert client.errors >= 1
        assert _errors_total() > e0
    finally:
        client.close()
        stop.set()
        srv.close()
        for c in held:
            c.close()


# ---------------------------------------------------------------------------
# Controller: restore-batch sizing from measured overhead (satellite 2)
# ---------------------------------------------------------------------------


def test_restore_batch_follows_overhead_ewma():
    assert "restore_batch" in KNOBS
    ctl = AdaptiveController(ControlConfig())
    cap = ctl.config.restore_batch_max
    assert cap == 8
    # Cold start: overhead unknown, nothing to stall -> full batch.
    assert ctl.restore_batch() == cap
    # Overhead invisible (fully overlapped) -> drain one page per
    # iteration, the classic PR-9 pace.
    ctl.note_overhead(ctl.config.overhead_low_s / 2)
    assert ctl.restore_batch() == 1
    # Overhead visible again -> full batch amortizes the flushes.
    for _ in range(200):
        ctl.note_overhead(ctl.config.overhead_high_s * 10)
    assert ctl.restore_batch() == cap
    st = ctl.stats()
    assert st["autotune_restore_batch"] == cap
    assert st["autotune_decisions_restore_batch"] >= 2  # cap->1->cap


def test_restore_batch_midband_and_disabled():
    mid = AdaptiveController(ControlConfig())
    mid.note_overhead(
        (mid.config.overhead_low_s + mid.config.overhead_high_s) / 2
    )
    assert mid.restore_batch() == max(1, mid.config.restore_batch_max // 2)
    off = AdaptiveController(ControlConfig(tune_restore_batch=False))
    off.note_overhead(off.config.overhead_low_s / 2)
    # Tuning off: the static cap, with no decision recorded.
    assert off.restore_batch() == off.config.restore_batch_max
    assert off.stats()["autotune_decisions_restore_batch"] == 0


# ---------------------------------------------------------------------------
# Roled fleet over the remote store: byte parity + zero re-prefill
# ---------------------------------------------------------------------------


def test_roled_fleet_over_remote_store_byte_parity(params):
    """The acceptance scenario: a ("prefill", "decode") fleet whose
    shared page store is a REMOTE server process boundary away streams
    byte-identical text versus a mixed-role control, with >= 1 chain
    handoff and zero header pages re-prefilled on the decode side."""
    page = _FCFG["page_size"]
    tok = ByteTokenizer()
    header_pages = len(tok.encode(_HEADER)) // page
    assert header_pages >= 3
    # BOS + 49 header chars + short tail stays inside the 64 bucket —
    # a longer tail would left-truncate away the shared header.
    prompts = [f"{_HEADER}p{i}?" for i in range(4)]

    def run(role, host_store=None):
        fleet = ReplicaSet(
            CFG,
            params,
            config=ContinuousConfig(**_FCFG),
            fleet=FleetConfig(replicas=2, role=role),
            host_store=host_store,
        )
        try:
            results = _serve(
                fleet, prompts, max_new_tokens=4, temperature=0.0
            )
            return [r.text for r in results], results, fleet.stats()
        finally:
            fleet.close()

    texts_mix, _, _ = run("mixed")

    store = HostPageStore(budget_bytes=64 << 20)
    server = PageStoreServer(store)
    server.start()
    client = RemotePageStore(server.endpoint, timeout_s=10.0)
    h0 = REGISTRY.get("gateway_role_handoffs_total").value
    f0 = sum(1 for e in flight.flight_recorder().events() if e.kind == "handoff")
    try:
        texts_dis, results, stats = run(
            ("prefill", "decode"), host_store=client
        )
    finally:
        client.close()
        server.close()

    # Byte parity with the mixed-role control (PR-4 restore contract).
    assert texts_dis == texts_mix
    # The handoff happened and is mirrored in fleet stats, the
    # process-global Prometheus family, and the flight recorder.
    assert stats["role_handoffs"] >= 1
    assert (
        REGISTRY.get("gateway_role_handoffs_total").value - h0
        == stats["role_handoffs"]
    )
    f1 = sum(1 for e in flight.flight_recorder().events() if e.kind == "handoff")
    assert f1 - f0 == stats["role_handoffs"]
    # Role surface: per-replica roles reported, prefill never routed.
    assert stats["roles"] == ["prefill", "decode"]
    assert stats["per_replica"][0]["role"] == "prefill"
    # Zero header pages re-prefilled on the decode side: every mate's
    # full header arrived shared (registry-resident) or restored from
    # the remote store.
    restored = 0
    for r in results:
        tm = r.timing
        covered = tm["header_pages_shared"] + tm["header_pages_restored"]
        assert covered >= header_pages, tm
        restored += tm["header_pages_restored"]
    assert restored >= 1  # at least one mate pulled pages over the wire
    # The export landed in the AUTHORITATIVE (server-side) store.
    assert store.demoted_pages >= 1


def test_roled_fleet_dead_store_never_wedges(params):
    """A roled fleet whose remote store endpoint is dead: every
    request still completes (degrade = recompute), the remote-store
    error counter moves, and the serving loops' heartbeats stay
    fresh — the worker loop never blocks on the dead socket."""
    client = RemotePageStore(_dead_endpoint(), timeout_s=0.3, retry_s=0.05)
    e0 = _errors_total()
    fleet = ReplicaSet(
        CFG,
        params,
        config=ContinuousConfig(**_FCFG),
        fleet=FleetConfig(replicas=2, role=("prefill", "decode")),
        host_store=client,
    )
    try:
        prompts = [f"{_HEADER}m{i}?" for i in range(4)]
        results = _serve(fleet, prompts, max_new_tokens=4, temperature=0.0)
        assert len(results) == 4
        assert all(r.text for r in results)
        assert _errors_total() > e0
        hb = fleet.heartbeat()
        assert hb["alive"] is True
        assert hb["last_tick_age_s"] < 5.0
    finally:
        fleet.close()
        client.close()


def test_handoff_dedup_claims_once_per_chain():
    """The dedup table admits ONE warm-up per chain per TTL window —
    a 4-mate panel burst must not warm the same header four times."""

    class _Fleet:
        pass

    co = HandoffCoordinator(_Fleet())
    chain = (("page", 0),)
    assert co._dedup_claim(chain) is True
    assert co._dedup_claim(chain) is False  # live claim
    other = (("page", 1),)
    assert co._dedup_claim(other) is True


# ---------------------------------------------------------------------------
# Gateway: /debug/chains probe + cross-host peer forwarding
# ---------------------------------------------------------------------------


def _boot(backend, admission=None, **gw_kw):
    reg = MetricsRegistry()
    gw = Gateway(
        backend,
        config=GatewayConfig(
            port=0, admission=admission or AdmissionConfig(), **gw_kw
        ),
        registry=reg,
    )
    handle = GatewayThread(gw).start()
    return handle, GatewayClient("127.0.0.1", handle.port), reg


def _probed_backend(registry_tokens=0, host_tokens=0):
    fb = FakeBackend()
    fb.prefix_probe = lambda ids: {
        "registry_tokens": registry_tokens,
        "host_tokens": host_tokens,
    }
    fb.tokenizer = ByteTokenizer()
    return fb


def test_debug_chains_endpoint():
    handle, client, _ = _boot(_probed_backend(registry_tokens=48))
    try:
        doc = client._json("GET", "/debug/chains?ids=1,2,3")
        # PR 20: the probe reply carries a clock-probe stamp too.
        assert doc.pop("now_pc") > 0
        assert doc == {"n_ids": 3, "registry_tokens": 48, "host_tokens": 0}
        n = len(ByteTokenizer().encode("hi"))
        doc = client._json("GET", "/debug/chains?prompt=hi")
        assert doc["n_ids"] == n and doc["registry_tokens"] == 48
        with pytest.raises(GatewayHTTPError) as e:
            client._json("GET", "/debug/chains")
        assert e.value.status == 400
        with pytest.raises(GatewayHTTPError) as e:
            client._json("GET", "/debug/chains?ids=1,nope")
        assert e.value.status == 400
    finally:
        handle.drain()
    # A backend without a probe (plain FakeBackend) 404s.
    handle, client, _ = _boot(FakeBackend())
    try:
        with pytest.raises(GatewayHTTPError) as e:
            client._json("GET", "/debug/chains?ids=1")
        assert e.value.status == 404
    finally:
        handle.drain()


def test_peer_forwarding_routes_by_residency():
    """Front gateway with two peers: the request lands on the peer
    whose /debug/chains reports the longest resident chain, and the
    response relays verbatim with an X-Peer header naming it."""
    warm_h, _, warm_reg = _boot(_probed_backend(registry_tokens=64))
    cold_h, _, cold_reg = _boot(_probed_backend(registry_tokens=0))
    warm_url = f"http://127.0.0.1:{warm_h.port}"
    cold_url = f"http://127.0.0.1:{cold_h.port}"
    front_h, front_client, _ = _boot(
        FakeBackend(), peers=(cold_url, warm_url)
    )
    try:
        resp, data = front_client._request(
            "POST", "/v1/generate", {"prompt": "route me"}
        )
        assert resp.getheader("X-Peer") == warm_url
        doc = json.loads(data)
        assert doc["text"] == "Echo: route me"
        # The warm peer served it; the cold peer saw only the probe.
        # (The peer's route counter lands AFTER its response bytes are
        # relayed — poll briefly instead of racing the handler tail.)
        deadline = time.monotonic() + 5.0
        while (
            time.monotonic() < deadline
            and 'route="/v1/generate"' not in warm_reg.render()
        ):
            time.sleep(0.02)
        assert 'route="/v1/generate"' in warm_reg.render()
        assert 'route="/v1/generate"' not in cold_reg.render()
    finally:
        front_h.drain()
        warm_h.drain()
        cold_h.drain()


def test_peer_forwarding_unreachable_peer_502s():
    dead = _dead_endpoint()
    front_h, front_client, _ = _boot(
        FakeBackend(), peers=(f"http://{dead[0]}:{dead[1]}",)
    )
    try:
        with pytest.raises(GatewayHTTPError) as e:
            front_client.generate("hello")
        assert e.value.status == 502
        assert "unreachable" in e.value.body
    finally:
        front_h.drain()


# ---------------------------------------------------------------------------
# The bench disaggregation leg (subprocess, CPU smoke sizes)
# ---------------------------------------------------------------------------


def test_bench_serve_disagg_cpu_leg(tmp_path: Path):
    """Acceptance: prefill+decode roles over a remote store in a REAL
    separate process, byte-identical text vs the mixed-role control,
    >= 1 cross-process handoff with zero re-prefilled header pages,
    then the store process is killed and the degrade burst completes
    with no 429s and /readyz still ready."""
    out = tmp_path / "disagg.json"
    r = subprocess.run(
        [
            sys.executable, "bench.py", "--tiny", "--cpu",
            "--serve-disagg", "--serve-requests", "8",
            "--serve-slots", "2", "--new-tokens", "6",
            "--prompt-len", "64", "--serve-chunk", "1",
            "--serve-prefill-chunk", "64", "--out", str(out),
        ],
        cwd=Path(__file__).resolve().parent.parent,
        capture_output=True,
        text=True,
        timeout=900,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    payload = json.loads(out.read_text())
    assert payload["status"] == "ok"
    assert payload["unit"] == "tokens/sec"
    assert payload["value"] > 0
