"""Tests for the inference engine: tokenizer, sampler, generate loop.

The reference has no tests at all (SURVEY.md §4); this suite covers the
layer that replaces its remote-API compute (``src/main.rs:82-86``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
from llm_consensus_tpu.engine.generate import generate
from llm_consensus_tpu.engine.sampler import SamplerConfig, sample_token
from llm_consensus_tpu.engine.tokenizer import ByteTokenizer, load_tokenizer
from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import init_params

# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    for text in ["hello world", "ünïcödé ☃", "", "a\nb\tc"]:
        ids = tok.encode(text)
        assert ids[0] == tok.bos_id
        assert tok.decode(ids) == text


def test_byte_tokenizer_ids_in_range():
    tok = ByteTokenizer()
    ids = tok.encode("\x00\xff arbitrary bytes")
    assert all(0 <= i < tok.vocab_size for i in ids)
    assert tok.vocab_size == 259


def test_load_tokenizer_falls_back_to_bytes():
    assert isinstance(load_tokenizer(None), ByteTokenizer)
    assert isinstance(load_tokenizer("/nonexistent/dir"), ByteTokenizer)


# ---------------------------------------------------------------------------
# Sampler
# ---------------------------------------------------------------------------


def test_greedy_picks_argmax():
    logits = jnp.array([[0.1, 5.0, 0.2], [3.0, 0.0, -1.0]], jnp.float32)
    tok, lp = sample_token(
        logits, jax.random.PRNGKey(0), jnp.zeros(2, jnp.float32)
    )
    assert tok.tolist() == [1, 0]
    # Greedy logprob is log_softmax at the argmax (temperature treated as 1).
    expected = jax.nn.log_softmax(logits, axis=-1)[jnp.arange(2), tok]
    np.testing.assert_allclose(lp, expected, rtol=1e-5)


def test_temperature_sampling_varies_and_respects_seed():
    logits = jnp.zeros((1, 64), jnp.float32)  # uniform
    t = jnp.ones(1, jnp.float32)
    draws = {
        int(sample_token(logits, jax.random.PRNGKey(s), t)[0][0])
        for s in range(16)
    }
    assert len(draws) > 1  # actually random
    a = sample_token(logits, jax.random.PRNGKey(7), t)[0]
    b = sample_token(logits, jax.random.PRNGKey(7), t)[0]
    assert a.tolist() == b.tolist()  # deterministic per seed


def test_top_k_restricts_support():
    logits = jnp.array([[0.0, 1.0, 2.0, 3.0, 4.0]], jnp.float32)
    cfg = SamplerConfig(top_k=2)
    for s in range(32):
        tok, _ = sample_token(
            logits, jax.random.PRNGKey(s), jnp.ones(1), cfg
        )
        assert int(tok[0]) in (3, 4)


def test_top_p_restricts_support():
    # Token 0 has ~88% mass; top_p=0.5 keeps only it.
    logits = jnp.array([[4.0, 2.0, 1.0, 0.0]], jnp.float32)
    cfg = SamplerConfig(top_p=0.5)
    for s in range(16):
        tok, _ = sample_token(
            logits, jax.random.PRNGKey(s), jnp.ones(1), cfg
        )
        assert int(tok[0]) == 0


def test_mixed_greedy_and_sampled_rows():
    logits = jnp.tile(
        jnp.array([[0.0, 3.0, 0.0, 0.0]], jnp.float32), (2, 1)
    )
    t = jnp.array([0.0, 5.0], jnp.float32)  # row0 greedy, row1 hot
    toks = [
        sample_token(logits, jax.random.PRNGKey(s), t)[0].tolist()
        for s in range(24)
    ]
    assert all(t0 == 1 for t0, _ in toks)  # greedy row fixed at argmax
    assert len({t1 for _, t1 in toks}) > 1  # hot row varies


# ---------------------------------------------------------------------------
# Generate loop
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny():
    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def test_generate_shapes_and_determinism(tiny):
    cfg, params = tiny
    b, s = 2, 8
    tokens = jnp.ones((b, s), jnp.int32)
    lengths = jnp.array([5, 8], jnp.int32)
    out1 = generate(
        cfg, params, tokens, lengths, jax.random.PRNGKey(0),
        jnp.zeros(b), max_new_tokens=6,
    )
    assert out1.tokens.shape == (b, 6)
    assert out1.num_tokens.shape == (b,)
    assert out1.logprob_sum.shape == (b,)
    out2 = generate(
        cfg, params, tokens, lengths, jax.random.PRNGKey(0),
        jnp.zeros(b), max_new_tokens=6,
    )
    assert out1.tokens.tolist() == out2.tokens.tolist()


def test_generate_matches_forward_greedy(tiny):
    """Greedy decode via cache must match greedy argmax over full forward."""
    from llm_consensus_tpu.models.transformer import forward

    cfg, params = tiny
    prompt = jnp.array([[5, 9, 13, 17]], jnp.int32)
    lengths = jnp.array([4], jnp.int32)
    steps = 5
    out = generate(
        cfg, params, prompt, lengths, jax.random.PRNGKey(0),
        jnp.zeros(1), max_new_tokens=steps, eos_id=-1,
    )
    # Reference: repeated full forward + argmax.
    seq = prompt
    got = []
    for _ in range(steps):
        logits = forward(cfg, params, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        got.append(int(nxt[0]))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    assert out.tokens[0].tolist() == got


def test_generate_eos_stops_and_pads(tiny):
    cfg, params = tiny
    # Force EOS at the very first sampled token by making eos = argmax token.
    tokens = jnp.ones((1, 4), jnp.int32)
    lengths = jnp.array([4], jnp.int32)
    probe = generate(
        cfg, params, tokens, lengths, jax.random.PRNGKey(0),
        jnp.zeros(1), max_new_tokens=1, eos_id=-1,
    )
    first = int(probe.tokens[0, 0])
    out = generate(
        cfg, params, tokens, lengths, jax.random.PRNGKey(0),
        jnp.zeros(1), max_new_tokens=5, eos_id=first, pad_id=0,
    )
    assert int(out.num_tokens[0]) == 1
    assert out.tokens[0, 1:].tolist() == [0, 0, 0, 0]


def test_generate_per_row_seeds_diverge(tiny):
    """Same prompt replicated with temperature>0 must yield diverse rows —
    the self-consistency fan-out property (BASELINE.md N-way configs)."""
    cfg, params = tiny
    b = 8
    tokens = jnp.tile(jnp.array([[3, 7, 11]], jnp.int32), (b, 1))
    lengths = jnp.full((b,), 3, jnp.int32)
    out = generate(
        cfg, params, tokens, lengths, jax.random.PRNGKey(0),
        jnp.full((b,), 2.0), max_new_tokens=8, eos_id=-1,
    )
    rows = {tuple(r) for r in out.tokens.tolist()}
    assert len(rows) > 1


# ---------------------------------------------------------------------------
# InferenceEngine (text in/out)
# ---------------------------------------------------------------------------


def test_engine_text_roundtrip(tiny):
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params, engine_config=EngineConfig(
            max_new_tokens=8, seq_buckets=(16, 32), batch_buckets=(1, 2, 4)
        ),
    )
    results = eng.generate_texts(["What is 2+2?", "Hi"])
    assert len(results) == 2
    for r in results:
        assert isinstance(r.text, str)
        assert r.num_tokens >= 1
        assert r.logprob <= 0.0


def test_engine_batch_padding_consistency(tiny):
    """A prompt's greedy output must not depend on its batch neighbours."""
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params, engine_config=EngineConfig(
            max_new_tokens=6, seq_buckets=(16,), batch_buckets=(1, 2, 4)
        ),
    )
    solo = eng.generate_texts(["What is 2+2?"])[0]
    batched = eng.generate_texts(["What is 2+2?", "Different neighbour!"])[0]
    assert solo.token_ids == batched.token_ids


def test_shared_prefill_matches_per_row_prefill(tiny):
    """Broadcast-cache fan-out must produce the same tokens as B-way
    prefill of the identical prompt (greedy, so rows are comparable)."""
    cfg, params = tiny
    b = 4
    tokens = jnp.tile(jnp.array([[5, 9, 13, 17]], jnp.int32), (b, 1))
    lengths = jnp.full((b,), 4, jnp.int32)
    kw = dict(max_new_tokens=5, eos_id=-1)
    ref = generate(
        cfg, params, tokens, lengths, jax.random.PRNGKey(0), jnp.zeros(b), **kw
    )
    got = generate(
        cfg, params, tokens, lengths, jax.random.PRNGKey(0), jnp.zeros(b),
        shared_prefill=True, **kw,
    )
    assert got.tokens.tolist() == ref.tokens.tolist()
    # Sampled rows still diverge from each other under shared prefill.
    hot = generate(
        cfg, params, tokens, lengths, jax.random.PRNGKey(0),
        jnp.full((b,), 2.0), shared_prefill=True, **kw,
    )
    assert len({tuple(r) for r in hot.tokens.tolist()}) > 1


def test_engine_overlong_prompt_truncates(tiny):
    """Prompts beyond the model context are left-truncated, not a crash
    (keeps the question tail)."""
    cfg, params = tiny  # max_seq_len=128
    eng = InferenceEngine(
        cfg, params, engine_config=EngineConfig(
            max_new_tokens=4, seq_buckets=(16, 512), batch_buckets=(1,)
        ),
    )
    results = eng.generate_texts(["x" * 500])  # ~500 byte tokens
    assert len(results) == 1
    assert results[0].num_tokens >= 1


def test_engine_batch_larger_than_biggest_bucket_chunks(tiny):
    """More prompts than batch_buckets[-1] run as multiple chunks."""
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params, engine_config=EngineConfig(
            max_new_tokens=3, seq_buckets=(16,), batch_buckets=(1, 2)
        ),
    )
    results = eng.generate_texts([f"q{i}" for i in range(5)])
    assert len(results) == 5
    assert all(r.num_tokens >= 1 for r in results)


def test_engine_rejects_small_vocab():
    cfg = get_config("test-tiny").with_(vocab_size=16)
    with pytest.raises(ValueError):
        InferenceEngine(cfg, params={}, tokenizer=ByteTokenizer())


# ---------------------------------------------------------------------------
# Mesh-wired engine (VERDICT r2 #4: the N-way fan-out as one sharded program)
# ---------------------------------------------------------------------------


def test_engine_mesh_sharded_self_consistency_matches_single_device(tiny):
    """self_consistency(n=16) on a dp=8 mesh: params replicated over
    `data`, candidate batch + KV cache sharded — tokens must match the
    unsharded engine exactly (same program, GSPMD-partitioned)."""
    from jax.sharding import PartitionSpec as P

    from llm_consensus_tpu.consensus.voting import self_consistency
    from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg, params = tiny
    ecfg = EngineConfig(
        max_new_tokens=6, seq_buckets=(16,), batch_buckets=(1, 2, 4, 8, 16)
    )
    mesh = make_mesh(MeshConfig(data=8))
    single = InferenceEngine(cfg, params, engine_config=ecfg)
    sharded = InferenceEngine(cfg, params, engine_config=ecfg, mesh=mesh)

    # Params actually landed sharded (replicated spec over the mesh).
    wq = sharded.params["blocks"]["wq"]
    assert wq.sharding.mesh.shape["data"] == 8
    assert wq.sharding.spec == P(None, None, "model")

    r_single = self_consistency(
        single, "What is 2+2?", n=16, temperature=0.8, seed=3
    )
    r_sharded = self_consistency(
        sharded, "What is 2+2?", n=16, temperature=0.8, seed=3
    )
    assert r_sharded.candidates == r_single.candidates
    assert r_sharded.vote.winner == r_single.vote.winner


def test_engine_mesh_batch_buckets_respect_data_axis(tiny):
    """A dp=8 mesh drops batch buckets that don't tile the data axis."""
    from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg, params = tiny
    mesh = make_mesh(MeshConfig(data=8))
    eng = InferenceEngine(
        cfg,
        params,
        engine_config=EngineConfig(
            max_new_tokens=3, seq_buckets=(16,), batch_buckets=(1, 2, 4, 8, 16)
        ),
        mesh=mesh,
    )
    assert eng.config.batch_buckets == (8, 16)
    # A 3-prompt call pads up to the 8-bucket and still returns 3 results.
    results = eng.generate_texts(["a", "bb", "ccc"])
    assert len(results) == 3
    assert all(r.num_tokens >= 1 for r in results)


def test_engine_chunked_prefill_matches_oneshot(tiny):
    """prefill_chunk engines produce identical texts to one-shot."""
    cfg, params = tiny
    base = EngineConfig(
        max_new_tokens=5, seq_buckets=(32,), batch_buckets=(1, 2)
    )
    from dataclasses import replace

    oneshot = InferenceEngine(cfg, params, engine_config=base)
    chunked = InferenceEngine(
        cfg, params, engine_config=replace(base, prefill_chunk=8)
    )
    prompts = ["the quick brown fox jumps over", "a longer test prompt here"]
    want = [r.text for r in oneshot.generate_texts(prompts)]
    got = [r.text for r in chunked.generate_texts(prompts)]
    assert got == want


def test_engine_rejects_prefill_chunk_with_kv_quant(tiny):
    cfg, params = tiny
    with pytest.raises(ValueError, match="prefill_chunk"):
        InferenceEngine(
            cfg,
            params,
            engine_config=EngineConfig(
                seq_buckets=(16,), batch_buckets=(1,),
                prefill_chunk=8, kv_quant=True,
            ),
        )
