"""Tests for the inference engine: tokenizer, sampler, generate loop.

The reference has no tests at all (SURVEY.md §4); this suite covers the
layer that replaces its remote-API compute (``src/main.rs:82-86``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llm_consensus_tpu.engine.engine import EngineConfig, InferenceEngine
from llm_consensus_tpu.engine.generate import generate
from llm_consensus_tpu.engine.sampler import SamplerConfig, sample_token
from llm_consensus_tpu.engine.tokenizer import ByteTokenizer, load_tokenizer
from llm_consensus_tpu.models.configs import get_config
from llm_consensus_tpu.models.transformer import init_params

# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    for text in ["hello world", "ünïcödé ☃", "", "a\nb\tc"]:
        ids = tok.encode(text)
        assert ids[0] == tok.bos_id
        assert tok.decode(ids) == text


def test_byte_tokenizer_ids_in_range():
    tok = ByteTokenizer()
    ids = tok.encode("\x00\xff arbitrary bytes")
    assert all(0 <= i < tok.vocab_size for i in ids)
    assert tok.vocab_size == 259


def test_load_tokenizer_falls_back_to_bytes():
    assert isinstance(load_tokenizer(None), ByteTokenizer)
    assert isinstance(load_tokenizer("/nonexistent/dir"), ByteTokenizer)


# ---------------------------------------------------------------------------
# Sampler
# ---------------------------------------------------------------------------


def test_greedy_picks_argmax():
    logits = jnp.array([[0.1, 5.0, 0.2], [3.0, 0.0, -1.0]], jnp.float32)
    tok, lp = sample_token(
        logits, jax.random.PRNGKey(0), jnp.zeros(2, jnp.float32)
    )
    assert tok.tolist() == [1, 0]
    # Greedy logprob is log_softmax at the argmax (temperature treated as 1).
    expected = jax.nn.log_softmax(logits, axis=-1)[jnp.arange(2), tok]
    np.testing.assert_allclose(lp, expected, rtol=1e-5)


def test_temperature_sampling_varies_and_respects_seed():
    logits = jnp.zeros((1, 64), jnp.float32)  # uniform
    t = jnp.ones(1, jnp.float32)
    draws = {
        int(sample_token(logits, jax.random.PRNGKey(s), t)[0][0])
        for s in range(16)
    }
    assert len(draws) > 1  # actually random
    a = sample_token(logits, jax.random.PRNGKey(7), t)[0]
    b = sample_token(logits, jax.random.PRNGKey(7), t)[0]
    assert a.tolist() == b.tolist()  # deterministic per seed


def test_top_k_restricts_support():
    logits = jnp.array([[0.0, 1.0, 2.0, 3.0, 4.0]], jnp.float32)
    cfg = SamplerConfig(top_k=2)
    for s in range(32):
        tok, _ = sample_token(
            logits, jax.random.PRNGKey(s), jnp.ones(1), cfg
        )
        assert int(tok[0]) in (3, 4)


def test_top_p_restricts_support():
    # Token 0 has ~88% mass; top_p=0.5 keeps only it.
    logits = jnp.array([[4.0, 2.0, 1.0, 0.0]], jnp.float32)
    cfg = SamplerConfig(top_p=0.5)
    for s in range(16):
        tok, _ = sample_token(
            logits, jax.random.PRNGKey(s), jnp.ones(1), cfg
        )
        assert int(tok[0]) == 0


def test_mixed_greedy_and_sampled_rows():
    logits = jnp.tile(
        jnp.array([[0.0, 3.0, 0.0, 0.0]], jnp.float32), (2, 1)
    )
    t = jnp.array([0.0, 5.0], jnp.float32)  # row0 greedy, row1 hot
    toks = [
        sample_token(logits, jax.random.PRNGKey(s), t)[0].tolist()
        for s in range(24)
    ]
    assert all(t0 == 1 for t0, _ in toks)  # greedy row fixed at argmax
    assert len({t1 for _, t1 in toks}) > 1  # hot row varies


# ---------------------------------------------------------------------------
# Generate loop
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny():
    cfg = get_config("test-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def test_generate_shapes_and_determinism(tiny):
    cfg, params = tiny
    b, s = 2, 8
    tokens = jnp.ones((b, s), jnp.int32)
    lengths = jnp.array([5, 8], jnp.int32)
    out1 = generate(
        cfg, params, tokens, lengths, jax.random.PRNGKey(0),
        jnp.zeros(b), max_new_tokens=6,
    )
    assert out1.tokens.shape == (b, 6)
    assert out1.num_tokens.shape == (b,)
    assert out1.logprob_sum.shape == (b,)
    out2 = generate(
        cfg, params, tokens, lengths, jax.random.PRNGKey(0),
        jnp.zeros(b), max_new_tokens=6,
    )
    assert out1.tokens.tolist() == out2.tokens.tolist()


def test_generate_matches_forward_greedy(tiny):
    """Greedy decode via cache must match greedy argmax over full forward."""
    from llm_consensus_tpu.models.transformer import forward

    cfg, params = tiny
    prompt = jnp.array([[5, 9, 13, 17]], jnp.int32)
    lengths = jnp.array([4], jnp.int32)
    steps = 5
    out = generate(
        cfg, params, prompt, lengths, jax.random.PRNGKey(0),
        jnp.zeros(1), max_new_tokens=steps, eos_id=-1,
    )
    # Reference: repeated full forward + argmax.
    seq = prompt
    got = []
    for _ in range(steps):
        logits = forward(cfg, params, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        got.append(int(nxt[0]))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    assert out.tokens[0].tolist() == got


def test_generate_eos_stops_and_pads(tiny):
    cfg, params = tiny
    # Force EOS at the very first sampled token by making eos = argmax token.
    tokens = jnp.ones((1, 4), jnp.int32)
    lengths = jnp.array([4], jnp.int32)
    probe = generate(
        cfg, params, tokens, lengths, jax.random.PRNGKey(0),
        jnp.zeros(1), max_new_tokens=1, eos_id=-1,
    )
    first = int(probe.tokens[0, 0])
    out = generate(
        cfg, params, tokens, lengths, jax.random.PRNGKey(0),
        jnp.zeros(1), max_new_tokens=5, eos_id=first, pad_id=0,
    )
    assert int(out.num_tokens[0]) == 1
    assert out.tokens[0, 1:].tolist() == [0, 0, 0, 0]


def test_generate_per_row_seeds_diverge(tiny):
    """Same prompt replicated with temperature>0 must yield diverse rows —
    the self-consistency fan-out property (BASELINE.md N-way configs)."""
    cfg, params = tiny
    b = 8
    tokens = jnp.tile(jnp.array([[3, 7, 11]], jnp.int32), (b, 1))
    lengths = jnp.full((b,), 3, jnp.int32)
    out = generate(
        cfg, params, tokens, lengths, jax.random.PRNGKey(0),
        jnp.full((b,), 2.0), max_new_tokens=8, eos_id=-1,
    )
    rows = {tuple(r) for r in out.tokens.tolist()}
    assert len(rows) > 1


# ---------------------------------------------------------------------------
# InferenceEngine (text in/out)
# ---------------------------------------------------------------------------


def test_engine_text_roundtrip(tiny):
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params, engine_config=EngineConfig(
            max_new_tokens=8, seq_buckets=(16, 32), batch_buckets=(1, 2, 4)
        ),
    )
    results = eng.generate_texts(["What is 2+2?", "Hi"])
    assert len(results) == 2
    for r in results:
        assert isinstance(r.text, str)
        assert r.num_tokens >= 1
        assert r.logprob <= 0.0


def test_engine_batch_padding_consistency(tiny):
    """A prompt's greedy output must not depend on its batch neighbours."""
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params, engine_config=EngineConfig(
            max_new_tokens=6, seq_buckets=(16,), batch_buckets=(1, 2, 4)
        ),
    )
    solo = eng.generate_texts(["What is 2+2?"])[0]
    batched = eng.generate_texts(["What is 2+2?", "Different neighbour!"])[0]
    assert solo.token_ids == batched.token_ids


def test_shared_prefill_matches_per_row_prefill(tiny):
    """Broadcast-cache fan-out must produce the same tokens as B-way
    prefill of the identical prompt (greedy, so rows are comparable)."""
    cfg, params = tiny
    b = 4
    tokens = jnp.tile(jnp.array([[5, 9, 13, 17]], jnp.int32), (b, 1))
    lengths = jnp.full((b,), 4, jnp.int32)
    kw = dict(max_new_tokens=5, eos_id=-1)
    ref = generate(
        cfg, params, tokens, lengths, jax.random.PRNGKey(0), jnp.zeros(b), **kw
    )
    got = generate(
        cfg, params, tokens, lengths, jax.random.PRNGKey(0), jnp.zeros(b),
        shared_prefill=True, **kw,
    )
    assert got.tokens.tolist() == ref.tokens.tolist()
    # Sampled rows still diverge from each other under shared prefill.
    hot = generate(
        cfg, params, tokens, lengths, jax.random.PRNGKey(0),
        jnp.full((b,), 2.0), shared_prefill=True, **kw,
    )
    assert len({tuple(r) for r in hot.tokens.tolist()}) > 1


def test_engine_overlong_prompt_truncates(tiny):
    """Prompts beyond the model context are left-truncated, not a crash
    (keeps the question tail)."""
    cfg, params = tiny  # max_seq_len=128
    eng = InferenceEngine(
        cfg, params, engine_config=EngineConfig(
            max_new_tokens=4, seq_buckets=(16, 512), batch_buckets=(1,)
        ),
    )
    results = eng.generate_texts(["x" * 500])  # ~500 byte tokens
    assert len(results) == 1
    assert results[0].num_tokens >= 1


def test_engine_batch_larger_than_biggest_bucket_chunks(tiny):
    """More prompts than batch_buckets[-1] run as multiple chunks."""
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params, engine_config=EngineConfig(
            max_new_tokens=3, seq_buckets=(16,), batch_buckets=(1, 2)
        ),
    )
    results = eng.generate_texts([f"q{i}" for i in range(5)])
    assert len(results) == 5
    assert all(r.num_tokens >= 1 for r in results)


def test_engine_rejects_small_vocab():
    cfg = get_config("test-tiny").with_(vocab_size=16)
    with pytest.raises(ValueError):
        InferenceEngine(cfg, params={}, tokenizer=ByteTokenizer())


# ---------------------------------------------------------------------------
# Mesh-wired engine (VERDICT r2 #4: the N-way fan-out as one sharded program)
# ---------------------------------------------------------------------------


def test_engine_mesh_sharded_self_consistency_matches_single_device(tiny):
    """self_consistency(n=16) on a dp=8 mesh: params replicated over
    `data`, candidate batch + KV cache sharded — tokens must match the
    unsharded engine exactly (same program, GSPMD-partitioned)."""
    from jax.sharding import PartitionSpec as P

    from llm_consensus_tpu.consensus.voting import self_consistency
    from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg, params = tiny
    ecfg = EngineConfig(
        max_new_tokens=6, seq_buckets=(16,), batch_buckets=(1, 2, 4, 8, 16)
    )
    mesh = make_mesh(MeshConfig(data=8))
    single = InferenceEngine(cfg, params, engine_config=ecfg)
    sharded = InferenceEngine(cfg, params, engine_config=ecfg, mesh=mesh)

    # Params actually landed sharded (replicated spec over the mesh).
    wq = sharded.params["blocks"]["wq"]
    assert wq.sharding.mesh.shape["data"] == 8
    assert wq.sharding.spec == P(None, None, "model")

    r_single = self_consistency(
        single, "What is 2+2?", n=16, temperature=0.8, seed=3
    )
    r_sharded = self_consistency(
        sharded, "What is 2+2?", n=16, temperature=0.8, seed=3
    )
    assert r_sharded.candidates == r_single.candidates
    assert r_sharded.vote.winner == r_single.vote.winner


def test_engine_mesh_moe_capacity_matches_single_device():
    """An MoE engine on a data x expert mesh, capacity dispatch pinned
    (the dispatch einsums become GSPMD all-to-alls over `expert`), must
    decode the same greedy tokens as the unsharded engine. Capacity
    factor = E so no token can drop (the exactness anchor); greedy so
    EP's collective reduction order (fp32 noise ~1e-6) can't flip a
    sampled near-tie."""
    from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh

    base = get_config("test-tiny-moe")
    cfg = base.with_(
        moe_capacity_factor=float(base.n_experts)
    ).with_moe_capacity_pinned()
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    ecfg = EngineConfig(
        max_new_tokens=6, seq_buckets=(16,), batch_buckets=(1, 2, 4)
    )
    mesh = make_mesh(MeshConfig(data=2, expert=4))
    single = InferenceEngine(cfg, params, engine_config=ecfg)
    sharded = InferenceEngine(cfg, params, engine_config=ecfg, mesh=mesh)
    prompts = ["2+2=", "3+3="]
    a = single.generate_texts(prompts, temperatures=[0.0, 0.0], seed=5)
    b = sharded.generate_texts(prompts, temperatures=[0.0, 0.0], seed=5)
    assert [r.text for r in a] == [r.text for r in b]


def test_engine_mesh_batch_buckets_respect_data_axis(tiny):
    """A dp=8 mesh drops batch buckets that don't tile the data axis."""
    from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg, params = tiny
    mesh = make_mesh(MeshConfig(data=8))
    eng = InferenceEngine(
        cfg,
        params,
        engine_config=EngineConfig(
            max_new_tokens=3, seq_buckets=(16,), batch_buckets=(1, 2, 4, 8, 16)
        ),
        mesh=mesh,
    )
    assert eng.config.batch_buckets == (8, 16)
    # A 3-prompt call pads up to the 8-bucket and still returns 3 results.
    results = eng.generate_texts(["a", "bb", "ccc"])
    assert len(results) == 3
    assert all(r.num_tokens >= 1 for r in results)


def test_engine_mesh_score_texts_matches_single_device(tiny):
    """score_texts on a dp=8 mesh (completions sharded over `data`, the
    B=1 prompt prefill replicated and GSPMD-broadcast into the sharded
    cache) must score identically to the single-device engine —
    unlocking rescore_vote/debate-rescore on the north-star config."""
    from llm_consensus_tpu.consensus.voting import rescore_vote
    from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg, params = tiny
    ecfg = EngineConfig(
        max_new_tokens=4, seq_buckets=(16,), batch_buckets=(1, 2, 4, 8, 16)
    )
    mesh = make_mesh(MeshConfig(data=8))
    single = InferenceEngine(cfg, params, engine_config=ecfg)
    sharded = InferenceEngine(cfg, params, engine_config=ecfg, mesh=mesh)

    prompt = "What is 2+2?"
    comps = ["four", "5", "four hundred", "4"]
    s_single = single.score_texts(prompt, comps)
    s_sharded = sharded.score_texts(prompt, comps)
    np.testing.assert_allclose(s_sharded, s_single, rtol=2e-4, atol=1e-5)

    # The unlocked consumer: judge rescoring over a sharded engine.
    v_single = rescore_vote(single, prompt, comps)
    v_sharded = rescore_vote(sharded, prompt, comps)
    assert v_sharded.winner == v_single.winner


def test_engine_chunked_prefill_matches_oneshot(tiny):
    """prefill_chunk engines produce identical texts to one-shot."""
    cfg, params = tiny
    base = EngineConfig(
        max_new_tokens=5, seq_buckets=(32,), batch_buckets=(1, 2)
    )
    from dataclasses import replace

    oneshot = InferenceEngine(cfg, params, engine_config=base)
    chunked = InferenceEngine(
        cfg, params, engine_config=replace(base, prefill_chunk=8)
    )
    prompts = ["the quick brown fox jumps over", "a longer test prompt here"]
    want = [r.text for r in oneshot.generate_texts(prompts)]
    got = [r.text for r in chunked.generate_texts(prompts)]
    assert got == want


def test_engine_chunked_prefill_composes_with_kv_quant(tiny):
    """prefill_chunk + int8 KV (formerly a hard ValueError): the chunk
    scatter quantizes K/V at the same per-(token, head) granularity as
    the one-shot quant prefill, so the two int8 engines write the same
    cache and serve the same greedy texts (first-token logits differ by
    int8 rounding only — greedy argmax on this model is stable to it)."""
    cfg, params = tiny
    from dataclasses import replace

    base = EngineConfig(
        max_new_tokens=5, seq_buckets=(32,), batch_buckets=(1, 2),
        kv_quant=True,
    )
    oneshot = InferenceEngine(cfg, params, engine_config=base)
    chunked = InferenceEngine(
        cfg, params, engine_config=replace(base, prefill_chunk=8)
    )
    prompts = ["the quick brown fox jumps over", "a longer test prompt here"]
    want = [r.text for r in oneshot.generate_texts(prompts)]
    got = [r.text for r in chunked.generate_texts(prompts)]
    assert got == want


# ---------------------------------------------------------------------------
# Prefix caching (engine/prefix_cache.py + generate_from_prefix)
# ---------------------------------------------------------------------------


def test_generate_from_prefix_matches_concatenated(tiny):
    """Prefix-continuation must equal plain generation on prefix+suffix."""
    from llm_consensus_tpu.engine.generate import generate_from_prefix
    from llm_consensus_tpu.models.cache import KVCache
    from llm_consensus_tpu.models.transformer import prefill

    cfg, params = tiny
    tok = ByteTokenizer()
    prefix_txt = "Shared few-shot header. "
    suffixes = ["What is 2+2?", "Name a color now."]

    prefix_ids = tok.encode(prefix_txt)  # BOS + bytes
    p = len(prefix_ids)
    cache1 = KVCache.create(cfg, 1, p)
    _, cache1 = prefill(
        cfg, params,
        jnp.asarray([prefix_ids], jnp.int32),
        jnp.asarray([p], jnp.int32),
        cache1,
    )

    suf = [tok.encode(s, add_bos=False) for s in suffixes]
    s_max = max(len(x) for x in suf)
    tokens = np.full((2, s_max), tok.pad_id, np.int32)
    for i, ids in enumerate(suf):
        tokens[i, : len(ids)] = ids
    lengths = jnp.asarray([len(x) for x in suf], jnp.int32)

    # Pad the prefix buffers past the true length: exercises the
    # bucketed-prefix contract (prefix_len is the real count).
    pad = ((0, 0), (0, 0), (0, 5), (0, 0), (0, 0))
    out = generate_from_prefix(
        cfg, params, jnp.pad(cache1.k, pad), jnp.pad(cache1.v, pad),
        jnp.asarray(p, jnp.int32),
        jnp.asarray(tokens), lengths,
        jax.random.PRNGKey(0), jnp.zeros(2),
        max_new_tokens=6,
    )

    # Plain path on the concatenated token streams.
    full = [prefix_ids + x for x in suf]
    f_max = max(len(x) for x in full)
    ftokens = np.full((2, f_max), tok.pad_id, np.int32)
    for i, ids in enumerate(full):
        ftokens[i, : len(ids)] = ids
    flengths = jnp.asarray([len(x) for x in full], jnp.int32)
    want = generate(
        cfg, params, jnp.asarray(ftokens), flengths,
        jax.random.PRNGKey(0), jnp.zeros(2), max_new_tokens=6,
    )
    assert out.tokens.tolist() == want.tokens.tolist()
    assert out.num_tokens.tolist() == want.num_tokens.tolist()
    np.testing.assert_allclose(
        out.logprob_sum, want.logprob_sum, rtol=2e-2, atol=2e-2
    )


def test_engine_prefix_matches_plain_and_caches(tiny):
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(32, 64), batch_buckets=(1, 2, 4), max_new_tokens=8
        ),
    )
    prefix = "Instructions: answer briefly. "
    prompts = ["Q: 2+2? A:", "Q: sky color? A:"]
    want = [r.text for r in eng.generate_texts([prefix + p for p in prompts])]
    got1 = [r.text for r in eng.generate_texts(prompts, prefix=prefix)]
    assert eng.prefix_cache.stats.misses == 1
    got2 = [r.text for r in eng.generate_texts(prompts, prefix=prefix)]
    assert eng.prefix_cache.stats.hits == 1
    assert got1 == want
    assert got2 == want


def test_engine_prefix_moe_straddles_dense_threshold():
    """MoE dispatch parity when the prefix-cache token budget straddles
    ``moe_dense_decode_tokens``: the pow2 prefix bucket (32 for a
    21-token header) overshoots the threshold the true total sits
    under. The path choice must come from the TRUE prefix length so the
    prefix-cache path picks dense exactly when the plain concatenated
    path does — at a tight capacity factor the capacity path DROPS
    tokens, so a bucket-width budget is a real numeric divergence, not
    a rounding quirk."""
    tok = ByteTokenizer()
    base = get_config("test-tiny-moe")
    cfg = base.with_(moe_capacity_factor=1.0, moe_dense_decode_tokens=64)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    eng = InferenceEngine(
        cfg,
        params,
        engine_config=EngineConfig(
            max_new_tokens=6, seq_buckets=(8, 32), batch_buckets=(1, 2, 4)
        ),
    )
    prefix = "Shared header text. "  # 20 bytes + BOS = 21 tokens
    prompts = ["2+2=", "3+3="]  # 4 tokens each (no BOS), suffix bucket 8
    p = len(tok.encode(prefix))
    pb = 1 << (p - 1).bit_length()  # the prefix cache's pow2 bucket
    true_total = 2 * (p + 8)
    bucket_total = 2 * (pb + 8)
    # Scenario self-check: the true budget is dense-side, the bucketed
    # one capacity-side — i.e. the threshold is genuinely straddled (a
    # bucket-geometry drift would otherwise make this test vacuous).
    assert cfg.moe_dense_at(true_total) and not cfg.moe_dense_at(bucket_total)

    got = eng.generate_texts(
        prompts, prefix=prefix, temperatures=[0.0, 0.0], seed=7
    )
    assert eng.prefix_cache.stats.misses == 1  # prefix path actually taken

    # Sharp check: the same prefix-path call under a config pinned dense
    # at EVERY shape traces the identical program when the straddling
    # config resolves dense too — bitwise-equal logprobs. The capacity
    # path diverges by ~1e-2 here (tight factor drops tokens), far
    # outside this tolerance, so a bucket-width budget fails this.
    dense = InferenceEngine(
        cfg.with_moe_dense_up_to(cfg.max_seq_len**2),
        params,
        engine_config=eng.config,
    )
    want = dense.generate_texts(
        prompts, prefix=prefix, temperatures=[0.0, 0.0], seed=7
    )
    assert [r.text for r in got] == [r.text for r in want]
    np.testing.assert_allclose(
        [r.logprob for r in got], [r.logprob for r in want], atol=1e-6
    )

    # And the plain concatenated path still agrees on the texts (its
    # own budget, 2 x 32 = 64, is dense-side as well).
    plain = [
        r.text
        for r in eng.generate_texts(
            [prefix + q for q in prompts], temperatures=[0.0, 0.0], seed=7
        )
    ]
    assert [r.text for r in got] == plain


def test_engine_prefix_kv_quant_rides_cache(tiny):
    """Quant-KV engines now ride the prefix cache (miss once, hit after,
    deterministic continuation). Text equality with the plain quant path
    is NOT asserted: the chunk attends dequantized prefix K/V where a
    from-scratch prefill attends bf16 — int8 rounding can flip a random
    tiny model's near-uniform argmax. The numerics bound lives in
    test_chunk_mode_quant_cache_close_to_bf16."""
    cfg, params = tiny
    plain = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(32,), batch_buckets=(1, 2), max_new_tokens=6,
            kv_quant=True,
        ),
    )
    prefix, prompts = "Header text. ", ["suffix one", "suffix two longer"]
    got1 = [r.text for r in plain.generate_texts(prompts, prefix=prefix)]
    assert plain.prefix_cache.stats.misses == 1
    assert len(plain.prefix_cache) == 1  # cached now, not bypassed
    got2 = [r.text for r in plain.generate_texts(prompts, prefix=prefix)]
    assert plain.prefix_cache.stats.hits == 1
    assert got1 == got2  # greedy continuation is deterministic


def test_chunk_mode_quant_cache_close_to_bf16(tiny):
    """The quant-cache chunk path (prefix-cached generation on kv_quant
    engines): hidden states must track the bf16 chunk path to within
    int8-KV rounding, and the suffix K/V written into the quant cache
    must be the quantization of what the bf16 path wrote."""
    from llm_consensus_tpu.models.cache import (
        KVCache,
        QuantKVCache,
        quantize_kv,
    )
    from llm_consensus_tpu.models.transformer import _chunk_hidden, prefill

    cfg, params = tiny
    b, p_len, k_len, cache_len = 3, 8, 5, 32
    ptoks = jnp.ones((1, p_len), jnp.int32) * 7
    plens = jnp.full((1,), p_len, jnp.int32)
    cache1 = KVCache.create(cfg, 1, p_len)
    _, cache1 = prefill(cfg, params, ptoks, plens, cache1)

    # bf16 reference: broadcast prefix into a B-row bf16 cache.
    bf = KVCache.create(cfg, b, cache_len)
    bf = KVCache(
        k=bf.k.at[:, :, :p_len].set(jnp.broadcast_to(
            cache1.k, (cfg.n_layers, b, p_len, cfg.n_kv_heads, cfg.head_dim)
        )),
        v=bf.v.at[:, :, :p_len].set(jnp.broadcast_to(
            cache1.v, (cfg.n_layers, b, p_len, cfg.n_kv_heads, cfg.head_dim)
        )),
        length=jnp.full((b,), p_len, jnp.int32),
    )
    # quant cache: same prefix, quantized (generate_from_prefix's rule).
    q = QuantKVCache.create(cfg, b, cache_len)
    kq, ks = quantize_kv(cache1.k)
    vq, vs = quantize_kv(cache1.v)
    bc = lambda x: jnp.broadcast_to(x, (x.shape[0], b, *x.shape[2:]))  # noqa: E731
    q = QuantKVCache(
        k_q=q.k_q.at[:, :, :, :p_len].set(bc(kq.transpose(0, 1, 3, 2, 4))),
        v_q=q.v_q.at[:, :, :, :p_len].set(bc(vq.transpose(0, 1, 3, 2, 4))),
        k_scale=q.k_scale.at[:, :, :, :p_len].set(bc(ks.transpose(0, 1, 3, 2))),
        v_scale=q.v_scale.at[:, :, :, :p_len].set(bc(vs.transpose(0, 1, 3, 2))),
        length=jnp.full((b,), p_len, jnp.int32),
    )

    chunk = (jnp.arange(b * k_len, dtype=jnp.int32) % 50).reshape(b, k_len) + 4
    h_bf, new_bf = _chunk_hidden(cfg, params, chunk, bf)
    h_q, new_q = _chunk_hidden(cfg, params, chunk, q)
    # Hidden states: int8-rounding-bounded closeness.
    np.testing.assert_allclose(
        np.asarray(h_q, np.float32),
        np.asarray(h_bf, np.float32),
        atol=0.15,
        rtol=0.05,
    )
    # Suffix K/V written by the quant chunk == quantize(bf16 chunk's
    # writes) to within 2 int8 steps (deep layers amplify the dequant
    # noise of the prefix the chunk attended).
    want_kq, _ = quantize_kv(new_bf.k[:, :, p_len : p_len + k_len])
    got_kq = new_q.k_q[:, :, :, p_len : p_len + k_len].transpose(0, 1, 3, 2, 4)
    assert (
        np.abs(
            np.asarray(got_kq, np.int32) - np.asarray(want_kq, np.int32)
        ).max()
        <= 2
    )


def test_engine_prefix_mesh_rides_cache(tiny):
    """Prefix-cached generation on a dp=8 mesh: the continuation batch
    shards over `data`, the B=1 header broadcasts — same text as the
    single-device prefix path and the plain concatenated path."""
    from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg, params = tiny
    ecfg = EngineConfig(
        seq_buckets=(32,), batch_buckets=(1, 2, 4, 8), max_new_tokens=6
    )
    mesh = make_mesh(MeshConfig(data=8))
    single = InferenceEngine(cfg, params, engine_config=ecfg)
    sharded = InferenceEngine(cfg, params, engine_config=ecfg, mesh=mesh)
    prefix = "Instructions: answer briefly. "
    prompts = ["Q: 2+2? A:", "Q: sky? A:", "Q: one? A:"]
    want = [r.text for r in single.generate_texts(prompts, prefix=prefix)]
    got = [r.text for r in sharded.generate_texts(prompts, prefix=prefix)]
    assert sharded.prefix_cache.stats.misses == 1
    assert got == want
    got2 = [r.text for r in sharded.generate_texts(prompts, prefix=prefix)]
    assert sharded.prefix_cache.stats.hits == 1
    assert got2 == want


def test_prefix_cache_lru_and_budgets():
    from llm_consensus_tpu.engine.prefix_cache import PrefixCache

    pc = PrefixCache(max_entries=2)
    k = jnp.zeros((1, 1, 4, 1, 2), jnp.bfloat16)
    pc.put((1,), k, k)
    pc.put((2,), k, k)
    assert pc.get((1,)) is not None  # refresh (1,)
    pc.put((3,), k, k)  # evicts (2,)
    assert pc.get((2,)) is None
    assert pc.get((1,)) is not None and pc.get((3,)) is not None
    assert pc.stats.evictions == 1

    tiny_budget = PrefixCache(max_entries=8, max_bytes=4 * k.size)
    tiny_budget.put((1,), k, k)
    tiny_budget.put((2,), k, k)  # 2 entries * 2k bytes > budget -> evict
    assert len(tiny_budget) == 1
    assert tiny_budget.nbytes <= 4 * k.size


# ---------------------------------------------------------------------------
# Stop sequences
# ---------------------------------------------------------------------------


def test_stop_ids_terminate_decode_like_eos(tiny):
    """A single-token stop halts the row: pads after, no logprob accrual."""
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(16,), batch_buckets=(1,), max_new_tokens=8
        ),
    )
    free = [r for r in eng.generate_texts(["count: one two"])][0]
    assert free.num_tokens > 1
    # Stop on the first character the unstopped run emitted.
    first_char = free.text[:1]
    if not first_char:
        pytest.skip("model emitted EOS immediately")
    stopped = eng.generate_texts(["count: one two"], stop=[first_char])[0]
    assert stopped.text == ""  # trimmed at the stop
    assert stopped.num_tokens <= 2  # device loop ended at the stop token


def test_stop_string_trims_host_side(tiny):
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(16,), batch_buckets=(1,), max_new_tokens=8
        ),
    )
    free = eng.generate_texts(["hello there"])[0]
    if len(free.text) < 3:
        pytest.skip("output too short to split")
    stop = free.text[1:3]  # multi-char stop (two byte tokens)
    trimmed = eng.generate_texts(["hello there"], stop=[stop])[0]
    assert trimmed.text == free.text[:1]
    assert stop not in trimmed.text


def test_multi_token_stop_ends_decode_early(tiny):
    """Multi-token stops ride the chunked decode path: the row stops
    burning device steps within ~one stop_check_chunk of the stop
    appearing, instead of decoding to EOS/max_new_tokens and trimming
    late. Other batch rows keep their full output."""
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(16,), batch_buckets=(1, 2), max_new_tokens=48,
            stop_check_chunk=4,
        ),
    )
    free = eng.generate_texts(["hello there", "another one"])
    if len(free[0].text) < 3:
        pytest.skip("output too short to split")
    stop = free[0].text[1:3]  # lands within the first few tokens of row 0
    got = eng.generate_texts(["hello there", "another one"], stop=[stop])
    assert got[0].text == free[0].text[:1]
    if free[0].num_tokens > 12:
        # Early exit is observable: the stopped row decoded far fewer
        # tokens than its unstopped run (stop at ~token 3, chunk 4 ->
        # done mask set at the next boundary).
        assert got[0].num_tokens < free[0].num_tokens
        assert got[0].num_tokens <= 12
    # The other row still runs to its own natural end (unless the stop
    # happens to occur in its text too).
    if stop not in free[1].text:
        assert got[1].text == free[1].text


def test_prefix_with_multi_token_stop_trims_and_exits_early(tiny):
    """Multi-token stops compose with the prefix cache: the prefix path
    routes through the same chunked host-checked decode, so the text
    trims identically and the stopped row does not decode to the full
    budget."""
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(32,), batch_buckets=(1, 2), max_new_tokens=48,
            stop_check_chunk=4,
        ),
    )
    prefix, q = "Shared header: ", "what is 2+2?"
    free = eng.generate_texts([q], prefix=prefix)[0]
    if len(free.text) < 3:
        pytest.skip("output too short to split")
    stop = free.text[1:3]
    got = eng.generate_texts([q], prefix=prefix, stop=[stop])[0]
    assert got.text == free.text[:1]
    assert eng.prefix_cache.stats.hits >= 1  # still rode the cache
    if free.num_tokens > 12:
        assert got.num_tokens <= 12  # early exit, not trim-at-the-end


def test_multi_token_stop_on_mesh_matches_single_device(tiny):
    """The chunked multi-token-stop decode on a dp=8 mesh (sharded
    cache, device_put done-mask updates between chunks) must trim
    exactly like the single-device path."""
    from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg, params = tiny
    ecfg = EngineConfig(
        seq_buckets=(16,), batch_buckets=(8,), max_new_tokens=24,
        stop_check_chunk=4,
    )
    mesh = make_mesh(MeshConfig(data=8))
    single = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(16,), batch_buckets=(1, 2), max_new_tokens=24,
            stop_check_chunk=4,
        ),
    )
    sharded = InferenceEngine(cfg, params, engine_config=ecfg, mesh=mesh)
    free = single.generate_texts(["tell me a fact"])[0]
    if len(free.text) < 3:
        pytest.skip("output too short to split")
    stop = free.text[1:3]
    want = single.generate_texts(["tell me a fact"], stop=[stop])[0]
    got = sharded.generate_texts(["tell me a fact"], stop=[stop])[0]
    assert got.text == want.text == free.text[:1]


def test_prefix_multi_stop_kv_quant_combination(tiny):
    """All three features composed: prefix cache + multi-token stop +
    int8 KV — the quant prefill_from_prefix feeds the chunked-stop
    decode; output is deterministic, trimmed, and the stop is honored."""
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(32,), batch_buckets=(1, 2), max_new_tokens=24,
            stop_check_chunk=4, kv_quant=True,
        ),
    )
    prefix = "Shared header: "
    q = free = None
    for cand in ("what is 2+2?", "tell me a fact", "abc", "longer query?"):
        r = eng.generate_texts([cand], prefix=prefix)[0]
        if len(r.text) >= 3:
            q, free = cand, r
            break
    if free is None:
        pytest.skip("all outputs too short to split")
    stop = free.text[1:3]
    got1 = eng.generate_texts([q], prefix=prefix, stop=[stop])[0]
    got2 = eng.generate_texts([q], prefix=prefix, stop=[stop])[0]
    assert got1.text == got2.text == free.text[:1]
    assert eng.prefix_cache.stats.hits >= 2


def test_engine_prefix_shared_suffix_fanout(tiny):
    """N identical suffixes under a prefix == plain shared-prefill run."""
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(32, 64), batch_buckets=(1, 2, 4), max_new_tokens=6
        ),
    )
    prefix, q = "Shared header: ", "what is 2+2?"
    want = [r.text for r in eng.generate_texts([prefix + q] * 4, seed=7)]
    got = [r.text for r in eng.generate_texts([q] * 4, prefix=prefix, seed=7)]
    assert got == want


def test_engine_prefix_short_header_keeps_token_budget(tiny):
    """A short header must not inflate to a coarse seq bucket and eat
    the generation budget (pow2 prefix bucketing regression)."""
    cfg, params = tiny  # max_seq_len=128
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(64,), batch_buckets=(1,), max_new_tokens=8
        ),
    )
    plain = eng.generate_texts(["Header. Q: hi A:"])[0]
    out = eng.generate_texts(["Q: hi A:"], prefix="Header. ")[0]
    assert out.num_tokens == plain.num_tokens
    assert out.text == plain.text


def test_engine_prefix_long_header_falls_back(tiny):
    """A header too long for the suffix to fit must fall back to the
    plain concatenated path (tail-keeping left truncation), not crush
    the question — and must not prefill/cache the hopeless prefix."""
    cfg, params = tiny  # max_seq_len=128
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(64, 128), batch_buckets=(1,), max_new_tokens=4
        ),
    )
    prefix = "H" * 110
    q = "Q" * 50  # 110 + 50 + bos > 128
    want = eng.generate_texts([prefix + q])[0].text
    got = eng.generate_texts([q], prefix=prefix)[0].text
    assert got == want
    assert len(eng.prefix_cache) == 0


def test_engine_prefix_long_header_keeps_full_budget(tiny):
    """The token budget must be charged at the TRUE prefix length, not
    its pow2 bucket — a long header with ample context previously
    collapsed generation to 1 token."""
    cfg, params = tiny
    cfg = cfg.with_(max_seq_len=4096)
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(64, 1024, 2048), batch_buckets=(1,),
            max_new_tokens=8,
        ),
    )
    prefix = "H" * 600  # 601 ids -> pow2 bucket 1024
    q = "Q" * 29
    want = eng.generate_texts([prefix + q])[0]
    got = eng.generate_texts([q], prefix=prefix)[0]
    assert got.text == want.text
    assert got.num_tokens == want.num_tokens
    assert got.num_tokens > 1


def test_engine_prefix_empty_suffix_falls_back(tiny):
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(64,), batch_buckets=(1, 2), max_new_tokens=4
        ),
    )
    prefix = "A header. "
    want = [r.text for r in eng.generate_texts([prefix + "", prefix + "q"])]
    got = [
        r.text for r in eng.generate_texts(["", "q"], prefix=prefix)
    ]
    assert got == want


# ---------------------------------------------------------------------------
# Streaming
# ---------------------------------------------------------------------------


def test_generate_stream_matches_batch_greedy(tiny):
    """Greedy stream increments concatenate to exactly the batch output,
    across several chunk sizes (incl. chunk boundaries mid-stream)."""
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(16,), batch_buckets=(1,), max_new_tokens=10
        ),
    )
    want = eng.generate_texts(["tell me a fact"])[0].text
    for chunk in (1, 3, 16):
        got = "".join(eng.generate_stream("tell me a fact", chunk=chunk))
        assert got == want, f"chunk={chunk}"


def test_generate_stream_stop_across_chunks(tiny):
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(16,), batch_buckets=(1,), max_new_tokens=10
        ),
    )
    full = eng.generate_texts(["tell me a fact"])[0].text
    if len(full) < 4:
        pytest.skip("output too short")
    stop = full[2:4]  # lands inside the stream
    got = "".join(eng.generate_stream("tell me a fact", chunk=3, stop=[stop]))
    assert got == full[:2]
    assert stop not in got


def test_generate_stream_sampled_reproducible(tiny):
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(16,), batch_buckets=(1,), max_new_tokens=8
        ),
    )
    a = "".join(eng.generate_stream("hi", temperature=1.0, seed=3, chunk=2))
    b = "".join(eng.generate_stream("hi", temperature=1.0, seed=3, chunk=2))
    assert a == b


def test_generate_stream_mesh_incremental(tiny):
    """Streaming on a dp=8 mesh decodes INCREMENTALLY (several yields,
    chunk-bounded) and concatenates to the sharded batch output — the
    north-star config no longer degrades to one blocking yield."""
    from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh

    cfg, params = tiny
    mesh = make_mesh(MeshConfig(data=8))
    ecfg = EngineConfig(
        seq_buckets=(16,), batch_buckets=(8,), max_new_tokens=10
    )
    sharded = InferenceEngine(cfg, params, engine_config=ecfg, mesh=mesh)
    want = sharded.generate_texts(["tell me a fact"])[0].text
    pieces = list(sharded.generate_stream("tell me a fact", chunk=3))
    assert "".join(pieces) == want
    assert len(pieces) > 1  # actually incremental, not one blob


def test_generate_stream_with_nonunit_batch_bucket(tiny):
    """Streaming must slice the padded prepare-batch down to one row
    (engines whose batch_buckets don't contain 1)."""
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(16,), batch_buckets=(4,), max_new_tokens=8
        ),
    )
    want = eng.generate_texts(["tell me a fact"])[0].text
    got = "".join(eng.generate_stream("tell me a fact", chunk=3))
    assert got == want


# ---------------------------------------------------------------------------
# Scoring (teacher-forced logprobs)
# ---------------------------------------------------------------------------


def test_score_texts_matches_forward_logprobs(tiny):
    """score_texts == summing log-softmax of the full forward pass over
    the completion's positions."""
    from llm_consensus_tpu.models.transformer import forward

    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(8, 16, 32), batch_buckets=(1, 2, 4)
        ),
    )
    tok = eng.tokenizer
    prompt = "Q: hi A:"
    comps = [" yes", " maybe so", " no!"]
    got = eng.score_texts(prompt, comps)

    p_ids = tok.encode(prompt)
    for c, lp in zip(comps, got):
        c_ids = tok.encode(c, add_bos=False)
        seq = jnp.asarray([p_ids + c_ids], jnp.int32)
        logits = forward(cfg, params, seq).astype(jnp.float32)
        lps = jax.nn.log_softmax(logits, axis=-1)
        want = sum(
            float(lps[0, len(p_ids) - 1 + i, c_ids[i]])
            for i in range(len(c_ids))
        )
        assert abs(lp - want) < 5e-2, (lp, want)


def test_score_texts_batch_order_and_length_independence(tiny):
    """Scores are per-completion: order and batch neighbours don't
    matter, and a completion scores the same alone or in a batch."""
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(8, 16, 32), batch_buckets=(1, 2, 4)
        ),
    )
    prompt = "Q: hi A:"
    comps = [" yes", " maybe so", " no!"]
    batch = eng.score_texts(prompt, comps)
    rev = eng.score_texts(prompt, comps[::-1])
    assert batch == rev[::-1]
    solo = eng.score_texts(prompt, [comps[1]])[0]
    assert abs(solo - batch[1]) < 5e-2


def test_score_texts_normalize_and_validation(tiny):
    cfg, params = tiny
    eng = InferenceEngine(cfg, params)
    s, = eng.score_texts("p", ["abcd"], normalize=True)
    assert s <= 0.0
    with pytest.raises(ValueError, match="empty completion"):
        eng.score_texts("p", [""])
    assert eng.score_texts("p", []) == []


def test_score_texts_chunks_and_truncates(tiny):
    """Candidate counts beyond the batch bucket chunk; completions
    beyond the seq bucket truncate instead of crashing; prompt lengths
    bucket so repeat calls share one compiled program."""
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(4, 8), batch_buckets=(1, 2)
        ),
    )
    comps = [" a", " bb", " ccc"]  # 3 > batch bucket 2
    batch = eng.score_texts("p:", comps)
    assert len(batch) == 3
    solo = [eng.score_texts("p:", [c])[0] for c in comps]
    for x, y in zip(batch, solo):
        assert abs(x - y) < 5e-2
    long = eng.score_texts("p:", ["x" * 50])  # > seq bucket 8: truncated
    assert len(long) == 1
    # Different prompt length, same buckets: must not error and should
    # reuse the compiled program (behavioral check only).
    assert len(eng.score_texts("p2:!", [" a"])) == 1


def test_engine_stats_counters(tiny):
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            max_new_tokens=6, seq_buckets=(16, 32), batch_buckets=(1, 2)
        ),
    )
    assert eng.stats()["calls"]["generate"] == 0
    eng.generate_texts(["hello"])
    "".join(eng.generate_stream("hi", chunk=2))
    eng.score_texts("p:", [" x"])
    s = eng.stats()
    assert s["calls"] == {
        "generate": 1, "speculative": 0, "stream": 1, "score": 1
    }
    assert s["tokens_generated"] >= 2
    assert set(s["prefix_cache"]) == {
        "hits", "misses", "evictions", "entries", "bytes"
    }


def test_stats_count_api_calls_not_chunks(tiny):
    """Counters are per public API call even when batches chunk."""
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            max_new_tokens=4, seq_buckets=(16,), batch_buckets=(1, 2)
        ),
    )
    eng.generate_texts(["a", "b", "c", "d", "e"])  # 3 chunks of <=2
    eng.score_texts("p:", [" a", " b", " c"])  # 2 chunks
    s = eng.stats()
    assert s["calls"]["generate"] == 1
    assert s["calls"]["score"] == 1


def test_memory_estimate_scales_and_fits(tiny):
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            max_new_tokens=8, seq_buckets=(16, 32), batch_buckets=(1, 2, 4)
        ),
    )
    small = eng.memory_estimate(n_candidates=1, prompt_len=16)
    big = eng.memory_estimate(n_candidates=4, prompt_len=16)
    assert big["kv_cache_bytes"] == 4 * small["kv_cache_bytes"]
    assert big["total_bytes"] > small["total_bytes"]
    assert small["params_bytes"] > 0
    assert eng.memory_estimate(1, 16, hbm_bytes=1 << 40)["fits"]
    assert not eng.memory_estimate(1, 16, hbm_bytes=16)["fits"]
    # int8 KV halves-ish the cache term vs bf16.
    q = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            max_new_tokens=8, seq_buckets=(16, 32), batch_buckets=(1,),
            kv_quant=True,
        ),
    )
    assert (
        q.memory_estimate(1, 16)["kv_cache_bytes"]
        < small["kv_cache_bytes"]
    )


def test_memory_estimate_counts_draft_and_mesh(tiny):
    """Draft models add their params + cache; meshes divide per chip."""
    cfg, params = tiny
    base = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            max_new_tokens=8, seq_buckets=(16,), batch_buckets=(1,)
        ),
    )
    drafted = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            max_new_tokens=8, seq_buckets=(16,), batch_buckets=(1,)
        ),
        draft=(cfg, params),
    )
    mb, md = base.memory_estimate(1, 16), drafted.memory_estimate(1, 16)
    assert md["params_bytes"] == 2 * mb["params_bytes"]
    assert md["kv_cache_bytes"] > mb["kv_cache_bytes"]

    from llm_consensus_tpu.parallel.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(data=4, model=2), jax.devices()[:8])
    sharded = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            max_new_tokens=8, seq_buckets=(16,), batch_buckets=(4, 8)
        ),
        mesh=mesh,
    )
    ms = sharded.memory_estimate(4, 16)
    # Per-LEAF division: matmul weights halve over `model`, but embed /
    # norms / lm-head-replicated leaves keep full size — params/chip
    # sits strictly between a naive half and the full tree, and equals
    # the PartitionSpec-walking helper exactly.
    from llm_consensus_tpu.parallel.partitioning import sharded_param_bytes

    assert (
        mb["params_bytes"] // 2
        < ms["params_bytes"]
        < mb["params_bytes"]
    )
    assert ms["params_bytes"] == sharded_param_bytes(
        sharded.params, {"model": 2, "data": 4}
    )
    # cache divides by data x model (batch also bucketed to 4 here vs 1)
    assert ms["kv_cache_bytes"] < 4 * mb["kv_cache_bytes"] // 4


def test_plan_memory_matches_memory_estimate(tiny):
    """plan_memory (config-only, eval_shape-based) must agree with the
    instantiated engine's memory_estimate — it exists so Mixtral-scale
    capacity questions are answerable without allocating weights."""
    from llm_consensus_tpu.engine.engine import plan_memory

    cfg, params = tiny
    eng = InferenceEngine(
        cfg,
        params,
        engine_config=EngineConfig(kv_quant=True, quant="int8"),
    )
    est = eng.memory_estimate(n_candidates=4, prompt_len=16, new_tokens=8)
    # plan_memory buckets with EngineConfig defaults; this engine also
    # runs default buckets, so the same raw shapes must agree exactly.
    plan = plan_memory(
        cfg,
        quant="int8",
        kv_quant=True,
        n_candidates=4,
        prompt_len=16,
        new_tokens=8,
    )
    assert plan["params_bytes"] == est["params_bytes"]
    assert plan["kv_cache_bytes"] == est["kv_cache_bytes"]
    assert plan["logits_bytes"] == est["logits_bytes"]
    assert plan["cache_len"] == est["cache_len"]
    # A 16 GiB budget fits the tiny model; 1 KiB does not.
    assert plan_memory(cfg, hbm_bytes=16 << 30)["fits"]
    assert not plan_memory(cfg, hbm_bytes=1 << 10)["fits"]


def test_per_request_sampler_matches_static_on_kth_ties():
    """Fused per-request top-k/top-p must keep tokens TIED at the kth
    logit exactly like the sequential static filters (value-mask, not
    position-mask — a position mask would drop ties from the nucleus)."""
    from llm_consensus_tpu.engine.sampler import (
        _NEG_INF,
        _apply_top_k,
        _apply_top_p,
        sample_token_per_request,
    )

    # Row with an exact tie at the kth (k=2) position.
    lg = jnp.array(
        [[3.0, 2.0, 2.0, 0.0, -1.0], [1.0, 5.0, 4.0, 4.0, 0.0]],
        jnp.float32,
    )
    t = jnp.array([1.0, 1.0], jnp.float32)
    want = _apply_top_p(_apply_top_k(lg, 2), 0.9)
    allowed = np.asarray(want) > _NEG_INF / 2
    seen: list[set] = [set(), set()]
    for s in range(96):
        tokr, _ = sample_token_per_request(
            lg,
            jax.random.split(jax.random.PRNGKey(s), 2),
            t,
            jnp.full((2,), 2, jnp.int32),
            jnp.full((2,), 0.9, jnp.float32),
        )
        for r in range(2):
            assert allowed[r, int(tokr[r])], (s, r, int(tokr[r]))
            seen[r].add(int(tokr[r]))
    # COVERAGE, not just membership: a position-mask regression that
    # drops the tied kth token would still pass membership (its support
    # is a strict subset) — the empirical support must equal the
    # sequential filters' full allowed set, ties included.
    for r in range(2):
        assert seen[r] == set(np.nonzero(allowed[r])[0].tolist()), (
            r,
            seen[r],
            allowed[r],
        )


def test_chunked_stop_accounting_matches_device_path(tiny):
    """r4 advisor: the chunked multi-token-stop path must report the
    same num_tokens/logprob accounting as the device single-token-stop
    path — the minimal token prefix whose decode contains the stop
    (stop tokens counted like EOS), no stop_check_chunk overshoot.
    test-tiny's vocab exceeds the byte range, so the random model
    interleaves empty-decoding ids — the expected count is computed
    from the free run's token stream, not from char arithmetic.
    """
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(16,), batch_buckets=(1,), max_new_tokens=32,
            stop_check_chunk=16,
        ),
    )
    free = eng.generate_texts(["hello there"])[0]
    if len(free.text) < 4 or free.num_tokens < 5:
        pytest.skip("output too short to split")
    stop = free.text[1:3]  # two byte tokens -> chunked path
    got = eng.generate_texts(["hello there"], stop=[stop])[0]
    assert got.text == free.text[:1]
    # Greedy decode is deterministic, so got's tokens are a prefix of
    # free's; the exact cut is the minimal k whose decode has the stop.
    k = next(
        k
        for k in range(1, free.num_tokens + 1)
        if stop in eng.tokenizer.decode(free.token_ids[:k])
    )
    assert k < free.num_tokens  # overshoot was possible -> test is real
    assert got.num_tokens == k
    assert got.token_ids == free.token_ids[:k]
    # logprob covers exactly those k tokens: a greedy no-stop run
    # capped at k new tokens decodes the same prefix and sums the same
    # per-token logprobs.
    want = eng.generate_texts(["hello there"], max_new_tokens=k)[0]
    assert want.token_ids == free.token_ids[:k]
    np.testing.assert_allclose(got.logprob, want.logprob, rtol=1e-4)


def test_chunked_stop_engine_token_counter_honest(tiny):
    """The engine-wide generated-token counter must match the reported
    (realigned) num_tokens — _exact_stop_accounting subtracts the
    overshoot _collect counted."""
    cfg, params = tiny
    eng = InferenceEngine(
        cfg, params,
        engine_config=EngineConfig(
            seq_buckets=(16,), batch_buckets=(1,), max_new_tokens=32,
            stop_check_chunk=16,
        ),
    )
    free = eng.generate_texts(["hello there"])[0]
    if len(free.text) < 4:
        pytest.skip("output too short to split")
    base = eng.stats()["tokens_generated"]
    got = eng.generate_texts(["hello there"], stop=[free.text[1:3]])[0]
    assert eng.stats()["tokens_generated"] - base == got.num_tokens
