"""Chaos testing: the consensus protocol under an adversarial backend.

The reference panics on any backend failure (``src/main.rs:85,97,138``);
these tests drive the coordinator's failure-detection layer (timeouts +
bounded retries + degraded verdicts, SURVEY.md §5) through seeded
injected faults and assert it still terminates with an answer.
"""

import asyncio

import pytest

from llm_consensus_tpu.backends import (
    BackendError,
    FakeBackend,
    FaultConfig,
    FaultInjectingBackend,
)
from llm_consensus_tpu.consensus import (
    Coordinator,
    CoordinatorConfig,
    default_panel,
)


def _run(coord, q="What is 2+2?"):
    return asyncio.run(coord.run(q))


def test_fault_config_validation():
    with pytest.raises(ValueError, match="error_rate"):
        FaultConfig(error_rate=1.5)


def test_faults_are_seeded_and_counted():
    async def _probe(seed):
        fb = FaultInjectingBackend(
            FakeBackend(),
            FaultConfig(error_rate=0.5, garbage_rate=0.5, seed=seed),
        )
        outcomes = []
        from llm_consensus_tpu.backends import GenerationRequest

        for _ in range(20):
            try:
                r = await fb.generate_batch([GenerationRequest(prompt="q")])
                outcomes.append(r[0].text)
            except BackendError:
                outcomes.append("<err>")
        return outcomes, fb.stats

    a, sa = asyncio.run(_probe(7))
    b, sb = asyncio.run(_probe(7))
    c, _ = asyncio.run(_probe(8))
    assert a == b  # reproducible per seed
    assert a != c
    assert sa.calls == 20
    assert sa.errors_injected > 0 and sa.garbage_injected > 0


def test_protocol_survives_transient_errors():
    """With retries, injected transient errors never panic the protocol
    — every seed still terminates with an answer (vs the reference's
    expect-panic on any failure)."""
    for seed in range(3):
        backend = FaultInjectingBackend(
            FakeBackend(), FaultConfig(error_rate=0.3, seed=seed)
        )
        coord = Coordinator(
            default_panel(),
            backend,
            CoordinatorConfig(seed=0, retries=4, max_rounds=3),
        )
        result = _run(coord)
        assert isinstance(result.answer, str) and result.answer


def test_protocol_survives_garbage_verdicts():
    """Garbled evaluator output parses as dissent (quirk #4) and the
    round cap still force-terminates — never a crash or a hang."""
    backend = FaultInjectingBackend(
        FakeBackend(), FaultConfig(garbage_rate=0.7, seed=1)
    )
    coord = Coordinator(
        default_panel(),
        backend,
        CoordinatorConfig(seed=0, retries=2, max_rounds=3),
    )
    result = _run(coord)
    assert isinstance(result.answer, str)
    assert result.rounds <= 3


def test_protocol_survives_delays_with_timeout():
    """Injected delays beyond call_timeout are retried, not fatal."""
    backend = FaultInjectingBackend(
        FakeBackend(),
        FaultConfig(delay_rate=0.5, delay_s=0.2, seed=3),
    )
    coord = Coordinator(
        default_panel(),
        backend,
        CoordinatorConfig(
            seed=0, retries=5, max_rounds=2, call_timeout=0.05
        ),
    )
    result = _run(coord)
    assert isinstance(result.answer, str) and result.answer
